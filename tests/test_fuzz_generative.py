"""Generative differential fuzzing of the Tier-1 contract.

Random patterns are assembled from the segment grammar (literals, classes,
repeats, optionals, alternations); every pattern the compiler ACCEPTS must
agree bit-exactly with `re.fullmatch` — match flags and capture spans — on
random and adversarial inputs.  Patterns the compiler rejects are fine (the
contract is soundness, not completeness).
"""

import re

import numpy as np
import pytest

from loongcollector_tpu.ops.device_batch import pack_rows, pick_length_bucket
from loongcollector_tpu.ops.kernels.field_extract import ExtractKernel
from loongcollector_tpu.ops.regex.program import Tier1Unsupported, compile_tier1

CLASSES = [r"\d", r"\w", r"\S", r"[a-c]", r"[^x]", r"[0-9a-f]", r"[^,;]",
           r"[A-Z]", r"."]
LITERALS = ["x", "-", ",", ";", ":", "ab", "GET", "=", "q7"]
QUANTS = ["", "+", "*", "{2}", "{1,3}", "?"]


PREFIX_FAMILIES = [["GET", "GETX"], ["WARN", "WARNING"], ["ab", "abab"],
                   ["x", "xq7"]]


def gen_pattern(rng) -> str:
    parts = []
    n = int(rng.integers(1, 7))
    # round 2: TWO ambiguous pivots may appear (the double-pivot compiler
    # path needs a literal between them — the grammar interleaves literals
    # naturally, and unsound placements must be REJECTED, never miscompiled)
    pivot_budget = 2 if rng.integers(4) == 0 else 1
    pivot_kind = int(rng.integers(3))   # same kind for both: lazy/greedy mix
    for _ in range(n):
        kind = rng.integers(0, 13)
        if kind == 12:
            # literal prefix-pair alternation (round-2 longest-first rule);
            # checked BEFORE the pivot branch so families can precede pivots
            fam = PREFIX_FAMILIES[int(rng.integers(len(PREFIX_FAMILIES)))]
            order = list(fam) if rng.integers(2) else list(reversed(fam))
            parts.append("(" + "|".join(order) + ")")
            continue
        if kind >= 10 and pivot_budget:
            pivot_budget -= 1
            parts.append(["(.*?)", "(.*)", r"(\S*?)"][pivot_kind])
            continue
        if kind < 3:
            parts.append(re.escape(LITERALS[int(rng.integers(len(LITERALS)))]))
        elif kind < 7:
            cls = CLASSES[int(rng.integers(len(CLASSES)))]
            q = QUANTS[int(rng.integers(len(QUANTS)))]
            seg = cls + q
            if rng.integers(2):
                seg = f"({seg})"
            parts.append(seg)
        elif kind < 8:
            # optional group of a small literal+class body
            lit = re.escape(LITERALS[int(rng.integers(len(LITERALS)))])
            cls = CLASSES[int(rng.integers(len(CLASSES)))]
            parts.append(f"(?:{lit}{cls}+)?")
        else:
            # alternation of literals / simple branches
            k = int(rng.integers(2, 4))
            alts = []
            for _ in range(k):
                if rng.integers(2):
                    alts.append(re.escape(
                        LITERALS[int(rng.integers(len(LITERALS)))]))
                else:
                    alts.append(CLASSES[int(rng.integers(len(CLASSES)))] + "+")
            parts.append("(" + "|".join(alts) + ")")
    return "".join(parts)


def gen_inputs(rng, pattern: str, count: int):
    """Random byte strings + mutations of strings that DO match."""
    # includes W/A/R/N/I so WARN/WARNING prefix families get matching inputs
    alphabet = b"abcxq7GET09f,;:=- \tXZWARNI"
    out = []
    for _ in range(count):
        ln = int(rng.integers(0, 24))
        out.append(bytes(alphabet[i]
                         for i in rng.integers(0, len(alphabet), ln)))
    # try to synthesize matching inputs by sampling re's own structure:
    # mutate random strings toward matches via simple hill climbing
    rx = re.compile(pattern.encode())
    for cand in list(out[:40]):
        if rx.fullmatch(cand):
            continue
        for _ in range(4):
            if not cand:
                break
            pos = int(rng.integers(len(cand)))
            cand = cand[:pos] + bytes([alphabet[int(
                rng.integers(len(alphabet)))]]) + cand[pos + 1:]
            if rx.fullmatch(cand):
                out.append(cand)
                break
    return out


def run_differential(pattern: str, lines, rng) -> None:
    prog = compile_tier1(pattern)
    kern = ExtractKernel(prog)
    lines = [l for l in lines if len(l) > 0] or [b"x"]
    arena = np.frombuffer(b"".join(lines), dtype=np.uint8)
    lens = np.array([len(l) for l in lines], dtype=np.int32)
    offs = np.concatenate([[0], np.cumsum(lens[:-1])]).astype(np.int64)
    L = pick_length_bucket(int(lens.max()))
    batch = pack_rows(arena, offs, lens, L)
    ok, coff, clen = kern(batch.rows, batch.lengths)
    ok = np.asarray(ok)[: batch.n_real]
    coff = np.asarray(coff)[: batch.n_real]
    clen = np.asarray(clen)[: batch.n_real]
    rx = re.compile(pattern.encode())
    for i, ln in enumerate(lines):
        m = rx.fullmatch(ln)
        assert bool(ok[i]) == (m is not None), (
            f"pattern={pattern!r} input={ln!r} kernel={bool(ok[i])} "
            f"re={m is not None}")
        if m:
            for g in range(rx.groups):
                s, e = m.span(g + 1)
                if s < 0:
                    assert clen[i, g] == -1, (pattern, ln, g)
                else:
                    assert (coff[i, g], clen[i, g]) == (s, e - s), (
                        f"pattern={pattern!r} input={ln!r} group={g} "
                        f"kernel=({coff[i,g]},{clen[i,g]}) re=({s},{e-s})")


@pytest.mark.parametrize("seed", range(8))
def test_generative_differential(seed):
    rng = np.random.default_rng(1000 + seed)
    accepted = 0
    attempts = 0
    while accepted < 12 and attempts < 200:
        attempts += 1
        pattern = gen_pattern(rng)
        try:
            compile_tier1(pattern)
        except Tier1Unsupported:
            continue
        except re.error:
            continue
        accepted += 1
        lines = gen_inputs(rng, pattern, 120)
        run_differential(pattern, lines, rng)
    assert accepted >= 6, f"grammar generated too few compilable patterns " \
                          f"({accepted}/{attempts})"


PIVOT_FORMS = ["(.*?)", "(.*)", r"(\S*?)", r"([^,]*)", r"([^;]*?)"]


@pytest.mark.parametrize("seed", range(4))
def test_generative_double_pivot(seed):
    """Targeted double-pivot generation: prefix + pivot + literal + pivot +
    suffix assembled from the grammar pieces; every ACCEPTED program must be
    bit-exact vs re (mismatched pivot kinds usually reject — also fine)."""
    rng = np.random.default_rng(4000 + seed)
    accepted = 0
    attempts = 0
    while accepted < 8 and attempts < 300:
        attempts += 1
        pk = int(rng.integers(len(PIVOT_FORMS)))
        p1 = PIVOT_FORMS[pk]
        p2 = (PIVOT_FORMS[pk] if rng.integers(4)
              else PIVOT_FORMS[int(rng.integers(len(PIVOT_FORMS)))])
        lit = re.escape(LITERALS[int(rng.integers(len(LITERALS)))])
        pre = (re.escape(LITERALS[int(rng.integers(len(LITERALS)))])
               if rng.integers(2)
               else CLASSES[int(rng.integers(len(CLASSES)))] + "+")
        suf = re.escape(LITERALS[int(rng.integers(len(LITERALS)))])
        if rng.integers(2):
            suf += CLASSES[int(rng.integers(len(CLASSES)))] + "+"
        pattern = f"{pre}{p1}{lit}{p2}{suf}"
        try:
            prog = compile_tier1(pattern)
        except (Tier1Unsupported, re.error):
            continue
        if prog.pivot2 is None:
            continue
        accepted += 1
        run_differential(pattern, gen_inputs(rng, pattern, 100), rng)
    assert accepted >= 4, f"too few double-pivot programs ({accepted})"
