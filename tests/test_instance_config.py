"""Instance configs: live flag application without pipeline restarts.

Reference: core/config/watcher/InstanceConfigWatcher.cpp +
InstanceConfigManager.cpp (VERDICT r4 #8).
"""

import json
import os
import time

import pytest

import loongcollector_tpu.monitor.watchdog  # noqa: F401 — defines the
# cpu_usage_limit flag the tests override
from loongcollector_tpu.config.instance_config import (InstanceConfigManager,
                                                       InstanceConfigWatcher)
from loongcollector_tpu.monitor.alarms import AlarmType
from loongcollector_tpu.utils import flags


@pytest.fixture()
def mgr():
    m = InstanceConfigManager()
    yield m
    # restore any flags the test overrode
    from loongcollector_tpu.config.instance_config import InstanceConfigDiff
    d = InstanceConfigDiff()
    d.removed = list(m._configs)
    m.update(d)


def _write(tmp_path, name, body):
    p = tmp_path / f"{name}.json"
    tmp = tmp_path / f".{name}.tmp"
    tmp.write_text(json.dumps(body))
    os.replace(tmp, p)
    # mtime granularity: ensure a subsequent rewrite is seen
    st = p.stat()
    os.utime(p, (st.st_atime, st.st_mtime + 0.01))
    return p


class TestWatcherDiff:
    def test_add_modify_remove(self, tmp_path):
        w = InstanceConfigWatcher()
        w.add_source(str(tmp_path))
        p = _write(tmp_path, "tuning", {"config": {"cpu_usage_limit": 0.5}})
        d = w.check_config_diff()
        assert "tuning" in d.added and d.empty() is False
        assert w.check_config_diff().empty()      # unchanged: no diff
        time.sleep(0.02)
        _write(tmp_path, "tuning", {"config": {"cpu_usage_limit": 0.7}})
        d = w.check_config_diff()
        assert "tuning" in d.modified
        p.unlink()
        d = w.check_config_diff()
        assert d.removed == ["tuning"]


class TestManagerApply:
    def test_apply_and_revert_without_restart(self, tmp_path, mgr):
        default = flags.get_flag("cpu_usage_limit")
        w = InstanceConfigWatcher()
        w.add_source(str(tmp_path))
        p = _write(tmp_path, "lim", {"config": {"cpu_usage_limit": 0.123}})
        mgr.update(w.check_config_diff())
        assert flags.get_flag("cpu_usage_limit") == 0.123
        # removal reverts to the default — no restart anywhere
        p.unlink()
        mgr.update(w.check_config_diff())
        assert flags.get_flag("cpu_usage_limit") == default

    def test_merge_order_and_unknown_flags(self, tmp_path, mgr):
        w = InstanceConfigWatcher()
        w.add_source(str(tmp_path))
        _write(tmp_path, "a_base", {"config": {"cpu_usage_limit": 0.3,
                                               "not_a_real_flag": 1}})
        _write(tmp_path, "b_override", {"cpu_usage_limit": 0.9})
        mgr.update(w.check_config_diff())
        # later file (name order) wins; unknown flags are ignored loudly
        assert flags.get_flag("cpu_usage_limit") == 0.9
        assert mgr.find_config("a_base") == {"cpu_usage_limit": 0.3}

    def test_flag_change_callback_fires(self, tmp_path, mgr):
        seen = []
        flags.on_flag_change("cpu_usage_limit", seen.append)
        w = InstanceConfigWatcher()
        w.add_source(str(tmp_path))
        _write(tmp_path, "cb", {"config": {"cpu_usage_limit": 0.42}})
        mgr.update(w.check_config_diff())
        assert 0.42 in seen


class TestAlarmTaxonomy:
    def test_reference_taxonomy_breadth(self):
        # VERDICT r4 #8: top-30+ reference alarm types, wire-name compatible
        names = {t.value for t in AlarmType}
        assert len(names) >= 60
        for required in ("READ_LOG_DELAY_ALARM", "SKIP_READ_LOG_ALARM",
                         "REGEX_MATCH_ALARM", "PARSE_TIME_FAIL_ALARM",
                         "SEND_DATA_FAIL_ALARM", "DISCARD_DATA_ALARM",
                         "CHECKPOINT_V2_ALARM", "EXACTLY_ONCE_ALARM",
                         "INOTIFY_DIR_NUM_LIMIT_ALARM", "DROP_LOG_ALARM",
                         "SPLIT_LOG_FAIL_ALARM", "LOG_TRUNCATE_ALARM",
                         "SENDING_COSTS_TOO_MUCH_TIME_ALARM",
                         "RELABEL_METRIC_FAIL_ALARM",
                         "HOST_MONITOR_ALARM"):
            assert required in names, required
