"""File input unit tests: reader rollback, rotation, checkpoints.

Mirrors reference core/unittest/reader/ + event_handler coverage.
"""

import os
import time

import pytest

from loongcollector_tpu.input.file.checkpoint import CheckPointManager
from loongcollector_tpu.input.file.file_server import FileServer, _ConfigState
from loongcollector_tpu.input.file.polling import (FileDiscoveryConfig,
                                                   PollingDirFile)
from loongcollector_tpu.input.file.reader import LogFileReader


def _chunk_bytes(group):
    """Chunk bytes of a file-server group — FileServer readers presplit
    into line columns (loongcolumn), so newline-aligned chunks
    reconstruct as line spans + '\\n' each; bare readers keep the
    one-RawEvent shape."""
    cols = group.columns
    if cols is not None and not group._events:
        raw = group.source_buffer.raw
        return b"".join(
            bytes(raw[int(o):int(o) + int(ln)]) + b"\n"
            for o, ln in zip(cols.offsets, cols.lengths))
    return group.events[0].content.to_bytes()


class TestReader:
    def test_rollback_to_last_line(self, tmp_path):
        p = tmp_path / "a.log"
        p.write_bytes(b"complete line\npartial")
        r = LogFileReader(str(p))
        g = r.read()
        assert g.events[0].content.to_bytes() == b"complete line\n"
        assert r.read() is None  # partial tail waits
        with open(p, "ab") as f:
            f.write(b" done\n")
        g2 = r.read()
        assert g2.events[0].content.to_bytes() == b"partial done\n"

    def test_force_flush_ships_partial(self, tmp_path):
        p = tmp_path / "b.log"
        p.write_bytes(b"no newline here")
        r = LogFileReader(str(p))
        assert r.read() is None
        g = r.read(force_flush=True)
        assert g.events[0].content.to_bytes() == b"no newline here"

    def test_truncation_restarts(self, tmp_path):
        p = tmp_path / "c.log"
        p.write_bytes(b"aaaa\nbbbb\n")
        r = LogFileReader(str(p))
        r.read()
        p.write_bytes(b"new\n")   # truncate + rewrite (signature changes)
        g = r.read()
        assert g.events[0].content.to_bytes() == b"new\n"

    def test_checkpoint_roundtrip(self, tmp_path):
        p = tmp_path / "d.log"
        p.write_bytes(b"line1\nline2\n")
        r = LogFileReader(str(p))
        r.read()
        cp = r.checkpoint()
        mgr = CheckPointManager(str(tmp_path / "cp.json"))
        mgr.update(cp)
        mgr.dump()
        mgr2 = CheckPointManager(str(tmp_path / "cp.json"))
        mgr2.load()
        got = mgr2.get(cp.dev, cp.inode)
        assert got.offset == cp.offset
        assert got.signature == cp.signature
        assert mgr2.get_by_path(str(p)).offset == cp.offset


class TestRotation:
    def test_rename_recreate_rotation(self, tmp_path):
        """logrotate pattern: rename + recreate must not lose either file's
        data (review finding regression)."""
        fs = FileServer()
        path = tmp_path / "rot.log"
        path.write_bytes(b"old content\n")
        st = _ConfigState("t", FileDiscoveryConfig([str(path)]),
                          queue_key=1, tail_existing=True)
        fs._configs["t"] = st
        pushed = []

        class FakePQM:
            def is_valid_to_push(self, key):
                return True

            def push_queue(self, key, group):
                pushed.append(_chunk_bytes(group))
                return True

        fs.process_queue_manager = FakePQM()
        fs._round()
        assert pushed == [b"old content\n"]
        # rotate: rename then recreate with new content
        os.rename(path, tmp_path / "rot.log.1")
        with open(tmp_path / "rot.log.1", "ab") as f:
            f.write(b"late write to rotated\n")  # written after rename
        path.write_bytes(b"fresh content\n")
        time.sleep(1.01)  # discovery interval
        fs._round()
        fs._round()
        assert b"fresh content\n" in pushed
        assert b"late write to rotated\n" in pushed


class TestPolling:
    def test_glob_and_excludes(self, tmp_path):
        (tmp_path / "x.log").write_text("1")
        (tmp_path / "y.log").write_text("1")
        (tmp_path / "skip.tmp").write_text("1")
        cfg = FileDiscoveryConfig([str(tmp_path / "*.log")],
                                  exclude_files=["y.*"])
        found = PollingDirFile(cfg).poll()
        assert found == [str(tmp_path / "x.log")]


class TestGBKDecode:
    """GBK transcode on read (reference ReadGBK, LogFileReader.cpp:1807)."""

    def test_gbk_file_transcodes_to_utf8(self, tmp_path):
        text = "时间=2024 级别=错误 消息=磁盘已满\nsecond line ascii\n"
        p = tmp_path / "g.log"
        p.write_bytes(text.encode("gbk"))
        r = LogFileReader(str(p), encoding="gbk")
        g = r.read()
        assert g.events[0].content.to_bytes().decode("utf-8") == text

    def test_partial_multibyte_held_at_chunk_boundary(self, tmp_path):
        """A GBK character split by the chunk boundary must not be mangled:
        the lead byte stays in the file until its trail byte arrives."""
        text = "前缀abc中文内容结尾\n"
        raw = text.encode("gbk")
        p = tmp_path / "h.log"
        # choose a chunk size that lands INSIDE a 2-byte character and has
        # no newline before it (forces the filled-chunk path)
        cut = raw.index("中".encode("gbk")) + 1
        p.write_bytes(raw[:cut])
        r = LogFileReader(str(p), chunk_size=cut, encoding="gbk")
        g1 = r.read()          # filled chunk: ships decodable prefix only
        p.write_bytes(raw)     # rest arrives (same prefix + remainder)
        out = b"" if g1 is None else g1.events[0].content.to_bytes()
        while True:
            g = r.read(force_flush=True)
            if g is None:
                break
            out += g.events[0].content.to_bytes()
        assert out.decode("utf-8") == text

    def test_invalid_bytes_replaced_not_fatal(self, tmp_path):
        p = tmp_path / "i.log"
        p.write_bytes(b"ok \x81\x20 bad\n")   # invalid GBK pair mid-line
        r = LogFileReader(str(p), encoding="gbk")
        g = r.read()
        s = g.events[0].content.to_bytes().decode("utf-8")
        assert "ok " in s and "bad" in s

    def test_source_length_metadata_under_gbk(self, tmp_path):
        """LOG_FILE_LENGTH must be SOURCE bytes (EO ranges + rollback index
        the raw file), not the transcoded UTF-8 length."""
        from loongcollector_tpu.models import EventGroupMetaKey
        text = "中文行\n"
        raw = text.encode("gbk")
        p = tmp_path / "j.log"
        p.write_bytes(raw)
        r = LogFileReader(str(p), encoding="gbk")
        g = r.read()
        assert int(str(g.get_metadata(EventGroupMetaKey.LOG_FILE_LENGTH))) \
            == len(raw)
        assert r.offset == len(raw)
        assert len(g.events[0].content.to_bytes()) == len(text.encode())

    def test_backpressure_rollback_gbk_exact(self, tmp_path):
        """Queue rejection rolls back by source bytes: re-read yields the
        identical content, no mid-character garble, no negative offset."""
        from loongcollector_tpu.input.file.file_server import (FileServer,
                                                               _ConfigState)
        from loongcollector_tpu.input.file.polling import FileDiscoveryConfig
        text = "中文行\n"
        p = tmp_path / "k.log"
        p.write_bytes(text.encode("gbk"))
        fs = FileServer()
        st = _ConfigState("t", FileDiscoveryConfig([str(p)]), queue_key=1,
                          tail_existing=True, encoding="gbk")

        class _RejectOnce:
            def __init__(self):
                self.calls = 0
                self.groups = []
            def is_valid_to_push(self, key):
                return True
            def push_queue(self, key, group):
                self.calls += 1
                if self.calls == 1:
                    return False
                self.groups.append(group)
                return True
        pqm = _RejectOnce()
        fs.process_queue_manager = pqm
        r = st.new_reader(str(p))
        assert r.open()
        st.readers[str(p)] = r
        fs._drain_reader(st, r)          # rejected: rolls back
        assert r.offset == 0
        fs._drain_reader(st, r)          # accepted
        assert pqm.groups
        assert _chunk_bytes(pqm.groups[0]).decode() == text

    def test_invalid_byte_before_newline_never_stalls(self, tmp_path):
        p = tmp_path / "l.log"
        p.write_bytes("好\n".encode("gbk") + b"\x81\n")
        r = LogFileReader(str(p), encoding="gbk")
        out = b""
        for _ in range(4):
            g = r.read()
            if g is None:
                break
            out += g.events[0].content.to_bytes()
        assert not r.has_more(), "reader stalled on the invalid byte"
        assert "好".encode() in out
