"""File input unit tests: reader rollback, rotation, checkpoints.

Mirrors reference core/unittest/reader/ + event_handler coverage.
"""

import os
import time

import pytest

from loongcollector_tpu.input.file.checkpoint import CheckPointManager
from loongcollector_tpu.input.file.file_server import FileServer, _ConfigState
from loongcollector_tpu.input.file.polling import (FileDiscoveryConfig,
                                                   PollingDirFile)
from loongcollector_tpu.input.file.reader import LogFileReader


class TestReader:
    def test_rollback_to_last_line(self, tmp_path):
        p = tmp_path / "a.log"
        p.write_bytes(b"complete line\npartial")
        r = LogFileReader(str(p))
        g = r.read()
        assert g.events[0].content.to_bytes() == b"complete line\n"
        assert r.read() is None  # partial tail waits
        with open(p, "ab") as f:
            f.write(b" done\n")
        g2 = r.read()
        assert g2.events[0].content.to_bytes() == b"partial done\n"

    def test_force_flush_ships_partial(self, tmp_path):
        p = tmp_path / "b.log"
        p.write_bytes(b"no newline here")
        r = LogFileReader(str(p))
        assert r.read() is None
        g = r.read(force_flush=True)
        assert g.events[0].content.to_bytes() == b"no newline here"

    def test_truncation_restarts(self, tmp_path):
        p = tmp_path / "c.log"
        p.write_bytes(b"aaaa\nbbbb\n")
        r = LogFileReader(str(p))
        r.read()
        p.write_bytes(b"new\n")   # truncate + rewrite (signature changes)
        g = r.read()
        assert g.events[0].content.to_bytes() == b"new\n"

    def test_checkpoint_roundtrip(self, tmp_path):
        p = tmp_path / "d.log"
        p.write_bytes(b"line1\nline2\n")
        r = LogFileReader(str(p))
        r.read()
        cp = r.checkpoint()
        mgr = CheckPointManager(str(tmp_path / "cp.json"))
        mgr.update(cp)
        mgr.dump()
        mgr2 = CheckPointManager(str(tmp_path / "cp.json"))
        mgr2.load()
        got = mgr2.get(cp.dev, cp.inode)
        assert got.offset == cp.offset
        assert got.signature == cp.signature
        assert mgr2.get_by_path(str(p)).offset == cp.offset


class TestRotation:
    def test_rename_recreate_rotation(self, tmp_path):
        """logrotate pattern: rename + recreate must not lose either file's
        data (review finding regression)."""
        fs = FileServer()
        path = tmp_path / "rot.log"
        path.write_bytes(b"old content\n")
        st = _ConfigState("t", FileDiscoveryConfig([str(path)]),
                          queue_key=1, tail_existing=True)
        fs._configs["t"] = st
        pushed = []

        class FakePQM:
            def is_valid_to_push(self, key):
                return True

            def push_queue(self, key, group):
                pushed.append(group.events[0].content.to_bytes())
                return True

        fs.process_queue_manager = FakePQM()
        fs._round()
        assert pushed == [b"old content\n"]
        # rotate: rename then recreate with new content
        os.rename(path, tmp_path / "rot.log.1")
        with open(tmp_path / "rot.log.1", "ab") as f:
            f.write(b"late write to rotated\n")  # written after rename
        path.write_bytes(b"fresh content\n")
        time.sleep(1.01)  # discovery interval
        fs._round()
        fs._round()
        assert b"fresh content\n" in pushed
        assert b"late write to rotated\n" in pushed


class TestPolling:
    def test_glob_and_excludes(self, tmp_path):
        (tmp_path / "x.log").write_text("1")
        (tmp_path / "y.log").write_text("1")
        (tmp_path / "skip.tmp").write_text("1")
        cfg = FileDiscoveryConfig([str(tmp_path / "*.log")],
                                  exclude_files=["y.*"])
        found = PollingDirFile(cfg).poll()
        assert found == [str(tmp_path / "x.log")]
