"""raceguard: whole-program thread-role race detection.

Three layers of coverage (ISSUE 16):

  1. the three races this repo actually shipped and later fixed —
     snapshot-vs-registration (PR 3), shed-vs-deliver double-pop (PR 8)
     and emit-under-lock reference escape (PR 5) — reproduced as
     faithful pre-fix fixtures that raceguard MUST catch;
  2. thread-role seeding, one fixture per entry family (worker loop,
     flusher sender, config watcher, timer pump, HTTP handler, profiler
     sampler, signal path, Thread-subclass run);
  3. precision pins: the idioms that must NOT fire (lock-held private
     helpers, Condition(lock) aliasing, pre-start publication,
     GIL-atomic single-op sites, single-instance input loops), plus
     runtime regression tests for the real-tree races this checker
     found and this PR fixed.
"""

import textwrap
import threading
import time

from loongcollector_tpu.analysis import ModuleInfo, Program
from loongcollector_tpu.analysis.raceguard.callgraph import CallGraph
from loongcollector_tpu.analysis.raceguard.checker import (
    CHECK_ATOMICITY, CHECK_GUARDED_BY, CHECK_LOCK_SCOPE, RaceGuardChecker)
from loongcollector_tpu.analysis.raceguard.roles import (
    ROLE_FLUSHER, ROLE_HTTP, ROLE_MAIN, ROLE_PROFILER, ROLE_SIGNAL,
    ROLE_THREAD, ROLE_TIMER, ROLE_WATCHER, ROLE_WORKER, RoleGraph)

FIXTURE_PATH = "loongcollector_tpu/ops/fixture.py"


def scan(src, relpath=FIXTURE_PATH):
    """Run raceguard over inline fixture source; returns findings."""
    checker = RaceGuardChecker()
    mod = ModuleInfo("/fx/" + relpath, relpath, textwrap.dedent(src))
    findings = list(checker.check_module(mod))
    findings += list(checker.finalize(Program("/fx", [mod])))
    return findings


def checks_of(findings):
    return {f.check for f in findings}


def rolegraph(src, relpath=FIXTURE_PATH):
    mod = ModuleInfo("/fx/" + relpath, relpath, textwrap.dedent(src))
    program = Program("/fx", [mod])
    cg = CallGraph(program)
    return RoleGraph(program, cg), cg


# ---------------------------------------------------------------------------
# 1. historical races — the three bugs this repo shipped, pre-fix shape.
# Each fixture is the minimal faithful skeleton of the code as it looked
# BEFORE the fixing PR; raceguard existing then would have caught all
# three at review time.


# PR 3 (self-monitor): pipeline registration wrote the registry dict
# with no lock while the exposition path snapshotted (iterated) it under
# one — a worker registering during a scrape corrupted the iteration.
SNAPSHOT_REGISTRATION = """
    import threading

    class PipelineRegistry:
        def __init__(self):
            self._lock = threading.Lock()
            self._records = {}

        def start(self):
            threading.Thread(target=self._run, name="worker-0").start()

        def _run(self):
            while True:
                self.register("p", object())

        def register(self, name, record):
            self._records[name] = record

        def snapshot(self):
            with self._lock:
                return list(self._records.values())
"""


# PR 8 (flusher shedding): deliver checked the queue head then popped it
# without a lock, while the shed path popped concurrently — the same
# batch could be delivered AND counted as shed.
SHED_VS_DELIVER = """
    import threading

    class DeliveryQueue:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def start(self):
            threading.Thread(target=self._send_loop,
                             name="flusher-sender").start()
            threading.Thread(target=self._shed_loop,
                             name="flusher-shed").start()

        def _send_loop(self):
            while True:
                self.deliver()

        def _shed_loop(self):
            while True:
                self.shed()

        def deliver(self):
            if self._items:
                return self._items.pop(0)
            return None

        def shed(self):
            with self._lock:
                if self._items:
                    return self._items.pop(0)
                return None
"""


# PR 5 (circuit breaker): pending() returned the guarded transition list
# out of the locked region; the sender iterated it lock-free while
# on_result kept appending — the exact emit-under-lock escape the
# breaker rework closed.
BREAKER_EMIT_ESCAPE = """
    import threading

    class Breaker:
        def __init__(self):
            self._lock = threading.Lock()
            self._transitions = []

        def start(self):
            threading.Thread(target=self._send_loop,
                             name="flusher-0").start()

        def _send_loop(self):
            while True:
                self.record(True)
                self.pending()

        def record(self, ok):
            with self._lock:
                self._transitions.append(ok)

        def pending(self):
            with self._lock:
                return self._transitions
"""


class TestHistoricalRaces:
    def test_snapshot_registration_race_is_caught(self):
        findings = scan(SNAPSHOT_REGISTRATION)
        assert CHECK_GUARDED_BY in checks_of(findings)
        hit = [f for f in findings if f.check == CHECK_GUARDED_BY][0]
        assert hit.symbol == "PipelineRegistry._records"
        # anchored at the unlocked registration write, the actual bug
        assert "register" not in hit.message or hit.line
        assert "worker" in hit.message

    def test_snapshot_registration_fixed_shape_is_clean(self):
        fixed = SNAPSHOT_REGISTRATION.replace(
            "            self._records[name] = record",
            "            with self._lock:\n"
            "                self._records[name] = record")
        assert scan(fixed) == []

    def test_shed_vs_deliver_race_is_caught(self):
        findings = scan(SHED_VS_DELIVER)
        assert CHECK_ATOMICITY in checks_of(findings)
        hit = [f for f in findings if f.check == CHECK_ATOMICITY][0]
        assert hit.symbol == "DeliveryQueue._items"
        assert "check-then-act" in hit.message
        # the locked shed path is NOT reported: check and act share one
        # continuous region there
        atom = [f for f in findings if f.check == CHECK_ATOMICITY]
        assert len(atom) == 1

    def test_shed_vs_deliver_fixed_shape_is_clean(self):
        fixed = SHED_VS_DELIVER.replace(
            "        def deliver(self):\n"
            "            if self._items:\n"
            "                return self._items.pop(0)\n"
            "            return None",
            "        def deliver(self):\n"
            "            with self._lock:\n"
            "                if self._items:\n"
            "                    return self._items.pop(0)\n"
            "                return None")
        assert scan(fixed) == []

    def test_breaker_emit_escape_is_caught(self):
        findings = scan(BREAKER_EMIT_ESCAPE)
        assert CHECK_LOCK_SCOPE in checks_of(findings)
        hit = [f for f in findings if f.check == CHECK_LOCK_SCOPE][0]
        assert hit.symbol == "Breaker._transitions"
        assert "copy" in hit.message

    def test_breaker_emit_fixed_shape_is_clean(self):
        fixed = BREAKER_EMIT_ESCAPE.replace(
            "                return self._transitions",
            "                return list(self._transitions)")
        assert scan(fixed) == []


# ---------------------------------------------------------------------------
# 2. thread-role seeding — one entry per family (ISSUE 16 satellite).


ROLE_FAMILIES = """
    import signal
    import threading
    from http.server import BaseHTTPRequestHandler

    class Agent:
        def start(self):
            threading.Thread(target=self._work, name="worker-0").start()
            threading.Thread(target=self._send_batches,
                             name="flusher-sender").start()
            threading.Thread(target=self._watch_config,
                             name="config-watch").start()
            threading.Timer(5.0, self._tick).start()
            threading.Thread(target=self._sample_profiler,
                             name="loongprof").start()
            signal.signal(signal.SIGTERM, self._on_signal)

        def _work(self):
            self._step()

        def _step(self):
            pass

        def _send_batches(self):
            pass

        def _watch_config(self):
            pass

        def _tick(self):
            pass

        def _sample_profiler(self):
            pass

        def _on_signal(self, signum, frame):
            pass

        def untouched(self):
            pass

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            pass

    class Puller(threading.Thread):
        def run(self):
            pass
"""


class TestRoleSeeding:
    def _entries(self):
        rg, cg = rolegraph(ROLE_FAMILIES)
        return rg, cg, {(fi.qualname, role) for fi, role, _ in rg.entries}

    def test_every_entry_family_is_classified(self):
        _rg, _cg, entries = self._entries()
        assert ("Agent._work", ROLE_WORKER) in entries
        assert ("Agent._send_batches", ROLE_FLUSHER) in entries
        assert ("Agent._watch_config", ROLE_WATCHER) in entries
        assert ("Agent._tick", ROLE_TIMER) in entries
        assert ("Agent._sample_profiler", ROLE_PROFILER) in entries
        assert ("Agent._on_signal", ROLE_SIGNAL) in entries
        assert ("Handler.do_GET", ROLE_HTTP) in entries
        assert ("Puller.run", ROLE_THREAD) in entries
        # lifecycle methods seed the main family
        assert ("Agent.start", ROLE_MAIN) in entries

    def test_roles_propagate_along_call_graph(self):
        rg, cg, _ = self._entries()
        step = [fi for fi in cg.functions
                if fi.qualname == "Agent._step"][0]
        assert ROLE_WORKER in rg.roles(step)

    def test_unreached_function_defaults_to_main(self):
        rg, cg, _ = self._entries()
        untouched = [fi for fi in cg.functions
                     if fi.qualname == "Agent.untouched"][0]
        assert rg.effective_roles(untouched.key) == frozenset((ROLE_MAIN,))

    def test_concurrency_judgement(self):
        # multi-instance families race with themselves; singletons don't
        assert RoleGraph.concurrent(frozenset((ROLE_WORKER,)))
        assert RoleGraph.concurrent(frozenset((ROLE_HTTP,)))
        assert RoleGraph.concurrent(frozenset((ROLE_FLUSHER,)))
        assert not RoleGraph.concurrent(frozenset((ROLE_THREAD,)))
        assert not RoleGraph.concurrent(frozenset((ROLE_MAIN,)))
        assert not RoleGraph.concurrent(frozenset())
        # two distinct families always can
        assert RoleGraph.concurrent(frozenset((ROLE_MAIN, ROLE_TIMER)))


# ---------------------------------------------------------------------------
# 3a. precision pins — idioms the checker must stay silent on.  Each of
# these is a real pattern from this tree that an earlier raceguard draft
# flagged; the pin keeps the false-positive fix honest.


class TestPrecisionPins:
    def test_lock_held_private_helper_is_silent(self):
        # disk_buffer/circuit idiom: a public method takes the lock and
        # delegates to a _helper that touches shared state.  Entry-lock
        # propagation must credit the helper's sites with the callers'
        # held locks.
        src = """
            import threading

            class Buf:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._pending = []
                    self._count = 0

                def start(self):
                    threading.Thread(target=self._run,
                                     name="worker-0").start()

                def _run(self):
                    while True:
                        self.add(1)

                def add(self, item):
                    with self._lock:
                        self._append_locked(item)

                def drain(self):
                    with self._lock:
                        out = list(self._pending)
                        self._pending = []
                        self._count = 0
                        return out

                def _append_locked(self, item):
                    self._pending.append(item)
                    self._count += 1
        """
        assert scan(src) == []

    def test_helper_called_unlocked_once_still_fires(self):
        # the same helper reached by even ONE lock-free call site loses
        # the inferred entry lock: intersection over call sites
        src = """
            import threading

            class Buf:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0

                def start(self):
                    threading.Thread(target=self._run,
                                     name="worker-0").start()

                def _run(self):
                    while True:
                        self.add()
                        self._bump()

                def add(self):
                    with self._lock:
                        self._bump()

                def _bump(self):
                    self._count += 1
        """
        assert CHECK_GUARDED_BY in checks_of(scan(src))

    def test_condition_wrapping_the_lock_is_one_lock(self):
        # device_plane idiom: self._freed = threading.Condition(self._lock)
        # — holding either name holds the same underlying mutex
        src = """
            import threading

            class Pool:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._freed = threading.Condition(self._lock)
                    self._free = 0

                def start(self):
                    threading.Thread(target=self._run,
                                     name="worker-0").start()

                def _run(self):
                    while True:
                        self.release()

                def acquire(self):
                    with self._lock:
                        self._free -= 1

                def release(self):
                    with self._freed:
                        self._free += 1
                        self._freed.notify()
        """
        assert scan(src) == []

    def test_prestart_publication_is_silent(self):
        # journal/file_server idiom: state written in start() BEFORE the
        # thread constructor exists only single-threaded — publication,
        # not a race
        src = """
            import threading

            class Loader:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._rows = []

                def start(self):
                    self._rows = ["seed"]
                    threading.Thread(target=self._run,
                                     name="worker-0").start()

                def _run(self):
                    while True:
                        with self._lock:
                            self._rows.append(1)
                            snap = list(self._rows)
        """
        assert scan(src) == []

    def test_poststart_publication_fires(self):
        # ...but the same write AFTER the thread starts races with it
        src = """
            import threading

            class Loader:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._rows = []

                def start(self):
                    threading.Thread(target=self._run,
                                     name="worker-0").start()
                    self._rows = ["seed"]

                def _run(self):
                    while True:
                        with self._lock:
                            self._rows.append(1)
                            snap = list(self._rows)
        """
        assert CHECK_GUARDED_BY in checks_of(scan(src))

    def test_gil_atomic_single_ops_are_silent(self):
        # metrics/extension idiom: single-op dict store/get/pop sites are
        # each one bytecode under the GIL — no lock needed until an
        # iteration or read-modify-write enters the conflict set
        src = """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._entries = {}

                def start(self):
                    threading.Thread(target=self._run,
                                     name="worker-0").start()

                def _run(self):
                    while True:
                        self.put("k", 1)

                def put(self, key, value):
                    self._entries[key] = value

                def get(self, key):
                    return self._entries.get(key)

                def forget(self, key):
                    self._entries.pop(key, None)

                def size(self):
                    with self._lock:
                        return len(self._entries)
        """
        assert scan(src) == []

    def test_iterating_read_turns_single_ops_into_a_race(self):
        # adding one unlocked iteration over the same dict must fire
        src = """
            import threading

            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._entries = {}

                def start(self):
                    threading.Thread(target=self._run,
                                     name="worker-0").start()

                def _run(self):
                    while True:
                        self.put("k", 1)

                def put(self, key, value):
                    self._entries[key] = value

                def dump(self):
                    with self._lock:
                        pass
                    return sorted(self._entries.values())
        """
        assert CHECK_GUARDED_BY in checks_of(scan(src))

    def test_single_instance_input_loop_is_silent(self):
        # one reader loop per input plugin instance: an unlocked += from
        # the single input role cannot interleave with itself
        src = """
            import threading

            class Reader:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._offset = 0

                def start(self):
                    threading.Thread(target=self._read_loop).start()

                def _read_loop(self):
                    while True:
                        self._offset += 1

                def position(self):
                    with self._lock:
                        return self._offset
        """
        assert scan(src, relpath="loongcollector_tpu/input/fixture.py") \
            == []


# ---------------------------------------------------------------------------
# 3b. runtime regressions for the real-tree races raceguard found and
# this PR fixed.  Each test exercises the FIXED code under contention.


class TestFixedRacesRuntime:
    def test_kafka_corr_ids_unique_under_contention(self):
        # flusher/kafka_client.py: _corr += 1 from sender + main raced;
        # duplicate correlation ids pair responses with wrong requests.
        # _next_corr() must hand out distinct ids under contention.
        from loongcollector_tpu.flusher.kafka_client import KafkaClient
        client = KafkaClient(["broker:9092"])
        out = []
        barrier = threading.Barrier(8)

        def grab():
            barrier.wait()
            ids = [client._next_corr() for _ in range(500)]
            out.append(ids)

        threads = [threading.Thread(target=grab) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        got = [i for ids in out for i in ids]
        assert len(set(got)) == len(got) == 4000

    def test_kafka_close_snapshots_connections(self):
        # close() iterated _conns while _connect/_drop mutated it; the
        # fix snapshots the address list under the lock first
        from loongcollector_tpu.flusher.kafka_client import KafkaClient

        class _Sock:
            def __init__(self):
                self.closed = 0

            def close(self):
                self.closed += 1

        client = KafkaClient(["broker:9092"])
        socks = {f"b{i}:9092": _Sock() for i in range(16)}
        client._conns.update(socks)
        errs = []
        barrier = threading.Barrier(2)

        def closer():
            barrier.wait()
            try:
                client.close()
            except Exception as exc:  # noqa: BLE001 — the assertion
                errs.append(exc)

        def dropper():
            barrier.wait()
            for addr in list(socks):
                try:
                    client._drop(addr)
                except Exception as exc:  # noqa: BLE001
                    errs.append(exc)

        t1 = threading.Thread(target=closer)
        t2 = threading.Thread(target=dropper)
        t1.start(), t2.start()
        t1.join(), t2.join()
        assert errs == []
        assert client._conns == {}
        assert all(s.closed >= 1 for s in socks.values())

    def test_profiler_concurrent_stop_is_safe(self):
        # prof/profiler.py: two stops raced between the None-check and
        # the join; the fix claims the thread attr in one atomic swap
        from loongcollector_tpu.prof.profiler import Profiler
        prof = Profiler(hz=50)
        prof.start()
        errs = []
        barrier = threading.Barrier(4)

        def stopper():
            barrier.wait()
            try:
                prof.stop()
            except Exception as exc:  # noqa: BLE001 — the assertion
                errs.append(exc)

        threads = [threading.Thread(target=stopper) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errs == []
        assert prof._thread is None
        prof.stop()     # and a later redundant stop stays a no-op

    def test_timeout_flush_claim_has_single_winner(self, monkeypatch):
        # runner/processor_runner.py: every worker shard compared
        # last_flush against the interval unlocked, so several shards
        # could claim the same interval and double-pump the flush
        # manager.  The fix claims the interval under _flush_claim.
        from loongcollector_tpu.runner import processor_runner as prmod
        from loongcollector_tpu.runner.processor_runner import \
            ProcessorRunner

        flushes = []

        class _FakeManager:
            def flush_timeout_batches(self):
                flushes.append(threading.get_ident())

        class _FakeTuner:
            def maybe_adjust(self):
                pass

        monkeypatch.setattr(prmod.TimeoutFlushManager, "instance",
                            staticmethod(lambda: _FakeManager()))
        monkeypatch.setattr(prmod, "auto_tuner", lambda: _FakeTuner())

        runner = ProcessorRunner.__new__(ProcessorRunner)
        runner.last_flush = 0.0     # interval long expired for everyone
        runner._flush_claim = threading.Lock()

        barrier = threading.Barrier(8)

        def pump():
            barrier.wait()
            runner._pump_timeout_flush()

        threads = [threading.Thread(target=pump) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(flushes) == 1, \
            f"{len(flushes)} shards claimed one flush interval"
        assert runner.last_flush > 0.0
        # and the next interval is claimable again
        runner.last_flush = 0.0
        runner._pump_timeout_flush()
        assert len(flushes) == 2
