"""processor_spl: query-language stages over columnar groups."""

import pytest

from loongcollector_tpu.processor.spl import ProcessorSPL, SPLError, compile_spl

from test_processors import CTX, split_group


def run_spl(script, data):
    g = split_group(data)
    p = ProcessorSPL()
    assert p.init({"Script": script}, CTX), script
    p.process(g)
    return g


class TestSPL:
    def test_parse_where_project(self):
        g = run_spl(
            r"* | parse content with regex '(?P<level>\w+) (?P<msg>.*)'"
            r" | where level = 'ERROR' | project level, msg",
            b"ERROR disk full\nINFO fine\nERROR cpu hot\n")
        events = g.materialize()
        assert len(events) == 2
        assert events[0].get_content(b"msg") == b"disk full"
        assert not events[0].has_content(b"content")

    def test_where_matches_device(self):
        g = run_spl(
            r"* | parse content with regex '(?P<path>\S+) (?P<code>\d+)'"
            r" | where path matches '/api/.*'",
            b"/api/users 200\n/static/x 200\n/api/pay 500\n")
        assert len(g) == 2

    def test_numeric_comparison(self):
        g = run_spl(
            r"* | parse content with regex '(?P<name>\w+)=(?P<ms>\d+)'"
            r" | where ms > 100",
            b"a=250\nb=50\nc=101\n")
        events = g.materialize()
        assert [e.get_content(b"name").to_bytes() for e in events] == [b"a", b"c"]

    def test_extend_concat_and_rename(self):
        g = run_spl(
            r"* | parse content with regex '(?P<h>\w+):(?P<l>\w+)'"
            r" | extend combo = concat(h, '-', l) | rename combo as id"
            r" | project id",
            b"n1:ERROR\nn2:WARN\n")
        events = g.materialize()
        assert events[0].get_content(b"id") == b"n1-ERROR"
        assert events[1].get_content(b"id") == b"n2-WARN"

    def test_limit(self):
        g = run_spl("* | limit 2", b"a\nb\nc\nd\n")
        assert len(g) == 2

    def test_contains(self):
        g = run_spl("* | where content contains 'needle'",
                    b"has needle here\nnothing\nneedle again\n")
        assert len(g) == 2

    def test_unsupported_stage_fails_init(self):
        p = ProcessorSPL()
        assert not p.init({"Script": "* | frobnicate x"}, CTX)

    def test_bad_regex_fails_init(self):
        p = ProcessorSPL()
        assert not p.init({"Script": "* | parse content with regex '('"}, CTX)


class TestSPLReviewFixes:
    def test_pipe_inside_regex_literal(self):
        g = run_spl(
            r"* | parse content with regex '(?P<m>GET|POST) (?P<p>\S+)'"
            r" | where m = 'POST'",
            b"GET /a\nPOST /b\n")
        assert len(g) == 1
        assert g.materialize()[0].get_content(b"p") == b"/b"

    def test_gte_lte_operators(self):
        g = run_spl(
            r"* | parse content with regex '(?P<n>\d+)' | where n >= 100",
            b"99\n100\n101\n")
        assert len(g) == 2
        g2 = run_spl(
            r"* | parse content with regex '(?P<n>\d+)' | where n <= 100",
            b"99\n100\n101\n")
        assert len(g2) == 2

    def test_concat_with_comma_literal(self):
        g = run_spl(
            r"* | parse content with regex '(?P<a>\w+) (?P<b>\w+)'"
            r" | extend x = concat(a, ', ', b) | project x",
            b"hello world\n")
        assert g.materialize()[0].get_content(b"x") == b"hello, world"


class TestStatsSort:
    """Aggregation verbs (round-2 VERDICT #8): stats + sort, both event
    forms (reference SPL engine, ProcessorSPL.cpp:69-80)."""

    def _obj_group(self, rows):
        from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
        sb = SourceBuffer(4096)
        g = PipelineEventGroup(sb)
        for ts, fields in rows:
            ev = g.add_log_event(ts)
            for k, v in fields.items():
                ev.set_content(sb.copy_string(k.encode()),
                               sb.copy_string(v.encode()))
        return g

    def _run(self, script, group):
        from loongcollector_tpu.pipeline.plugin.interface import PluginContext
        from loongcollector_tpu.processor.spl import ProcessorSPL
        p = ProcessorSPL()
        assert p.init({"Script": script}, PluginContext("t"))
        p.process(group)
        return group

    def _rows(self, g):
        out = []
        for ev in g.events:
            out.append({k.to_str(): v.to_bytes() for k, v in ev.contents})
        return out

    def test_stats_count_by(self):
        g = self._obj_group([(1, {"level": "E"}), (2, {"level": "I"}),
                             (3, {"level": "E"})])
        self._run("* | stats count() by level", g)
        rows = {r["level"]: r["count"] for r in self._rows(g)}
        assert rows == {b"E": b"2", b"I": b"1"}

    def test_stats_sum_avg_min_max(self):
        g = self._obj_group([(1, {"lat": "10"}), (2, {"lat": "30"}),
                             (3, {"lat": "20"})])
        self._run("* | stats sum(lat), avg(lat), min(lat), "
                  "max(lat) as peak", g)
        r = self._rows(g)[0]
        assert r["sum_lat"] == b"60"
        assert r["avg_lat"] == b"20"
        assert r["min_lat"] == b"10"
        assert r["peak"] == b"30"

    def test_sort_numeric_desc(self):
        g = self._obj_group([(1, {"lat": "10", "id": "a"}),
                             (2, {"lat": "30", "id": "b"}),
                             (3, {"lat": "20", "id": "c"})])
        self._run("* | sort by -lat", g)
        assert [r["id"] for r in self._rows(g)] == [b"b", b"c", b"a"]

    def test_stats_columnar_path(self):
        """Columnar group: parse → stats runs on span columns and rebuilds
        columnar output."""
        from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
        from loongcollector_tpu.pipeline.plugin.interface import PluginContext
        from loongcollector_tpu.processor.parse_regex import \
            ProcessorParseRegex
        from loongcollector_tpu.processor.split_log_string import \
            ProcessorSplitLogString
        data = b"E 10\nI 20\nE 30\n"
        sb = SourceBuffer(len(data) + 64)
        g = PipelineEventGroup(sb)
        g.add_raw_event(1).set_content(sb.copy_string(data))
        ctx = PluginContext("t")
        sp = ProcessorSplitLogString(); sp.init({}, ctx)
        pr = ProcessorParseRegex()
        pr.init({"Regex": r"(\w+) (\d+)", "Keys": ["level", "lat"]}, ctx)
        sp.process(g); pr.process(g)
        self._run("* | stats count(), sum(lat) by level | sort by level", g)
        cols = g.columns
        raw = g.source_buffer.as_array()
        def col(name, i):
            fo, fl = cols.fields[name]
            return bytes(raw[fo[i]:fo[i] + fl[i]].tobytes())
        assert len(cols) == 2
        assert col("level", 0) == b"E" and col("count", 0) == b"2"
        assert col("sum_lat", 0) == b"40"
        assert col("level", 1) == b"I" and col("sum_lat", 1) == b"20"

    def test_count_field_counts_non_null(self):
        g = self._obj_group([(1, {"lat": "10"}), (2, {"x": "1"}),
                             (3, {"lat": "20"})])
        self._run("* | stats count(lat), count()", g)
        r = self._rows(g)[0]
        assert r["count_lat"] == b"2"
        assert r["count"] == b"3"

    def test_nan_does_not_poison_stats_or_sort(self):
        g = self._obj_group([(1, {"lat": "10", "id": "a"}),
                             (2, {"lat": "nan", "id": "b"}),
                             (3, {"lat": "5", "id": "c"})])
        self._run("* | sort by lat", g)
        # nan falls back to bytewise ordering for the whole column —
        # deterministic, never arbitrary
        ids = [r["id"] for r in self._rows(g)]
        assert ids == [b"a", b"c", b"b"]  # b"10" < b"5" < b"nan"
        g2 = self._obj_group([(1, {"lat": "10"}), (2, {"lat": "nan"})])
        self._run("* | stats max(lat)", g2)
        assert self._rows(g2)[0]["max_lat"] == b"10"


class TestFunctionLibrary:
    """Round-3 SPL depth: nested function calls in extend."""

    def _run(self, script, rows):
        from loongcollector_tpu.processor.spl import ProcessorSPL
        from loongcollector_tpu.pipeline.plugin.interface import \
            PluginContext
        p = ProcessorSPL()
        assert p.init({"Script": script}, PluginContext("t")), script
        g = _mk_group(rows)
        p.process(g)
        return [{k.to_str(): v.to_bytes() for k, v in ev.contents}
                for ev in g.events]

    def test_string_functions(self):
        rows = self._run(
            "* | extend u = upper(name) | extend s = substring(name, 1, 2)"
            " | extend r = replace(name, 'a', 'o')"
            " | extend p = split_part(path, '/', 3)",
            [{"name": "alice", "path": "/api/users/42"}])
        assert rows[0]["u"] == b"ALICE"
        assert rows[0]["s"] == b"li"
        assert rows[0]["r"] == b"olice"
        assert rows[0]["p"] == b"users"

    def test_nested_calls(self):
        rows = self._run(
            "* | extend x = concat(upper(kind), '-', md5(kind))",
            [{"kind": "web"}])
        import hashlib
        assert rows[0]["x"] == (b"WEB-"
                                + hashlib.md5(b"web").hexdigest().encode())

    def test_math_and_round(self):
        rows = self._run(
            "* | extend total = add(a, b) | extend r = round(div(a, b), 2)",
            [{"a": "10", "b": "4"}])
        assert rows[0]["total"] == b"14"
        assert rows[0]["r"] == b"2.5"

    def test_if_conditional(self):
        rows = self._run(
            "* | extend level = if(status >= 500, 'error', 'ok')",
            [{"status": "503"}, {"status": "200"}])
        assert rows[0]["level"] == b"error"
        assert rows[1]["level"] == b"ok"

    def test_json_extract_and_coalesce(self):
        rows = self._run(
            "* | extend city = json_extract(doc, '$.addr.city')"
            " | extend who = coalesce(nick, name)",
            [{"doc": '{"addr": {"city": "hz"}}', "name": "bob",
              "nick": ""}])
        assert rows[0]["city"] == b"hz"
        assert rows[0]["who"] == b"bob"

    def test_from_unixtime(self):
        rows = self._run(
            "* | extend t = from_unixtime(ts, '%Y-%m-%d')",
            [{"ts": "1700000000"}])
        assert rows[0]["t"] == b"2023-11-14"

    def test_unknown_function_fails_compile(self):
        from loongcollector_tpu.processor.spl import ProcessorSPL
        from loongcollector_tpu.pipeline.plugin.interface import \
            PluginContext
        p = ProcessorSPL()
        assert not p.init({"Script": "* | extend x = frobnicate(a)"},
                          PluginContext("t"))


class TestJoin:
    def _table(self, tmp_path):
        f = tmp_path / "lookup.csv"
        f.write_text("uid,team,region\n42,core,eu\n7,infra,us\n")
        return str(f)

    def _run(self, script, rows):
        from loongcollector_tpu.processor.spl import ProcessorSPL
        from loongcollector_tpu.pipeline.plugin.interface import \
            PluginContext
        p = ProcessorSPL()
        assert p.init({"Script": script}, PluginContext("t")), script
        g = _mk_group(rows)
        p.process(g)
        return [{k.to_str(): v.to_bytes() for k, v in ev.contents}
                for ev in g.events]

    def test_inner_join(self, tmp_path):
        path = self._table(tmp_path)
        rows = self._run(
            f"* | join file('{path}') on uid",
            [{"uid": "42", "msg": "a"}, {"uid": "99", "msg": "b"}])
        assert len(rows) == 1
        assert rows[0]["team"] == b"core" and rows[0]["region"] == b"eu"

    def test_left_join_keeps_unmatched(self, tmp_path):
        path = self._table(tmp_path)
        rows = self._run(
            f"* | join type=left file('{path}') on uid",
            [{"uid": "7"}, {"uid": "99"}])
        assert len(rows) == 2
        assert rows[0]["team"] == b"infra"
        assert "team" not in rows[1]

    def test_absent_table_defers_malformed_fails(self, tmp_path):
        from loongcollector_tpu.processor.spl import ProcessorSPL
        from loongcollector_tpu.pipeline.plugin.interface import \
            PluginContext
        # ABSENT table: config valid, events pass through until it ships
        p = ProcessorSPL()
        missing = tmp_path / "later.csv"
        assert p.init(
            {"Script": f"* | join file('{missing}') on uid"},
            PluginContext("t"))
        g = _mk_group([{"uid": "42"}])
        p.process(g)
        assert len(g.events) == 1          # passthrough, not dropped
        # table arrives: next batch joins
        missing.write_text("uid,team\n42,core\n")
        g2 = _mk_group([{"uid": "42"}, {"uid": "9"}])
        p.process(g2)
        rows = [{k.to_str(): v.to_bytes() for k, v in ev.contents}
                for ev in g2.events]
        assert len(rows) == 1 and rows[0]["team"] == b"core"
        # PRESENT but malformed table still fails at config time
        bad = tmp_path / "bad.csv"
        bad.write_text("wrong,header\n1,2\n")
        p2 = ProcessorSPL()
        assert not p2.init(
            {"Script": f"* | join file('{bad}') on uid"},
            PluginContext("t"))


def _mk_group(rows):
    from loongcollector_tpu.models import PipelineEventGroup
    g = PipelineEventGroup()
    sb = g.source_buffer
    for row in rows:
        ev = g.add_log_event(1700000000)
        for k, v in row.items():
            ev.set_content(sb.copy_string(k.encode()),
                           sb.copy_string(v.encode()))
    return g


class TestReviewRegressions:
    def _run(self, script, rows):
        from loongcollector_tpu.processor.spl import ProcessorSPL
        from loongcollector_tpu.pipeline.plugin.interface import \
            PluginContext
        p = ProcessorSPL()
        assert p.init({"Script": script}, PluginContext("t")), script
        g = _mk_group(rows)
        p.process(g)
        return [{k.to_str(): v.to_bytes() for k, v in ev.contents}
                for ev in g.events]

    def test_nested_if(self):
        rows = self._run(
            "* | extend sev = if(code >= 500, 'err',"
            " if(code >= 400, 'warn', 'ok'))",
            [{"code": "503"}, {"code": "404"}, {"code": "200"}])
        assert [r["sev"] for r in rows] == [b"err", b"warn", b"ok"]

    def test_if_inside_concat(self):
        rows = self._run(
            "* | extend m = concat('[', if(n > 1, 'many', 'one'), ']')",
            [{"n": "5"}])
        assert rows[0]["m"] == b"[many]"

    def test_inner_join_on_columnar_group_drops_all(self, tmp_path):
        """Dropped rows must NOT resurrect from stale columns."""
        import numpy as np

        from loongcollector_tpu.models import (ColumnarLogs,
                                               PipelineEventGroup,
                                               SourceBuffer)
        from loongcollector_tpu.pipeline.plugin.interface import \
            PluginContext
        from loongcollector_tpu.processor.spl import ProcessorSPL
        f = tmp_path / "t.csv"
        f.write_text("uid,team\n42,core\n")
        data = b"uid=7\nuid=8\n"
        sb = SourceBuffer(len(data) + 64)
        view = sb.copy_string(data)
        g = PipelineEventGroup(sb)
        offs = np.array([view.offset, view.offset + 6], dtype=np.int64)
        lens = np.array([5, 5], dtype=np.int32)
        cols = ColumnarLogs(offs.astype(np.int32), lens,
                            np.full(2, 1700000000, dtype=np.int64))
        cols.set_field("uid", np.array([view.offset + 4,
                                        view.offset + 10],
                                       dtype=np.int32),
                       np.array([1, 1], dtype=np.int32))
        g.set_columns(cols)
        p = ProcessorSPL()
        assert p.init({"Script": f"* | join file('{f}') on uid"},
                      PluginContext("t"))
        p.process(g)
        assert len(g) == 0, "unmatched rows resurrected from columns"
