"""processor_spl: query-language stages over columnar groups."""

import pytest

from loongcollector_tpu.processor.spl import ProcessorSPL, SPLError, compile_spl

from test_processors import CTX, split_group


def run_spl(script, data):
    g = split_group(data)
    p = ProcessorSPL()
    assert p.init({"Script": script}, CTX), script
    p.process(g)
    return g


class TestSPL:
    def test_parse_where_project(self):
        g = run_spl(
            r"* | parse content with regex '(?P<level>\w+) (?P<msg>.*)'"
            r" | where level = 'ERROR' | project level, msg",
            b"ERROR disk full\nINFO fine\nERROR cpu hot\n")
        events = g.materialize()
        assert len(events) == 2
        assert events[0].get_content(b"msg") == b"disk full"
        assert not events[0].has_content(b"content")

    def test_where_matches_device(self):
        g = run_spl(
            r"* | parse content with regex '(?P<path>\S+) (?P<code>\d+)'"
            r" | where path matches '/api/.*'",
            b"/api/users 200\n/static/x 200\n/api/pay 500\n")
        assert len(g) == 2

    def test_numeric_comparison(self):
        g = run_spl(
            r"* | parse content with regex '(?P<name>\w+)=(?P<ms>\d+)'"
            r" | where ms > 100",
            b"a=250\nb=50\nc=101\n")
        events = g.materialize()
        assert [e.get_content(b"name").to_bytes() for e in events] == [b"a", b"c"]

    def test_extend_concat_and_rename(self):
        g = run_spl(
            r"* | parse content with regex '(?P<h>\w+):(?P<l>\w+)'"
            r" | extend combo = concat(h, '-', l) | rename combo as id"
            r" | project id",
            b"n1:ERROR\nn2:WARN\n")
        events = g.materialize()
        assert events[0].get_content(b"id") == b"n1-ERROR"
        assert events[1].get_content(b"id") == b"n2-WARN"

    def test_limit(self):
        g = run_spl("* | limit 2", b"a\nb\nc\nd\n")
        assert len(g) == 2

    def test_contains(self):
        g = run_spl("* | where content contains 'needle'",
                    b"has needle here\nnothing\nneedle again\n")
        assert len(g) == 2

    def test_unsupported_stage_fails_init(self):
        p = ProcessorSPL()
        assert not p.init({"Script": "* | frobnicate x"}, CTX)

    def test_bad_regex_fails_init(self):
        p = ProcessorSPL()
        assert not p.init({"Script": "* | parse content with regex '('"}, CTX)


class TestSPLReviewFixes:
    def test_pipe_inside_regex_literal(self):
        g = run_spl(
            r"* | parse content with regex '(?P<m>GET|POST) (?P<p>\S+)'"
            r" | where m = 'POST'",
            b"GET /a\nPOST /b\n")
        assert len(g) == 1
        assert g.materialize()[0].get_content(b"p") == b"/b"

    def test_gte_lte_operators(self):
        g = run_spl(
            r"* | parse content with regex '(?P<n>\d+)' | where n >= 100",
            b"99\n100\n101\n")
        assert len(g) == 2
        g2 = run_spl(
            r"* | parse content with regex '(?P<n>\d+)' | where n <= 100",
            b"99\n100\n101\n")
        assert len(g2) == 2

    def test_concat_with_comma_literal(self):
        g = run_spl(
            r"* | parse content with regex '(?P<a>\w+) (?P<b>\w+)'"
            r" | extend x = concat(a, ', ', b) | project x",
            b"hello world\n")
        assert g.materialize()[0].get_content(b"x") == b"hello, world"
