"""Sink breadth (round-2 VERDICT #4): ES bulk, Loki push, ClickHouse
JSONEachRow, OTLP/HTTP, Prometheus remote-write — each verified end-to-end
against a local fake endpoint capturing the wire body — plus the aggregator
stage and the native LZ4/snappy block codecs.
"""

import http.server
import json
import struct
import threading
import urllib.parse

import pytest

from loongcollector_tpu.models import (EventGroupMetaKey, MetricValue,
                                       PipelineEventGroup, SourceBuffer)
from loongcollector_tpu.pipeline.plugin.interface import PluginContext
from loongcollector_tpu.pipeline.plugin.registry import PluginRegistry
from loongcollector_tpu.runner.flusher_runner import FlusherRunner
from loongcollector_tpu.runner.http_sink import HttpSink
from loongcollector_tpu.pipeline.queue.sender_queue import SenderQueueManager


class _Capture(http.server.BaseHTTPRequestHandler):
    requests = []

    def _capture(self):
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        _Capture.requests.append(
            {"path": self.path, "headers": dict(self.headers),
             "body": body, "method": self.command})
        self.send_response(200)
        self.end_headers()
        self.wfile.write(b"{}")

    def do_POST(self):
        self._capture()

    def do_PUT(self):
        self._capture()

    def log_message(self, *a):
        pass


@pytest.fixture
def endpoint():
    _Capture.requests = []
    server = http.server.HTTPServer(("127.0.0.1", 0), _Capture)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_port}", _Capture.requests
    server.shutdown()


def _log_group(rows):
    sb = SourceBuffer(4096)
    g = PipelineEventGroup(sb)
    for ts, fields in rows:
        ev = g.add_log_event(ts)
        for k, v in fields.items():
            ev.set_content(sb.copy_string(k.encode()),
                           sb.copy_string(v.encode()))
    return g


def _metric_group(samples):
    sb = SourceBuffer(1024)
    g = PipelineEventGroup(sb)
    for ts, name, value, tags in samples:
        ev = g.add_metric_event(ts)
        ev.name = name.encode()
        ev.value = MetricValue(value)
        for k, v in tags.items():
            ev.set_tag(k.encode(), v.encode())
    return g


def _drive(flusher_type, config, group):
    """Run a flusher through the REAL sender path: batcher → sender queue →
    FlusherRunner → HttpSink → local endpoint."""
    registry = PluginRegistry.instance()
    registry.load_static_plugins()
    fl = registry.create_flusher(flusher_type)
    assert fl is not None, flusher_type
    sqm = SenderQueueManager()
    fl.queue_key = 9000 + hash(flusher_type) % 1000
    fl.sender_queue = sqm.create_or_reuse_queue(fl.queue_key,
                                                pipeline_name="t")
    assert fl.init(config, PluginContext("t")), flusher_type
    sink = HttpSink(workers=1)
    sink.init()
    runner = FlusherRunner(sqm, sink)
    runner.init()
    try:
        fl.send(group)
        fl.flush_all()
        import time
        deadline = time.monotonic() + 10
        while not _Capture.requests and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        fl.stop(True)
        runner.stop(drain=True, timeout=5)
        sink.stop()
    assert _Capture.requests, f"{flusher_type}: nothing reached the endpoint"
    return _Capture.requests[0]


class TestElasticsearch:
    def test_bulk_wire_body(self, endpoint):
        url, _ = endpoint
        req = _drive("flusher_elasticsearch",
                     {"Addresses": [url], "Index": "logs-%{app}",
                      "Authentication": {"PlainText": {
                          "Username": "u", "Password": "p"}}},
                     _log_group([(1700000001, {"app": "web", "msg": "hi"}),
                                 (1700000002, {"app": "api", "msg": "yo"})]))
        assert req["path"] == "/_bulk"
        assert req["headers"]["Authorization"].startswith("Basic ")
        lines = req["body"].decode().strip().split("\n")
        assert len(lines) == 4
        action0 = json.loads(lines[0])
        assert action0["index"]["_index"] == "logs-web"
        doc0 = json.loads(lines[1])
        assert doc0["msg"] == "hi"
        # ISO-8601, not epoch seconds (ES would read a bare int as millis)
        assert doc0["@timestamp"] == "2023-11-14T22:13:21Z"
        assert json.loads(lines[2])["index"]["_index"] == "logs-api"


class TestLoki:
    def test_push_wire_body(self, endpoint):
        url, _ = endpoint
        req = _drive("flusher_loki",
                     {"URL": url, "TenantID": "t1",
                      "StaticLabels": {"job": "lc"},
                      "DynamicLabels": ["app"]},
                     _log_group([(1700000001, {"app": "web", "msg": "hi"})]))
        assert req["path"] == "/loki/api/v1/push"
        assert req["headers"]["X-Scope-OrgID"] == "t1"
        body = json.loads(req["body"])
        stream = body["streams"][0]
        assert stream["stream"] == {"job": "lc", "app": "web"}
        ts, line = stream["values"][0]
        assert ts == str(1700000001 * 10**9)
        assert json.loads(line)["msg"] == "hi"


class TestClickHouse:
    def test_insert_wire_body(self, endpoint):
        url, _ = endpoint
        req = _drive("flusher_clickhouse",
                     {"Addresses": [url], "Database": "db", "Table": "logs"},
                     _log_group([(1700000001, {"msg": "hi"})]))
        q = urllib.parse.parse_qs(urllib.parse.urlparse(req["path"]).query)
        assert q["query"][0] == "INSERT INTO db.logs FORMAT JSONEachRow"
        row = json.loads(req["body"].decode().strip())
        assert row["msg"] == "hi" and row["_timestamp"] == 1700000001


class TestOTLP:
    def test_logs_wire_body(self, endpoint):
        url, _ = endpoint
        req = _drive("flusher_otlp",
                     {"Endpoint": url,
                      "ResourceAttributes": {"service.name": "svc"}},
                     _log_group([(1700000001,
                                  {"content": "hello", "level": "INFO",
                                   "k": "v"})]))
        assert req["path"] == "/v1/logs"
        body = json.loads(req["body"])
        rl = body["resourceLogs"][0]
        assert rl["resource"]["attributes"][0]["key"] == "service.name"
        rec = rl["scopeLogs"][0]["logRecords"][0]
        assert rec["body"]["stringValue"] == "hello"
        assert rec["severityText"] == "INFO"
        assert rec["timeUnixNano"] == str(1700000001 * 10**9)
        assert {"key": "k", "value": {"stringValue": "v"}} \
            in rec["attributes"]


def _decode_write_request(raw: bytes):
    """Minimal independent PB reader for WriteRequest (test oracle)."""
    series = []

    def read_varint(b, p):
        v = s = 0
        while True:
            x = b[p]; p += 1
            v |= (x & 0x7F) << s
            if not x & 0x80:
                return v, p
            s += 7

    p = 0
    while p < len(raw):
        tag, p = read_varint(raw, p)
        assert tag == (1 << 3) | 2
        ln, p = read_varint(raw, p)
        ts_raw = raw[p:p + ln]; p += ln
        labels, samples = {}, []
        q = 0
        while q < len(ts_raw):
            t, q = read_varint(ts_raw, q)
            fl, wt = t >> 3, t & 7
            if fl == 1:
                ln2, q = read_varint(ts_raw, q)
                lab = ts_raw[q:q + ln2]; q += ln2
                r = 0
                name = val = b""
                while r < len(lab):
                    t2, r = read_varint(lab, r)
                    ln3, r = read_varint(lab, r)
                    if t2 >> 3 == 1:
                        name = lab[r:r + ln3]
                    else:
                        val = lab[r:r + ln3]
                    r += ln3
                labels[name.decode()] = val.decode()
            else:
                ln2, q = read_varint(ts_raw, q)
                sm = ts_raw[q:q + ln2]; q += ln2
                value = struct.unpack("<d", sm[1:9])[0]
                tsv, _ = read_varint(sm, 10)
                samples.append((value, tsv))
        series.append((labels, samples))
    return series


class TestPrometheusRemoteWrite:
    def test_write_request_wire_body(self, endpoint):
        url, _ = endpoint
        req = _drive("flusher_prometheus",
                     {"Endpoint": url + "/api/v1/write"},
                     _metric_group([(1700000001, "http_requests_total",
                                     42.5, {"method": "GET"})]))
        assert req["path"] == "/api/v1/write"
        assert req["headers"]["Content-Encoding"] == "snappy"
        assert req["headers"]["Content-Type"] == "application/x-protobuf"
        assert "X-Prometheus-Remote-Write-Version" in req["headers"]
        from loongcollector_tpu import native
        raw = native.snappy_decompress(req["body"])
        assert raw is not None
        series = _decode_write_request(raw)
        assert len(series) == 1
        labels, samples = series[0]
        assert labels == {"__name__": "http_requests_total",
                          "method": "GET"}
        assert samples == [(42.5, 1700000001 * 1000)]


class TestNativeCodecs:
    def test_lz4_roundtrip(self):
        from loongcollector_tpu import native
        import os
        for data in (b"", b"a", b"hello " * 1000, os.urandom(5000),
                     b"ab" * 50000):
            c = native.lz4_compress(data)
            assert c is not None
            assert native.lz4_decompress(c, len(data)) == data

    def test_snappy_roundtrip(self):
        from loongcollector_tpu import native
        import os
        for data in (b"", b"a", b"hello " * 1000, os.urandom(5000),
                     bytes(range(256)) * 300):
            c = native.snappy_compress(data)
            assert c is not None
            assert native.snappy_decompress(c) == data

    def test_lz4_compressor_in_factory(self):
        from loongcollector_tpu.pipeline.compression import create_compressor
        c = create_compressor("lz4")
        assert c.name == "lz4"
        data = b"payload " * 500
        assert c.decompress(c.compress(data), len(data)) == data

    def test_sls_default_lz4_no_silent_degrade(self):
        """VERDICT weak #5: the SLS default codec must actually be LZ4."""
        from loongcollector_tpu.pipeline.compression import create_compressor
        assert create_compressor("lz4").name == "lz4"


class TestAggregators:
    def _ctx(self):
        return PluginContext("t")

    def test_base_packs_by_count(self):
        reg = PluginRegistry.instance()
        reg.load_static_plugins()
        agg = reg.create_aggregator("aggregator_base")
        agg.init({"MaxLogCount": 3}, self._ctx())
        g = _log_group([(1, {"m": str(i)}) for i in range(7)])
        out = agg.add(g)
        assert [len(o.events) for o in out] == [3, 3]
        rest = agg.flush()
        assert len(rest) == 1 and len(rest[0].events) == 1

    def test_metadata_group_splits_by_field(self):
        reg = PluginRegistry.instance()
        agg = reg.create_aggregator("aggregator_metadata_group")
        agg.init({"GroupMetadataKeys": ["app"]}, self._ctx())
        g = _log_group([(1, {"app": "a", "m": "1"}),
                        (1, {"app": "b", "m": "2"}),
                        (1, {"app": "a", "m": "3"})])
        out = agg.add(g) + agg.flush()
        by_tag = {bytes(o.get_tag(b"app")): len(o.events) for o in out}
        assert by_tag == {b"a": 2, b"b": 1}

    def test_shardhash_sets_source_id(self):
        reg = PluginRegistry.instance()
        agg = reg.create_aggregator("aggregator_shardhash")
        agg.init({"ShardHashKeys": ["host"]}, self._ctx())
        g = _log_group([(1, {"m": "x"})])
        g.set_tag(b"host", b"h1")
        out = agg.add(g)
        assert out == [g]
        sid = g.get_metadata(EventGroupMetaKey.SOURCE_ID)
        assert sid is not None and len(str(sid)) == 32

    def test_pipeline_wires_aggregator(self):
        from loongcollector_tpu.pipeline.pipeline import CollectionPipeline
        p = CollectionPipeline()
        ok = p.init("agg-e2e", {
            "inputs": [],
            "processors": [],
            "aggregators": [{"Type": "aggregator_metadata_group",
                             "GroupMetadataKeys": ["app"]}],
            "flushers": [{"Type": "flusher_blackhole"}],
        })
        assert ok
        bh = p.flushers[0].plugin
        g = _log_group([(1, {"app": "a"}), (1, {"app": "b"})])
        p.send([g])
        p.flush_batch()
        assert bh.total_events == 2
        p.release()


class TestSinkReviewFixes:
    """Round-2 review regressions: label sanitization, tag-keyed buckets."""

    def test_loki_label_names_sanitized(self):
        from loongcollector_tpu.flusher.loki import _label_name
        assert _label_name("app-name") == "app_name"
        assert _label_name("k8s.pod/name") == "k8s_pod_name"
        assert _label_name("0bad") == "_0bad"
        assert _label_name("ok_name:x") == "ok_name:x"

    def test_aggregator_never_merges_differing_tags(self):
        reg = PluginRegistry.instance()
        reg.load_static_plugins()
        agg = reg.create_aggregator("aggregator_base")
        agg.init({"MaxLogCount": 100}, PluginContext("t"))
        g1 = _log_group([(1, {"m": "a"})])
        g1.set_tag(b"host", b"h1")
        g2 = _log_group([(1, {"m": "b"})])
        g2.set_tag(b"host", b"h2")
        agg.add(g1)
        agg.add(g2)
        out = agg.flush()
        hosts = sorted(bytes(o.get_tag(b"host")) for o in out)
        assert hosts == [b"h1", b"h2"]

    def test_aggregator_context_copies_source_metadata(self):
        reg = PluginRegistry.instance()
        agg = reg.create_aggregator("aggregator_context")
        agg.init({}, PluginContext("t"))
        g = _log_group([(1, {"m": "a"})])
        g.set_metadata(EventGroupMetaKey.LOG_FILE_PATH, "/var/log/a")
        agg.add(g)
        out = agg.flush()
        assert str(out[0].get_metadata(EventGroupMetaKey.LOG_FILE_PATH)) \
            == "/var/log/a"


class TestDoris:
    def test_stream_load_wire_body(self, endpoint):
        url, _ = endpoint
        _Capture.requests.clear()
        req = _drive("flusher_doris",
                     {"Addresses": [url], "Database": "db", "Table": "t",
                      "Username": "root", "Password": ""},
                     _log_group([(1700000001, {"msg": "hi"})]))
        assert req["path"] == "/api/db/t/_stream_load"
        assert req["method"] == "PUT"
        assert req["headers"]["format"] == "json"
        assert req["headers"]["label"].startswith("loongcollector_")
        assert req["headers"]["Authorization"].startswith("Basic ")
        row = json.loads(req["body"].decode().strip())
        assert row["msg"] == "hi" and row["_timestamp"] == 1700000001


class TestDorisResponseSemantics:
    def _fl(self):
        reg = PluginRegistry.instance()
        reg.load_static_plugins()
        fl = reg.create_flusher("flusher_doris")
        fl._init_sink({"Addresses": ["http://x"], "Database": "d",
                       "Table": "t"})
        return fl

    def test_status_fail_in_200_body_drops_with_error(self):
        fl = self._fl()
        assert fl.on_send_done(None, 200, b'{"Status": "Fail", '
                               b'"Message": "schema mismatch"}') == "drop"

    def test_success_and_duplicate_label_ok(self):
        fl = self._fl()
        assert fl.on_send_done(None, 200, b'{"Status": "Success"}') == "ok"
        assert fl.on_send_done(
            None, 200, b'{"Status": "Label Already Exists"}') == "ok"

    def test_transport_errors_inherit_retry(self):
        fl = self._fl()
        assert fl.on_send_done(None, 503, b"") == "retry"


class TestRedirectFollow:
    def test_307_followed_preserving_method_and_body(self):
        """Doris FEs answer stream-load with 307 → BE; the sink must follow
        method-preserving redirects."""
        import http.server as hs
        import threading as th
        hits = []

        class BE(hs.BaseHTTPRequestHandler):
            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                hits.append(("be", self.command, self.rfile.read(n)))
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b'{"Status": "Success"}')

            def log_message(self, *a):
                pass

        be = hs.HTTPServer(("127.0.0.1", 0), BE)
        th.Thread(target=be.serve_forever, daemon=True).start()

        class FE(hs.BaseHTTPRequestHandler):
            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                hits.append(("fe", self.command, b""))
                self.send_response(307)
                self.send_header(
                    "Location",
                    f"http://127.0.0.1:{be.server_port}/loaded")
                self.send_header("Content-Length", "0")
                self.end_headers()

            def log_message(self, *a):
                pass

        fe = hs.HTTPServer(("127.0.0.1", 0), FE)
        th.Thread(target=fe.serve_forever, daemon=True).start()
        from loongcollector_tpu.flusher.http import HttpRequest
        from loongcollector_tpu.runner.http_sink import HttpSink
        sink = HttpSink(workers=1)
        sink.init()
        done = []
        try:
            sink.add_request(HttpRequest(
                "PUT", f"http://127.0.0.1:{fe.server_port}/api/d/t/_stream_load",
                {}, b"row-data"), lambda st, b: done.append((st, b)))
            import time as _t
            deadline = _t.monotonic() + 10
            while not done and _t.monotonic() < deadline:
                _t.sleep(0.01)
            assert done, "redirect transfer never completed"
            status, body = done[0]
        finally:
            sink.stop()
            fe.shutdown()
            be.shutdown()
        assert status == 200 and b"Success" in body
        assert [h[0] for h in hits] == ["fe", "be"]
        assert hits[1][1] == "PUT" and hits[1][2] == b"row-data"
