"""Processor tests: split, regex parse (columnar+row), json, delimiter,
timestamp, filter, desensitize, multiline — per-feature + fail-path, after
the reference's unittest style (core/unittest/processor/)."""

import json

import numpy as np
import pytest

from loongcollector_tpu.models import (ColumnarLogs, PipelineEventGroup,
                                       SourceBuffer)
from loongcollector_tpu.pipeline.plugin.interface import PluginContext
from loongcollector_tpu.processor.desensitize import ProcessorDesensitize
from loongcollector_tpu.processor.filter import ProcessorFilter
from loongcollector_tpu.processor.parse_delimiter import ProcessorParseDelimiter
from loongcollector_tpu.processor.parse_json import ProcessorParseJson
from loongcollector_tpu.processor.parse_regex import ProcessorParseRegex
from loongcollector_tpu.processor.parse_timestamp import ProcessorParseTimestamp
from loongcollector_tpu.processor.split_log_string import ProcessorSplitLogString
from loongcollector_tpu.processor.split_multiline import \
    ProcessorSplitMultilineLogString

CTX = PluginContext("test")


def raw_group(data: bytes) -> PipelineEventGroup:
    sb = SourceBuffer()
    view = sb.copy_string(data)
    g = PipelineEventGroup(sb)
    ev = g.add_raw_event(100)
    ev.set_content(view)
    return g


def split_group(data: bytes) -> PipelineEventGroup:
    g = raw_group(data)
    p = ProcessorSplitLogString()
    p.init({}, CTX)
    p.process(g)
    return g


class TestSplitLogString:
    def test_basic_lines(self):
        g = split_group(b"one\ntwo\nthree\n")
        assert len(g) == 3
        events = g.materialize()
        assert events[0].get_content(b"content") == b"one"
        assert events[2].get_content(b"content") == b"three"

    def test_no_trailing_newline(self):
        g = split_group(b"one\ntwo")
        assert len(g) == 2

    def test_empty_interior_lines_kept(self):
        g = split_group(b"a\n\nb\n")
        assert len(g) == 3
        assert g.materialize()[1].get_content(b"content") == b""


class TestParseRegexColumnar:
    def test_parse_fields(self):
        g = split_group(b"1.2.3.4 GET /x\n9.9.9.9 POST /y\nbadline\n")
        p = ProcessorParseRegex()
        p.init({"Regex": r"(\S+) (\S+) (\S+)",
                "Keys": ["ip", "method", "url"]}, CTX)
        p.process(g)
        events = g.materialize()
        assert events[0].get_content(b"ip") == b"1.2.3.4"
        assert events[1].get_content(b"method") == b"POST"
        # failed line keeps raw under rawLog (KeepingSourceWhenParseFail)
        assert events[2].get_content(b"rawLog") == b"badline"
        assert not events[2].has_content(b"ip")

    def test_discard_unmatch(self):
        g = split_group(b"ok 1\nbad\n")
        p = ProcessorParseRegex()
        p.init({"Regex": r"(\w+) (\d+)", "Keys": ["w", "d"],
                "KeepingSourceWhenParseFail": False}, CTX)
        p.process(g)
        events = g.materialize()
        assert not events[1].has_content(b"rawLog")
        assert len(events[1]) == 0

    def test_row_path(self):
        g = PipelineEventGroup()
        sb = g.source_buffer
        ev = g.add_log_event(1)
        ev.set_content(sb.copy_string(b"content"), sb.copy_string(b"k=v"))
        p = ProcessorParseRegex()
        p.init({"Regex": r"([^=]+)=(\S+)", "Keys": ["k", "v"]}, CTX)
        p.process(g)
        assert g.events[0].get_content(b"k") == b"k"
        assert g.events[0].get_content(b"v") == b"v"
        assert not g.events[0].has_content(b"content")


class TestParseJson:
    def test_columnar(self):
        g = split_group(b'{"a": 1, "b": "x"}\nnot json\n')
        p = ProcessorParseJson()
        p.init({}, CTX)
        p.process(g)
        events = g.materialize()
        assert events[0].get_content(b"a") == b"1"
        assert events[0].get_content(b"b") == b"x"
        assert events[1].get_content(b"rawLog") == b"not json"

    def test_nested_value_reserialized(self):
        g = split_group(b'{"o": {"x": 1}}\n')
        p = ProcessorParseJson()
        p.init({}, CTX)
        p.process(g)
        ev = g.materialize()[0]
        assert json.loads(ev.get_content(b"o").to_bytes()) == {"x": 1}


class TestParseDelimiter:
    def test_columnar_tpu_path(self):
        g = split_group(b"a,b,c\n1,2,3\nshort\n")
        p = ProcessorParseDelimiter()
        p.init({"Separator": ",", "Keys": ["f1", "f2", "f3"]}, CTX)
        p.process(g)
        events = g.materialize()
        assert events[0].get_content(b"f2") == b"b"
        assert events[1].get_content(b"f3") == b"3"
        assert events[2].get_content(b"rawLog") == b"short"

    def test_extra_columns_merge_into_last(self):
        g = split_group(b"a,b,c,d,e\n")
        p = ProcessorParseDelimiter()
        p.init({"Separator": ",", "Keys": ["f1", "f2"]}, CTX)
        p.process(g)
        ev = g.materialize()[0]
        assert ev.get_content(b"f1") == b"a"
        assert ev.get_content(b"f2") == b"b,c,d,e"

    def test_quote_mode(self):
        g = PipelineEventGroup()
        sb = g.source_buffer
        ev = g.add_log_event(1)
        ev.set_content(sb.copy_string(b"content"),
                       sb.copy_string(b'"x,y",2,"he said ""hi"""'))
        p = ProcessorParseDelimiter()
        p.init({"Separator": ",", "Quote": '"', "Keys": ["a", "b", "c"]}, CTX)
        p.process(g)
        assert g.events[0].get_content(b"a") == b"x,y"
        assert g.events[0].get_content(b"c") == b'he said "hi"'


class TestParseTimestamp:
    def test_rewrites_event_time(self):
        g = split_group(b"x\ny\n")
        cols = g.columns
        sb = g.source_buffer
        v1 = sb.copy_string(b"2024-01-02 03:04:05")
        cols.set_field("time", np.array([v1.offset, 0]),
                       np.array([v1.length, -1]))
        p = ProcessorParseTimestamp()
        p.init({"SourceKey": "time", "SourceFormat": "%Y-%m-%d %H:%M:%S",
                "SourceTimezone": "GMT+00:00"}, CTX)
        p.process(g)
        import calendar, time as _t
        want = calendar.timegm(_t.strptime("2024-01-02 03:04:05",
                                           "%Y-%m-%d %H:%M:%S"))
        assert g.columns.timestamps[0] == want
        assert g.columns.timestamps[1] == 100  # untouched


class TestFilter:
    def test_include_exclude_columnar(self):
        g = split_group(b"ERROR x\nINFO y\nERROR z\n")
        p = ProcessorParseRegex()
        p.init({"Regex": r"(\w+) (\S+)", "Keys": ["level", "msg"]}, CTX)
        p.process(g)
        f = ProcessorFilter()
        f.init({"Include": {"level": "ERROR"}}, CTX)
        f.process(g)
        assert len(g) == 2
        events = g.materialize()
        assert events[1].get_content(b"msg") == b"z"


class TestDesensitize:
    def test_const_mask(self):
        g = PipelineEventGroup()
        sb = g.source_buffer
        ev = g.add_log_event(1)
        ev.set_content(sb.copy_string(b"content"),
                       sb.copy_string(b"password=hunter2,other=x"))
        p = ProcessorDesensitize()
        p.init({"Regex": r"(password=)([^,]+)", "Method": "const",
                "ReplacingString": "***"}, CTX)
        p.process(g)
        assert g.events[0].get_content(b"content") == b"password=***,other=x"

    def test_columnar_mask(self):
        g = split_group(b"card=1234 end\nno secret\n")
        p = ProcessorDesensitize()
        p.init({"Regex": r"(card=)(\d+)", "Method": "const",
                "ReplacingString": "X"}, CTX)
        p.process(g)
        events = g.materialize()
        assert events[0].get_content(b"content") == b"card=X end"
        assert events[1].get_content(b"content") == b"no secret"


class TestSplitMultiline:
    def test_start_pattern_java_stacktrace(self):
        data = (b"2024-01-01 ERROR boom\n"
                b"  at com.example.Foo(Foo.java:1)\n"
                b"  at com.example.Bar(Bar.java:2)\n"
                b"2024-01-01 INFO ok\n")
        g = split_group(data)
        p = ProcessorSplitMultilineLogString()
        p.init({"Multiline": {"StartPattern": r"\d{4}-\d{2}-\d{2} .*"}}, CTX)
        p.process(g)
        assert len(g) == 2
        events = g.materialize()
        first = events[0].get_content(b"content").to_bytes()
        assert first.startswith(b"2024-01-01 ERROR boom\n  at")
        assert events[1].get_content(b"content") == b"2024-01-01 INFO ok"

    def test_leading_unmatched_single_line(self):
        data = b"orphan\n2024-01-01 start\ncont\n"
        g = split_group(data)
        p = ProcessorSplitMultilineLogString()
        p.init({"Multiline": {"StartPattern": r"\d{4}.*",
                              "UnmatchedContentTreatment": "single_line"}}, CTX)
        p.process(g)
        assert len(g) == 2
        assert g.materialize()[0].get_content(b"content") == b"orphan"

    def test_leading_unmatched_discard(self):
        data = b"orphan\n2024-01-01 start\n"
        g = split_group(data)
        p = ProcessorSplitMultilineLogString()
        p.init({"Multiline": {"StartPattern": r"\d{4}.*",
                              "UnmatchedContentTreatment": "discard"}}, CTX)
        p.process(g)
        assert len(g) == 1
