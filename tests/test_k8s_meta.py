"""service_kubernetes_meta entity/link collection against a fake
apiserver (reference plugins/input/kubernetesmetav2 field contract)."""

import http.server
import json
import threading

import pytest

from loongcollector_tpu.input.k8s_meta import ServiceK8sMeta
from loongcollector_tpu.pipeline.plugin.interface import PluginContext


_OBJECTS = {
    "/api/v1/pods": [
        {"metadata": {"name": "web-abc", "namespace": "prod",
                      "labels": {"app": "web"},
                      "creationTimestamp": "2026-01-01T00:00:00Z",
                      "ownerReferences": [
                          {"kind": "ReplicaSet", "name": "web-rs"}]},
         "spec": {"nodeName": "n1",
                  "containers": [{"name": "app", "image": "web:1",
                                  "resources": {"requests": {"cpu": "100m",
                                                             "memory": "64Mi"},
                                                "limits": {"cpu": "1"}}}],
                  "volumes": [{"name": "data",
                               "persistentVolumeClaim":
                                   {"claimName": "data-pvc"}}]},
         "status": {"phase": "Running", "podIP": "10.0.0.5"}},
    ],
    "/api/v1/nodes": [
        {"metadata": {"name": "n1"},
         "status": {"addresses": [{"type": "InternalIP",
                                   "address": "192.168.1.10"}],
                    "nodeInfo": {"osImage": "linux",
                                 "kubeletVersion": "v1.29"}}},
    ],
    "/api/v1/services": [
        {"metadata": {"name": "web-svc", "namespace": "prod"},
         "spec": {"selector": {"app": "web"}, "clusterIP": "10.96.0.1",
                  "type": "ClusterIP"}},
    ],
    "/apis/apps/v1/replicasets": [
        {"metadata": {"name": "web-rs", "namespace": "prod",
                      "ownerReferences": [{"kind": "Deployment",
                                           "name": "web"}]},
         "spec": {"replicas": 2}, "status": {"readyReplicas": 2}},
    ],
    "/apis/apps/v1/deployments": [
        {"metadata": {"name": "web", "namespace": "prod"},
         "spec": {"replicas": 2}, "status": {"readyReplicas": 2}},
    ],
}


class _Api(http.server.BaseHTTPRequestHandler):
    objects = {}

    def do_GET(self):
        path = self.path.split("?")[0]
        items = self.objects.get(path)
        if items is None:
            self.send_response(404)
            self.end_headers()
            return
        data = json.dumps({"items": items}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):
        pass


@pytest.fixture
def apiserver():
    _Api.objects = dict(_OBJECTS)
    srv = http.server.HTTPServer(("127.0.0.1", 0), _Api)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_port
    srv.shutdown()


def _mk(port, extra=None):
    cfg = {"Pod": True, "Node": True, "Service": True, "ReplicaSet": True,
           "Deployment": True, "Container": True, "Interval": 60,
           "ClusterID": "c1", "EnableLabels": True,
           "Endpoint": {"Scheme": "http", "Host": "127.0.0.1",
                        "Port": port, "Token": "t"}}
    cfg.update(extra or {})
    inp = ServiceK8sMeta()
    assert inp.init(cfg, PluginContext("t"))
    return inp


def _rows(group):
    return [{k.to_str(): v.to_bytes().decode() for k, v in ev.contents}
            for ev in group.events]


class TestEntities:
    def test_entity_fields_and_methods(self, apiserver):
        inp = _mk(apiserver)
        client = inp._client()
        g = inp.collect_once(client)
        rows = _rows(g)
        pods = [r for r in rows if r.get("__entity_type__") == "k8s.pod"]
        assert len(pods) == 1
        pod = pods[0]
        assert pod["__domain__"] == "k8s"
        assert pod["__method__"] == "Add"
        assert pod["__category__"] == "entity"
        assert pod["__keep_alive_seconds__"] == "120"
        assert pod["status"] == "Running"
        assert pod["instance_ip"] == "10.0.0.5"
        assert json.loads(pod["labels"]) == {"app": "web"}
        assert pod["cluster_id"] == "c1"
        # containers become entities too (Container: true)
        cont = [r for r in rows
                if r.get("__entity_type__") == "k8s.container"]
        assert len(cont) == 1
        assert cont[0]["image"] == "web:1"
        assert cont[0]["cpu_request"] == "100m"
        assert cont[0]["memory_request"] == "64Mi"
        assert cont[0]["cpu_limit"] == "1"
        # node custom fields
        node = next(r for r in rows
                    if r.get("__entity_type__") == "k8s.node")
        assert node["internal_ip"] == "192.168.1.10"
        assert node["kubelet_version"] == "v1.29"
        # second collection: methods become Update
        rows2 = _rows(inp.collect_once(client))
        pod2 = next(r for r in rows2
                    if r.get("__entity_type__") == "k8s.pod")
        assert pod2["__method__"] == "Update"
        assert pod2["__first_observed_time__"] == \
            pod["__first_observed_time__"]

    def test_delete_on_disappearance(self, apiserver):
        inp = _mk(apiserver)
        client = inp._client()
        inp.collect_once(client)
        _Api.objects = {k: ([] if k == "/api/v1/pods" else v)
                        for k, v in _Api.objects.items()}
        rows = _rows(inp.collect_once(client))
        deleted = [r for r in rows if r.get("__method__") == "Delete"]
        # the pod and its container entity disappear
        kinds = {r["__entity_type__"] for r in deleted}
        assert "k8s.pod" in kinds


class TestLinks:
    def test_structural_links(self, apiserver):
        inp = _mk(apiserver, {
            "Node2Pod": "runs", "ReplicaSet2Pod": "manages",
            "Deployment2ReplicaSet": "manages", "Deployment2Pod": "controls",
            "Service2Pod": "selects", "Pod2Container": "contains",
            "Pod2PersistentVolumeClaim": "mounts",
        })
        client = inp._client()
        rows = _rows(inp.collect_once(client))
        links = [r for r in rows if r.get("__category__") == "entity_link"]
        rels = {r["__relation_type__"] for r in links}
        assert {"runs", "manages", "controls", "selects",
                "contains", "mounts"} <= rels
        sel = next(r for r in links if r["__relation_type__"] == "selects")
        assert sel["__src_entity_type__"] == "k8s.service"
        assert sel["__dest_entity_type__"] == "k8s.pod"
        # entity ids are md5(cluster_id + kind + ns + name) — stable
        import hashlib
        assert sel["__dest_entity_id__"] == hashlib.md5(
            b"c1Podprodweb-abc").hexdigest()
