"""Container discovery over the Docker Engine unix-socket API."""

import pytest


class TestDockerDiscoveryUnixSocket:
    def test_list_containers_over_socket(self, tmp_path):
        """DockerDiscovery against a fake Engine API on an AF_UNIX socket."""
        import http.server
        import json as _json
        import socketserver
        import threading

        sock_path = str(tmp_path / "docker.sock")

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                body = _json.dumps([{
                    "Id": "abc123", "Names": ["/web-1"],
                    "Image": "nginx:latest",
                    "Labels": {"io.kubernetes.pod.name": "web-1",
                               "io.kubernetes.pod.namespace": "prod",
                               "io.kubernetes.container.name": "nginx"},
                }]).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        server = socketserver.UnixStreamServer(sock_path, Handler)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            from loongcollector_tpu.container_manager import DockerDiscovery
            disc = DockerDiscovery(sock_path)
            found = disc.list_containers()
            assert len(found) == 1
            info = found[0]
            assert info.name == "web-1"
            assert info.k8s_namespace == "prod"
            assert info.log_path.endswith("abc123-json.log")
        finally:
            server.shutdown()
            server.server_close()

    def test_error_body_returns_empty(self, tmp_path):
        import http.server
        import socketserver
        import threading

        sock_path = str(tmp_path / "docker.sock")

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                body = b'{"message": "daemon restarting"}'
                self.send_response(500)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        server = socketserver.UnixStreamServer(sock_path, Handler)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            from loongcollector_tpu.container_manager import DockerDiscovery
            assert DockerDiscovery(sock_path).list_containers() == []
        finally:
            server.shutdown()
            server.server_close()
