"""Checkpoint v2 / exactly-once range checkpoints (reference
CheckpointManagerV2 + ExactlyOnceQueueManager semantics)."""

from loongcollector_tpu.input.file.checkpoint_v2 import (CheckpointManagerV2,
                                                         ExactlyOnceSender,
                                                         RangeCheckpoint)


class TestCheckpointV2:
    def test_save_commit_roundtrip(self, tmp_path):
        mgr = CheckpointManagerV2(str(tmp_path / "v2.db"))
        cp = RangeCheckpoint(key="p/0", inode=7, file_path="/var/a.log",
                             read_offset=100, read_length=50, sequence_id=1)
        mgr.save(cp)
        assert len(mgr.uncommitted("p/")) == 1
        mgr.commit("p/0", 1)
        assert mgr.uncommitted("p/") == []
        got = mgr.get("p/0")
        assert got.committed and got.read_offset == 100
        mgr.close()

    def test_replay_after_crash(self, tmp_path):
        path = str(tmp_path / "v2.db")
        mgr = CheckpointManagerV2(path)
        sender = ExactlyOnceSender(mgr, "pipe", concurrency=2)
        cp1 = sender.acquire_slot("/a.log", 1, 2, 0, 100)
        cp2 = sender.acquire_slot("/a.log", 1, 2, 100, 100)
        assert sender.acquire_slot("/a.log", 1, 2, 200, 100) is None  # full
        sender.commit_slot(cp1)
        mgr.close()
        # "restart": uncommitted ranges must replay
        mgr2 = CheckpointManagerV2(path)
        sender2 = ExactlyOnceSender(mgr2, "pipe", concurrency=2)
        replays = sender2.pending_replays()
        assert len(replays) == 1
        assert replays[0].read_offset == 100
        mgr2.close()

    def test_gc_committed(self, tmp_path):
        mgr = CheckpointManagerV2(str(tmp_path / "v2.db"))
        cp = RangeCheckpoint(key="x/0", sequence_id=1)
        mgr.save(cp)
        mgr.commit("x/0", 1)
        assert mgr.gc(max_age_s=-1) == 1
        assert mgr.get("x/0") is None
        mgr.close()
