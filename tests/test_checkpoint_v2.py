"""Checkpoint v2 / exactly-once range checkpoints (reference
CheckpointManagerV2 + ExactlyOnceQueueManager semantics)."""

from loongcollector_tpu.input.file.checkpoint_v2 import (CheckpointManagerV2,
                                                         ExactlyOnceSender,
                                                         RangeCheckpoint)


class TestCheckpointV2:
    def test_save_commit_roundtrip(self, tmp_path):
        mgr = CheckpointManagerV2(str(tmp_path / "v2.db"))
        cp = RangeCheckpoint(key="p/0", inode=7, file_path="/var/a.log",
                             read_offset=100, read_length=50, sequence_id=1)
        mgr.save(cp)
        assert len(mgr.uncommitted("p/")) == 1
        mgr.commit("p/0", 1)
        assert mgr.uncommitted("p/") == []
        got = mgr.get("p/0")
        assert got.committed and got.read_offset == 100
        mgr.close()

    def test_replay_after_crash(self, tmp_path):
        path = str(tmp_path / "v2.db")
        mgr = CheckpointManagerV2(path)
        sender = ExactlyOnceSender(mgr, "pipe", concurrency=2)
        cp1 = sender.acquire_slot("/a.log", 1, 2, 0, 100)
        cp2 = sender.acquire_slot("/a.log", 1, 2, 100, 100)
        assert sender.acquire_slot("/a.log", 1, 2, 200, 100) is None  # full
        sender.commit_slot(cp1)
        mgr.close()
        # "restart": uncommitted ranges must replay
        mgr2 = CheckpointManagerV2(path)
        sender2 = ExactlyOnceSender(mgr2, "pipe", concurrency=2)
        replays = sender2.pending_replays()
        assert len(replays) == 1
        assert replays[0].read_offset == 100
        mgr2.close()

    def test_gc_committed(self, tmp_path):
        mgr = CheckpointManagerV2(str(tmp_path / "v2.db"))
        cp = RangeCheckpoint(key="x/0", sequence_id=1)
        mgr.save(cp)
        mgr.commit("x/0", 1)
        assert mgr.gc(max_age_s=-1) == 1
        assert mgr.get("x/0") is None
        mgr.close()


class TestExactlyOnceEndToEnd:
    def test_crash_between_send_and_ack_replays_range(self, tmp_path):
        """Uncommitted range at 'crash' → application replay re-injects the
        exact file range marked IS_REPLAY."""
        import os
        from loongcollector_tpu.input.file import checkpoint_v2 as cv2
        from loongcollector_tpu.models import EventGroupMetaKey

        # isolate the process-wide default manager
        old = cv2._default_manager
        cv2._default_manager = None
        try:
            mgr = cv2.get_default_manager(str(tmp_path / "v2.db"))
            log_path = tmp_path / "eo.log"
            log_path.write_bytes(b"range line A\nrange line B\n")
            sender = cv2.ExactlyOnceSender(mgr, "eopipe:flusher_http/0",
                                           concurrency=2)
            cp = sender.acquire_slot(str(log_path),
                                     0, os.stat(log_path).st_ino, 0, 26)
            assert cp is not None
            # crash: no commit. Simulate the application replay logic.
            from loongcollector_tpu.application import Application
            app = Application.__new__(Application)

            class FakePipe:
                process_queue_key = 42

            class FakeMgr:
                def find_pipeline(self, name):
                    return FakePipe() if name == "eopipe" else None

            pushed = []

            class FakePQM:
                def push_queue(self, key, group):
                    pushed.append((key, group))
                    return True

            app.pipeline_manager = FakeMgr()
            app.process_queue_manager = FakePQM()
            app._eo_pending = mgr.uncommitted()
            app._replay_exactly_once()
            assert app._eo_pending == []
            assert len(pushed) == 1
            key, group = pushed[0]
            assert key == 42
            assert group.events[0].content.to_bytes() == \
                b"range line A\nrange line B\n"
            assert group.get_metadata(EventGroupMetaKey.IS_REPLAY) == "true"
            assert mgr.uncommitted() == []  # consumed
        finally:
            if cv2._default_manager is not None:
                cv2._default_manager.close()
            cv2._default_manager = old
