"""Long-tail input plugins (round-2 VERDICT missing #2, inputs side):
http_server, OTLP receive, journal parse, MQTT subscriber (vs scripted
broker), SNMP v2c (vs scripted UDP agent)."""

import json
import socket
import struct
import threading
import time
import urllib.request

import pytest

from loongcollector_tpu.pipeline.plugin.interface import PluginContext
from loongcollector_tpu.pipeline.plugin.registry import PluginRegistry


class _PQM:
    def __init__(self):
        self.groups = []

    def is_valid_to_push(self, key):
        return True

    def push_queue(self, key, group):
        self.groups.append(group)
        return True


def _mk_input(name, config):
    reg = PluginRegistry.instance()
    reg.load_static_plugins()
    inp = reg.create_input(name)
    assert inp is not None, name
    ctx = PluginContext("t")
    ctx.process_queue_key = 1
    ctx.process_queue_manager = _PQM()
    assert inp.init(config, ctx), (name, config)
    return inp, ctx.process_queue_manager


def _events(pqm):
    out = []
    for g in pqm.groups:
        for ev in g.events:
            out.append({k.to_str(): v.to_bytes() for k, v in ev.contents})
    return out


class TestHTTPServer:
    def _post(self, port, body, headers=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/ingest", data=body,
            headers=headers or {}, method="POST")
        return urllib.request.urlopen(req, timeout=5)

    def test_ndjson_ingest(self):
        inp, pqm = _mk_input("input_http_server",
                             {"Address": "127.0.0.1:0", "Format": "ndjson"})
        assert inp.start()
        try:
            self._post(inp.port, b'{"msg": "a"}\n{"msg": "b"}\n')
        finally:
            inp.stop()
        evs = _events(pqm)
        assert [e["msg"] for e in evs] == [b"a", b"b"]

    def test_gzip_json_array(self):
        import gzip
        inp, pqm = _mk_input("input_http_server",
                             {"Address": "127.0.0.1:0", "Format": "json"})
        assert inp.start()
        try:
            body = gzip.compress(json.dumps(
                [{"k": "1"}, {"k": "2"}]).encode())
            self._post(inp.port, body, {"Content-Encoding": "gzip"})
        finally:
            inp.stop()
        assert [e["k"] for e in _events(pqm)] == [b"1", b"2"]

    def test_bad_body_400(self):
        inp, pqm = _mk_input("input_http_server",
                             {"Address": "127.0.0.1:0", "Format": "json"})
        assert inp.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._post(inp.port, b"not json")
            assert ei.value.code == 400
        finally:
            inp.stop()
        assert not pqm.groups


class TestOTLPReceive:
    def test_otlp_logs_roundtrip_with_flusher(self):
        """The OTLP flusher's wire body feeds the OTLP receiver — the two
        ends of the protocol agree."""
        from loongcollector_tpu.flusher.otlp import FlusherOTLP
        from loongcollector_tpu.models import (PipelineEventGroup,
                                               SourceBuffer)
        sb = SourceBuffer(1024)
        g = PipelineEventGroup(sb)
        ev = g.add_log_event(1700000001)
        ev.set_content(b"content", sb.copy_string(b"hello"))
        ev.set_content(b"level", sb.copy_string(b"WARN"))
        fl = FlusherOTLP()
        fl._init_sink({"Endpoint": "http://x"})
        body, _ = fl.build_payload([g])

        inp, pqm = _mk_input("input_otlp", {"Address": "127.0.0.1:0"})
        assert inp.start()
        try:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{inp.port}/v1/logs", data=body,
                method="POST"), timeout=5)
        finally:
            inp.stop()
        evs = _events(pqm)
        assert len(evs) == 1
        assert evs[0]["content"] == b"hello"
        assert evs[0]["severity"] == b"WARN"


class TestJournalParse:
    def test_parse_journal_entry(self):
        from loongcollector_tpu.input.journal import parse_journal_entry
        line = json.dumps({
            "__REALTIME_TIMESTAMP": "1700000001000000",
            "__CURSOR": "s=abc;i=1",
            "MESSAGE": "unit started",
            "PRIORITY": "6",
            "_SYSTEMD_UNIT": "nginx.service",
            "_HOSTNAME": "h1",
            "_PID": "42",
        }).encode()
        ts, fields, cursor = parse_journal_entry(line)
        assert ts == 1700000001
        assert fields[b"content"] == b"unit started"
        assert fields[b"unit"] == b"nginx.service"
        assert fields[b"priority"] == b"6"
        assert cursor == "s=abc;i=1"

    def test_binary_message_field(self):
        from loongcollector_tpu.input.journal import parse_journal_entry
        line = json.dumps({"MESSAGE": [104, 105],
                           "__REALTIME_TIMESTAMP": "1000000"}).encode()
        ts, fields, _ = parse_journal_entry(line)
        assert fields[b"content"] == b"hi"


class FakeMQTTBroker(threading.Thread):
    """Scripted MQTT 3.1.1 broker: accepts CONNECT/SUBSCRIBE, then
    publishes the scripted messages to the subscriber."""

    def __init__(self, to_publish):
        super().__init__(daemon=True)
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(1)
        self.port = self.sock.getsockname()[1]
        self.to_publish = to_publish
        self.subscribed = []

    def run(self):
        from loongcollector_tpu.input.mqtt import (_read_packet,
                                                   _remaining_len, _mqtt_str)
        try:
            conn, _ = self.sock.accept()
        except OSError:
            return
        pkt = _read_packet(conn)                       # CONNECT
        assert pkt and pkt[0] == 1
        conn.sendall(bytes([2 << 4, 2, 0, 0]))         # CONNACK ok
        pkt = _read_packet(conn)                       # SUBSCRIBE
        assert pkt and pkt[0] == 8
        pid = struct.unpack(">H", pkt[2][:2])[0]
        body = pkt[2][2:]
        pos = 0
        while pos < len(body):
            tlen = struct.unpack(">H", body[pos:pos + 2])[0]
            self.subscribed.append(body[pos + 2:pos + 2 + tlen].decode())
            pos += 2 + tlen + 1
        conn.sendall(bytes([9 << 4, 3]) + struct.pack(">H", pid) + b"\x00")
        for topic, payload, qos in self.to_publish:
            var = _mqtt_str(topic) + (struct.pack(">H", 7) if qos else b"")
            conn.sendall(bytes([(3 << 4) | (qos << 1)])
                         + _remaining_len(len(var) + len(payload))
                         + var + payload)
            if qos:
                ack = _read_packet(conn)               # PUBACK
                assert ack and ack[0] == 4
        time.sleep(0.5)
        conn.close()

    def stop(self):
        try:
            self.sock.close()
        except OSError:
            pass


class TestMQTT:
    def test_subscribe_and_receive(self):
        broker = FakeMQTTBroker([(b"logs/app", b"payload-0", 0),
                                 (b"logs/app", b"payload-1", 1)])
        broker.start()
        inp, pqm = _mk_input("input_mqtt",
                             {"Address": f"127.0.0.1:{broker.port}",
                              "Topics": ["logs/#"]})
        assert inp.start()
        try:
            deadline = time.monotonic() + 10
            while len(pqm.groups) < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
        finally:
            inp.stop()
            broker.stop()
        evs = _events(pqm)
        assert {e["content"] for e in evs} == {b"payload-0", b"payload-1"}
        assert all(e["topic"] == b"logs/app" for e in evs)
        assert broker.subscribed == ["logs/#"]


class FakeSNMPAgent(threading.Thread):
    """Scripted v2c agent answering GetRequest with fixed varbinds."""

    def __init__(self, values):
        super().__init__(daemon=True)
        self.values = values          # oid → int | bytes
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.port = self.sock.getsockname()[1]
        self.running = True

    def run(self):
        from loongcollector_tpu.input.snmp import (_ber_int, _parse_tlv,
                                                   _tlv, decode_oid,
                                                   encode_oid)
        while self.running:
            try:
                data, addr = self.sock.recvfrom(65535)
            except OSError:
                return
            _, msg, _ = _parse_tlv(data, 0)
            pos = 0
            _, _, pos = _parse_tlv(msg, pos)
            _, community, pos = _parse_tlv(msg, pos)
            _, pdu, _ = _parse_tlv(msg, pos)
            _, rid, pos2 = _parse_tlv(pdu, 0)
            binds = []
            _, _, pos2 = _parse_tlv(pdu, pos2)
            _, _, pos2 = _parse_tlv(pdu, pos2)
            _, vbl, _ = _parse_tlv(pdu, pos2)
            p = 0
            while p < len(vbl):
                _, vb, p = _parse_tlv(vbl, p)
                _, oid_body, _ = _parse_tlv(vb, 0)
                oid = decode_oid(oid_body)
                v = self.values.get(oid)
                if isinstance(v, int):
                    venc = _tlv(0x42, v.to_bytes(
                        (v.bit_length() + 7) // 8 or 1, "big"))  # Gauge32
                elif isinstance(v, bytes):
                    venc = _tlv(0x04, v)
                else:
                    venc = _tlv(0x05, b"")
                binds.append(_tlv(0x30, encode_oid(oid) + venc))
            resp_pdu = _tlv(0xA2, _tlv(0x02, rid) + _ber_int(0)
                            + _ber_int(0) + _tlv(0x30, b"".join(binds)))
            out = _tlv(0x30, _ber_int(1) + _tlv(0x04, community) + resp_pdu)
            self.sock.sendto(out, addr)

    def stop(self):
        self.running = False
        try:
            self.sock.close()
        except OSError:
            pass


class TestSNMP:
    def test_ber_oid_roundtrip(self):
        from loongcollector_tpu.input.snmp import decode_oid, encode_oid, \
            _parse_tlv
        for oid in ("1.3.6.1.2.1.1.3.0", "1.3.6.1.4.1.2021.10.1.3.1"):
            tag, body, _ = _parse_tlv(encode_oid(oid), 0)
            assert tag == 0x06 and decode_oid(body) == oid

    def test_poll_against_fake_agent(self):
        agent = FakeSNMPAgent({
            "1.3.6.1.2.1.1.3.0": 123456,            # sysUptime
            "1.3.6.1.2.1.1.5.0": b"host-one",       # sysName
        })
        agent.start()
        inp, pqm = _mk_input("input_snmp", {
            "Targets": [f"127.0.0.1:{agent.port}"],
            "Oids": {"uptime": "1.3.6.1.2.1.1.3.0",
                     "sysname": "1.3.6.1.2.1.1.5.0"},
            "IntervalSecs": 3600,
        })
        try:
            inp.poll_once()
        finally:
            agent.stop()
        assert pqm.groups
        g = pqm.groups[0]
        metrics = [ev for ev in g.events if hasattr(ev, "value")]
        logs = [ev for ev in g.events if hasattr(ev, "contents")]
        assert metrics and float(metrics[0].value.value) == 123456.0
        assert bytes(metrics[0].name) == b"uptime"
        fields = {k.to_str(): v.to_bytes() for k, v in logs[0].contents}
        assert fields["sysname"] == b"host-one"


class TestHostMonitorDepth:
    def test_process_entity_detail(self):
        from loongcollector_tpu.input.host_monitor import ProcessCollector
        out = ProcessCollector(top_n=3).collect()
        names = {n for n, _, _ in out}
        assert {"process_cpu_ticks", "process_rss_bytes",
                "process_threads", "process_start_ticks"} <= names
        # entity tags present on at least one process
        tagged = [t for _, _, t in out if "cmdline" in t or "uid" in t]
        assert tagged

    def test_gpu_collector_gated(self):
        from loongcollector_tpu.input.host_monitor import GPUCollector
        out = GPUCollector().collect()   # no nvidia-smi here: empty, no crash
        assert isinstance(out, list)


class TestHttpSinkReuse:
    def test_connection_reused_across_requests(self):
        import http.server, threading
        conns = []

        class H(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_POST(self):
                conns.append(self.client_address[1])
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                body = b"{}"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        server = http.server.HTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        from loongcollector_tpu.flusher.http import HttpRequest
        from loongcollector_tpu.runner.http_sink import HttpSink
        sink = HttpSink(workers=1)
        sink.init()
        import queue as q
        done = q.Queue()
        try:
            for _ in range(3):
                sink.add_request(
                    HttpRequest("POST",
                                f"http://127.0.0.1:{server.server_port}/x",
                                {}, b"data"),
                    lambda status, body: done.put(status))
            for _ in range(3):
                assert done.get(timeout=10) == 200
        finally:
            sink.stop()
            server.shutdown()
        # all three requests arrived over ONE client connection (same
        # source port) — the worker reused its kept-alive connection
        assert len(set(conns)) == 1, conns


class TestIngestRobustness:
    def test_corrupt_gzip_returns_400(self):
        inp, pqm = _mk_input("input_http_server",
                             {"Address": "127.0.0.1:0", "Format": "json"})
        assert inp.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{inp.port}/i",
                    data=b"\x1f\x8b\x08" + b"\x00" * 10,
                    headers={"Content-Encoding": "gzip"}, method="POST")
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 400
        finally:
            inp.stop()

    def test_json_array_of_scalars_400(self):
        inp, pqm = _mk_input("input_http_server",
                             {"Address": "127.0.0.1:0", "Format": "json"})
        assert inp.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{inp.port}/i", data=b'["a", "b"]',
                    method="POST")
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 400
        finally:
            inp.stop()


class FakeRedis(threading.Thread):
    """Scripted Redis: AUTH + INFO over RESP."""

    INFO = (b"# Server\r\nredis_version:7.2.0\r\nuptime_in_seconds:12345\r\n"
            b"connected_clients:7\r\nused_memory:1048576\r\n"
            b"role:master\r\n")

    def __init__(self, password=""):
        super().__init__(daemon=True)
        self.password = password
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(2)
        self.port = self.sock.getsockname()[1]
        self.authed_cmds = []

    def run(self):
        try:
            conn, _ = self.sock.accept()
        except OSError:
            return
        buf = b""
        authed = not self.password
        pending = []      # RESP array args being collected
        want = 0
        while True:
            try:
                chunk = conn.recv(4096)
            except OSError:
                return
            if not chunk:
                return
            buf += chunk
            while b"\r\n" in buf:
                line, buf = buf.split(b"\r\n", 1)
                if line.startswith(b"*"):
                    want = int(line[1:])
                    pending = []
                    continue
                if line.startswith(b"$"):
                    continue
                pending.append(line)
                if len(pending) < want:
                    continue
                parts, pending, want = pending, [], 0
                cmd = parts[0].upper()
                self.authed_cmds.append(cmd)
                if cmd == b"AUTH":
                    if parts[1].decode() == self.password:
                        authed = True
                        conn.sendall(b"+OK\r\n")
                    else:
                        conn.sendall(b"-ERR invalid password\r\n")
                elif cmd == b"INFO":
                    if not authed:
                        conn.sendall(b"-NOAUTH\r\n")
                    else:
                        conn.sendall(b"$%d\r\n%s\r\n"
                                     % (len(self.INFO), self.INFO))
                else:
                    conn.sendall(b"-ERR unknown\r\n")

    def stop(self):
        try:
            self.sock.close()
        except OSError:
            pass


class TestRedisInput:
    def test_info_metrics(self):
        srv = FakeRedis()
        srv.start()
        inp, pqm = _mk_input("input_redis",
                             {"Targets": [f"127.0.0.1:{srv.port}"],
                              "IntervalSecs": 3600})
        try:
            inp.poll_once()
        finally:
            srv.stop()
        assert pqm.groups
        metrics = {bytes(ev.name): float(ev.value.value)
                   for ev in pqm.groups[0].events}
        assert metrics[b"redis_uptime_in_seconds"] == 12345.0
        assert metrics[b"redis_connected_clients"] == 7.0
        assert b"redis_role" not in metrics        # non-numeric skipped
        assert b"redis_redis_version" not in metrics

    def test_auth(self):
        srv = FakeRedis(password="sekret")
        srv.start()
        inp, pqm = _mk_input("input_redis",
                             {"Targets": [f"127.0.0.1:{srv.port}"],
                              "Password": "sekret", "IntervalSecs": 3600})
        try:
            inp.poll_once()
        finally:
            srv.stop()
        assert pqm.groups
        assert srv.authed_cmds[0] == b"AUTH"

    def test_metric_name_serializes_clean(self):
        """bytes metric names must not render as b'…' reprs on the wire."""
        from loongcollector_tpu.models import (MetricValue,
                                               PipelineEventGroup)
        from loongcollector_tpu.pipeline.serializer.json_serializer import \
            JsonSerializer
        g = PipelineEventGroup()
        ev = g.add_metric_event(1)
        ev.name = b"redis_uptime_in_seconds"
        ev.value = MetricValue(1.0)
        out = JsonSerializer().serialize([g]).decode()
        assert '"__name__": "redis_uptime_in_seconds"' in out
        assert "b'" not in out
