"""Syslog input (UDP/TCP), Prometheus HTTP SD, PB forward decode."""

import http.server
import json
import socket
import threading
import time

import pytest

from loongcollector_tpu.input.syslog import SyslogServer, parse_syslog
from loongcollector_tpu.pipeline.queue.process_queue_manager import \
    ProcessQueueManager


class TestSyslogParse:
    def test_rfc3164(self):
        f = parse_syslog(b"<34>Oct 11 22:14:15 mymachine su[123]: "
                         b"'su root' failed on /dev/pts/8")
        assert f[b"facility"] == b"auth"
        assert f[b"severity"] == b"crit"
        assert f[b"hostname"] == b"mymachine"
        assert f[b"program"] == b"su"
        assert f[b"pid"] == b"123"
        assert f[b"content"] == b"'su root' failed on /dev/pts/8"

    def test_rfc5424(self):
        f = parse_syslog(b"<165>1 2024-01-02T03:04:05.003Z host app 1234 "
                         b"ID47 - An application event")
        assert f[b"facility"] == b"local4"
        assert f[b"severity"] == b"notice"
        assert f[b"program"] == b"app"
        assert f[b"content"] == b"An application event"

    def test_garbage_returns_none(self):
        assert parse_syslog(b"not syslog at all") is None


class TestSyslogServer:
    def _mk(self, protocol):
        pqm = ProcessQueueManager()
        pqm.create_or_reuse_queue(11)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        server = SyslogServer(f"127.0.0.1:{port}", protocol, 11, pqm)
        assert server.start()
        return pqm, server, port

    def test_udp_roundtrip(self):
        pqm, server, port = self._mk("udp")
        try:
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.sendto(b"<13>Oct 11 22:14:15 h prog: hello udp", 
                        ("127.0.0.1", port))
            sock.close()
            deadline = time.monotonic() + 5
            item = None
            while item is None and time.monotonic() < deadline:
                item = pqm.pop_item(timeout=0.2)
            assert item is not None
            _, group = item
            ev = group.events[0]
            assert ev.get_content(b"content") == b"hello udp"
            assert ev.get_content(b"severity") == b"notice"
        finally:
            server.stop()

    def test_tcp_framing(self):
        pqm, server, port = self._mk("tcp")
        try:
            sock = socket.create_connection(("127.0.0.1", port))
            sock.sendall(b"<13>Oct 11 22:14:15 h p: line one\n"
                         b"<13>Oct 11 22:14:15 h p: line two\nnot syslog\n")
            sock.close()
            deadline = time.monotonic() + 5
            events = []
            while len(events) < 3 and time.monotonic() < deadline:
                item = pqm.pop_item(timeout=0.2)
                if item:
                    events.extend(item[1].events)
            assert events[0].get_content(b"content") == b"line one"
            assert events[1].get_content(b"content") == b"line two"
            assert events[2].get_content(b"content") == b"not syslog"
        finally:
            server.stop()


class TestPrometheusHttpSD:
    def test_sd_refresh_and_relabel(self):
        class SD(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                body = json.dumps([
                    {"targets": ["127.0.0.1:9100", "127.0.0.1:9101"],
                     "labels": {"env": "prod"}},
                    {"targets": ["127.0.0.1:9102"],
                     "labels": {"env": "staging"}},
                ]).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        server = http.server.HTTPServer(("127.0.0.1", 0), SD)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            from loongcollector_tpu.input.prometheus.scraper import (
                PrometheusInputRunner, ScrapeJob)
            job = ScrapeJob("sd-job", {
                "HttpSDUrl": f"http://127.0.0.1:{port}/sd",
                "RelabelConfigs": [
                    {"source_labels": ["env"], "regex": "prod",
                     "action": "keep"}],
            }, queue_key=1)
            assert job.sd_url
            job.refresh_sd(PrometheusInputRunner._fetch)
            urls = sorted(t.url for t in job.targets)
            assert urls == ["http://127.0.0.1:9100/metrics",
                            "http://127.0.0.1:9101/metrics"]  # staging dropped
            assert all(t.labels.get("env") == "prod" for t in job.targets)
            # second refresh preserves target objects (scrape state)
            before = {t.url: id(t) for t in job.targets}
            job.refresh_sd(PrometheusInputRunner._fetch)
            after = {t.url: id(t) for t in job.targets}
            assert before == after
        finally:
            server.shutdown()
            server.server_close()


class TestPBForwardDecode:
    def test_loggroup_roundtrip(self):
        from loongcollector_tpu.input.forward import _ForwardHandler
        from loongcollector_tpu.models import PipelineEventGroup
        from loongcollector_tpu.pipeline.serializer.sls_serializer import (
            SLSEventGroupSerializer, parse_loggroup)

        g = PipelineEventGroup()
        sb = g.source_buffer
        g.set_tag(b"host", b"n1")
        ev = g.add_log_event(1700000123)
        ev.set_content(sb.copy_string(b"level"), sb.copy_string(b"warn"))
        ev.set_content(sb.copy_string(b"msg"), sb.copy_string(b"hello pb"))
        wire = SLSEventGroupSerializer().serialize([g])

        g2 = parse_loggroup(wire)
        ev2 = g2.events[0]
        assert ev2.timestamp == 1700000123
        assert ev2.get_content(b"msg") == b"hello pb"
        assert g2.get_tag(b"host") == b"n1"

        # and through the forward handler's decoder
        decoded = _ForwardHandler._decode(wire)
        assert decoded.events[0].get_content(b"level") == b"warn"


class TestReviewRegressions:
    def test_rfc5424_multiple_sd_elements(self):
        f = parse_syslog(b'<165>1 2024-01-02T03:04:05Z host app 123 ID47 '
                         b'[a@1 k="v"][b@2 x="y"] hello')
        assert f[b"content"] == b"hello"

    def test_truncated_pb_falls_to_raw(self):
        from loongcollector_tpu.input.forward import _ForwardHandler
        from loongcollector_tpu.models import PipelineEventGroup
        from loongcollector_tpu.pipeline.serializer.sls_serializer import \
            SLSEventGroupSerializer
        g = PipelineEventGroup()
        sb = g.source_buffer
        ev = g.add_log_event(1)
        ev.set_content(sb.copy_string(b"k"),
                       sb.copy_string(b"a long value that gets cut off"))
        wire = SLSEventGroupSerializer().serialize([g])
        truncated = wire[:-10]
        decoded = _ForwardHandler._decode(truncated)
        # not silently-corrupted structured data: retained as a raw event
        assert decoded.events[0].content is not None

    def test_bad_syslog_address_fails_init(self):
        from loongcollector_tpu.input.syslog import InputSyslog
        from loongcollector_tpu.pipeline.plugin.interface import PluginContext
        p = InputSyslog()
        assert not p.init({"Address": "0.0.0.0"}, PluginContext("t"))

    def test_sd_meta_labels_stripped_and_distinct_labelsets_kept(self):
        from loongcollector_tpu.input.prometheus.scraper import ScrapeJob
        job = ScrapeJob("j", {"HttpSDUrl": "http://x/sd"}, 1)
        import json as _json
        payload = _json.dumps([
            {"targets": ["a:1"], "labels": {"__meta_dc": "dc1", "env": "p"}},
            {"targets": ["a:1"], "labels": {"env": "q"}},
            {"targets": ["a:1"], "labels": {"env": "q"}},  # exact dup
        ]).encode()
        job.refresh_sd(lambda url, t: (payload, True))
        assert len(job.targets) == 2  # two distinct labelsets, dup dropped
        labelsets = sorted(tuple(sorted(t.labels.items()))
                           for t in job.targets)
        assert labelsets == [(("env", "p"),), (("env", "q"),)]
        assert all("__meta_dc" not in t.labels for t in job.targets)
