"""Application-level end-to-end scenarios: the REAL agent process (module
entry point, config watcher, runners, orderly exit) driven over tmp dirs.

The analogue of the reference's e2e scenario suite (test/e2e/test_cases/):
each scenario boots `python -m loongcollector_tpu.application --cpu`,
feeds inputs, and asserts on sink-side evidence — never on queue state.
Subprocess isolation keeps the singletons (FileServer, registries) clean
between scenarios.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _spawn(config_dir, data_dir):
    env = dict(os.environ)
    env.setdefault("LOONG_DISABLE_INOTIFY", "")  # keep inotify active
    return subprocess.Popen(
        [sys.executable, "-m", "loongcollector_tpu.application", "--cpu",
         "--config", str(config_dir), "--data-dir", str(data_dir)],
        cwd=str(REPO), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


from conftest import wait_for


def _wait_for(predicate, timeout=45.0, interval=0.2):
    return wait_for(predicate, timeout=timeout, interval=interval)


def _stop(proc, timeout=20.0):
    proc.send_signal(signal.SIGTERM)
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        pytest.fail("agent did not exit on SIGTERM:\n"
                    + out.decode(errors="replace")[-2000:])
    return out.decode(errors="replace")


@pytest.fixture
def scenario(tmp_path):
    (tmp_path / "conf").mkdir()
    (tmp_path / "data").mkdir()
    (tmp_path / "logs").mkdir()
    (tmp_path / "out").mkdir()
    return tmp_path


class TestTailRestartScenario:
    def test_tail_rotate_restart_no_loss_no_dup(self, scenario):
        """The reference quick-start scenario plus logrotate plus an agent
        restart: every line delivered exactly once across all of it."""
        sink = scenario / "out" / "s.jsonl"
        logf = scenario / "logs" / "app.log"
        (scenario / "conf" / "t.json").write_text(json.dumps({
            "inputs": [{"Type": "input_file",
                        "FilePaths": [str(logf)], "TailExisted": True}],
            "flushers": [{"Type": "flusher_file", "FilePath": str(sink)}],
        }))
        logf.write_text("one\n")
        proc = _spawn(scenario / "conf", scenario / "data")
        try:
            assert _wait_for(lambda: sink.exists()
                             and "one" in sink.read_text())
            with logf.open("a") as f:
                f.write("two\n")
            os.rename(logf, str(logf) + ".1")
            logf.write_text("three\n")
            assert _wait_for(lambda: "three" in sink.read_text())
        finally:
            _stop(proc)
        # restart: append while down, then verify continuity
        with logf.open("a") as f:
            f.write("four\n")
        proc = _spawn(scenario / "conf", scenario / "data")
        try:
            assert _wait_for(lambda: "four" in sink.read_text())
        finally:
            _stop(proc)
        contents = [json.loads(l)["content"]
                    for l in sink.read_text().splitlines()]
        assert sorted(contents) == ["four", "one", "three", "two"], contents


class TestMultilineShutdownScenario:
    def test_open_record_ships_on_sigterm(self, scenario):
        sink = scenario / "out" / "s.jsonl"
        logf = scenario / "logs" / "app.log"
        (scenario / "conf" / "t.json").write_text(json.dumps({
            "inputs": [{"Type": "input_file", "FilePaths": [str(logf)],
                        "TailExisted": True,
                        "Multiline": {"StartPattern": r"\d{4}-.*"}}],
            "flushers": [{"Type": "flusher_file", "FilePath": str(sink)}],
        }))
        logf.write_text("2024-01-02 ERROR boom\n  at Foo\n  at Bar\n")
        proc = _spawn(scenario / "conf", scenario / "data")
        try:
            # the record is OPEN (no closing start line): nothing may ship
            # before the flush timeout; SIGTERM drain must deliver it whole
            time.sleep(3.0)
        finally:
            out = _stop(proc)
        assert sink.exists(), out[-1500:]
        rec = json.loads(sink.read_text().splitlines()[0])
        assert rec["content"] == "2024-01-02 ERROR boom\n  at Foo\n  at Bar"


class TestHTTPIngestScenario:
    def test_ingest_to_file_with_grok(self, scenario):
        import urllib.request
        sink = scenario / "out" / "s.jsonl"
        (scenario / "conf" / "t.json").write_text(json.dumps({
            "inputs": [{"Type": "input_http_server",
                        "Address": "127.0.0.1:18977", "Format": "raw"}],
            "processors": [{"Type": "processor_grok",
                            "Match": "%{LOGLEVEL:lvl} %{GREEDYDATA:msg}"}],
            "flushers": [{"Type": "flusher_file", "FilePath": str(sink)}],
        }))
        proc = _spawn(scenario / "conf", scenario / "data")
        try:
            def _post():
                try:
                    urllib.request.urlopen(urllib.request.Request(
                        "http://127.0.0.1:18977/i",
                        data=b"WARNING disk almost full\n",
                        method="POST"), timeout=2)
                    return True
                except OSError:
                    return False
            assert _wait_for(_post, timeout=30)
            assert _wait_for(lambda: sink.exists() and sink.read_text())
        finally:
            _stop(proc)
        rec = json.loads(sink.read_text().splitlines()[0])
        assert rec["lvl"] == "WARNING"
        assert rec["msg"] == "disk almost full"
