"""loongresident (ISSUE 14): single-dispatch pipeline fusion.

Contracts under test:

1. **Single dispatch** — an all-device-capable 3-stage pipeline (filter →
   parse_regex → filter-on-capture) executes in exactly ONE device
   dispatch per batch slot (``FusedProgramKernel.dispatch_count`` and the
   DevicePlane dispatch ledger both asserted), byte-identical to the
   per-stage path.
2. **Planning** — runs form only over statically-bindable consecutive
   stages; unbindable conditions, consumed sources and terminal stages
   end a run; ``LOONG_FUSED=0`` executes per-stage with identical bytes.
3. **Fault isolation** — an injected ``device_plane.fused_dispatch``
   ERROR demotes exactly that chunk to the per-stage dispatch path
   (counted in ``fused_demotions_total``, alarmed once per program), a
   DELAY just rides the window; a real kernel failure demotes too.
4. **Program cache** — content-addressed in-process LRU + the
   ``fused_cache/`` plan record with geometry recovery (cache hit/miss
   counters asserted).
5. **Round-trip win** — under the LatencyInjectedKernel device model the
   fused program beats the staged path ≥ 2× on a 3-stage pipeline (the
   ISSUE acceptance bound; the bench records the same sweep).
6. **Storm** — 8 seeded fused-dispatch storms with the live conservation
   ledger: residual == 0 at mid/post-storm quiesce, zero loss, per-source
   order, and ``fused_demotions_total`` == injected errors.
"""

import json
import time

import numpy as np
import pytest

from loongcollector_tpu import chaos, models
from loongcollector_tpu.chaos import ChaosPlan, FaultSpec
from loongcollector_tpu.models import (ColumnarLogs, PipelineEventGroup,
                                       SourceBuffer)
from loongcollector_tpu.monitor import ledger
from loongcollector_tpu.monitor.alarms import AlarmManager, AlarmType
from loongcollector_tpu.ops import device_stream
from loongcollector_tpu.ops import fused_pipeline as fp
from loongcollector_tpu.ops.device_plane import (DevicePlane,
                                                 LatencyInjectedKernel)
from loongcollector_tpu.pipeline.fused_chain import plan_fusion
from loongcollector_tpu.pipeline.pipeline import CollectionPipeline
from loongcollector_tpu.pipeline.pipeline_manager import (
    CollectionPipelineManager, ConfigDiff)
from loongcollector_tpu.pipeline.queue.process_queue_manager import \
    ProcessQueueManager
from loongcollector_tpu.pipeline.queue.sender_queue import SenderQueueManager
from loongcollector_tpu.runner.processor_runner import ProcessorRunner

from conftest import wait_for

SEEDS = [3, 7, 11, 19, 23, 31, 43, 59]

RX = r"([a-z]+) (\d+)"


@pytest.fixture(autouse=True)
def _fused_env(monkeypatch):
    """Fusion forced on (CPU backend would auto-disable it), fresh device
    plane / ring / program cache per test."""
    monkeypatch.setenv("LOONG_FUSED", "1")
    prev = models.set_columnar_enabled(True)
    DevicePlane.reset_for_testing()
    device_stream.reset_for_testing()
    fp.reset_for_testing()
    yield
    models.set_columnar_enabled(prev)
    DevicePlane.reset_for_testing()
    device_stream.reset_for_testing()
    fp.reset_for_testing()


def make_group(lines):
    blob = b"".join(lines)
    sb = SourceBuffer(len(blob) + 256)
    g = PipelineEventGroup(sb)
    views = [sb.copy_string(ln) for ln in lines]
    g.set_columns(ColumnarLogs(
        offsets=np.array([v.offset for v in views], np.int32),
        lengths=np.array([len(ln) for ln in lines], np.int32),
        timestamps=np.full(len(lines), 1700000002, np.int64)))
    return g


THREE_STAGE = {
    "inputs": [],
    "processors": [
        {"Type": "processor_filter_native",
         "Include": {"content": r"[a-z]+ \d+"}},
        {"Type": "processor_parse_regex_tpu", "Regex": RX,
         "Keys": ["word", "num"]},
        {"Type": "processor_filter_native", "Include": {"num": r"1\d*"}},
    ],
    "flushers": [{"Type": "flusher_stdout"}],
}

LINES = [b"abc 123", b"nope!", b"zz 15", b"yy 25", b"q 1", b"mixed 9x",
         b"deep 1000"]
#: rows surviving filter1 ∧ parse ∧ filter2(num ~ 1\d*) — the re-derived
#: reference the device path must reproduce byte-for-byte
EXPECT = [(b"abc", b"123"), (b"zz", b"15"), (b"q", b"1"),
          (b"deep", b"1000")]


def build_pipeline(config=THREE_STAGE, name="fused-t"):
    p = CollectionPipeline()
    assert p.init(name, dict(config))
    return p


def snapshot(group):
    """Canonical (content, fields) bytes view of a columnar group."""
    cols = group.columns
    arena = group.source_buffer.as_array()
    n = len(cols)
    content = []
    if not cols.content_consumed:
        for i in range(n):
            o, ln = int(cols.offsets[i]), int(cols.lengths[i])
            content.append(bytes(arena[o:o + ln].tobytes()))
    fields = {}
    for k, (offs, lens) in sorted(cols.fields.items()):
        vals = []
        for i in range(n):
            ln = int(lens[i])
            vals.append(None if ln < 0 else
                        bytes(arena[int(offs[i]):int(offs[i]) + ln]
                              .tobytes()))
        fields[k] = vals
    return {"n": n, "content": content, "fields": fields}


def process_one(pipeline, lines):
    g = make_group(lines)
    fin = pipeline.process_begin([g])
    if fin is not None:
        fin()
    return g


# ---------------------------------------------------------------------------
# 1. single dispatch + byte identity


class TestSingleDispatch:
    def test_three_stage_is_one_dispatch_per_batch_slot(self):
        p = build_pipeline()
        assert [(r.head, r.end) for r in p._fused_runs] == [(0, 3)]
        plane = DevicePlane.reset_for_testing()
        g = process_one(p, LINES)
        # THE acceptance assertion: one device dispatch for the whole
        # 3-stage chain over one batch slot
        assert plane.dispatched_total() == 1
        program = p._fused_runs[0].program()
        assert program.dispatch_count == 1
        got = [(w, n) for w, n in zip(snapshot(g)["fields"]["word"],
                                      snapshot(g)["fields"]["num"])]
        assert got == EXPECT
        # second group: one more slot, one more dispatch
        process_one(p, LINES)
        assert plane.dispatched_total() == 2
        assert program.dispatch_count == 2

    def test_byte_identical_to_per_stage_path(self, monkeypatch):
        p_fused = build_pipeline(name="fused-a")
        g1 = process_one(p_fused, LINES)
        assert p_fused._fused_runs[0].program().dispatch_count == 1
        monkeypatch.setenv("LOONG_FUSED", "0")
        p_staged = build_pipeline(name="fused-b")
        g2 = process_one(p_staged, LINES)
        assert snapshot(g1) == snapshot(g2)

    def test_keep_flags_and_rawlog_identical(self, monkeypatch):
        cfg = dict(THREE_STAGE)
        cfg["processors"] = [
            {"Type": "processor_parse_regex_tpu", "Regex": RX,
             "Keys": ["word", "num"], "KeepingSourceWhenParseFail": True},
            {"Type": "processor_filter_native",
             "Include": {"word": r"[a-z]{2,}"}},
        ]
        p_fused = build_pipeline(cfg, name="fused-keep-a")
        assert len(p_fused._fused_runs) == 1
        g1 = process_one(p_fused, LINES)
        monkeypatch.setenv("LOONG_FUSED", "0")
        p_staged = build_pipeline(cfg, name="fused-keep-b")
        g2 = process_one(p_staged, LINES)
        assert snapshot(g1) == snapshot(g2)

    def test_delimiter_extract_stage_fuses(self, monkeypatch):
        cfg = {
            "inputs": [],
            "processors": [
                {"Type": "processor_filter_native",
                 "Include": {"content": r"[a-z]+,.*"}},
                {"Type": "processor_parse_delimiter_tpu", "Separator": ",",
                 "Keys": ["a", "b", "c"]},
            ],
            "flushers": [{"Type": "flusher_stdout"}],
        }
        lines = [b"ab,cd,ef", b"zz,1,2", b"NOPE,x,y", b"q,w"]
        p = build_pipeline(cfg, name="fused-delim-a")
        assert len(p._fused_runs) == 1
        plane = DevicePlane.reset_for_testing()
        g1 = process_one(p, lines)
        assert plane.dispatched_total() == 1
        monkeypatch.setenv("LOONG_FUSED", "0")
        p2 = build_pipeline(cfg, name="fused-delim-b")
        g2 = process_one(p2, lines)
        assert snapshot(g1) == snapshot(g2)

    def test_grok_classify_stage_fuses(self, monkeypatch):
        cfg = {
            "inputs": [],
            "processors": [
                {"Type": "processor_filter_native",
                 "Include": {"content": r"\w+ .*"}},
                {"Type": "processor_grok",
                 "Match": [r"%{WORD:w} %{INT:n}",
                           r"%{WORD:w} %{WORD:v}"]},
            ],
            "flushers": [{"Type": "flusher_stdout"}],
        }
        lines = [b"abc 123", b"abc def", b"!!", b"zz 9"]
        p = build_pipeline(cfg, name="fused-grok-a")
        if not p._fused_runs:
            pytest.skip("grok set did not device-fuse on this host")
        g1 = process_one(p, lines)
        monkeypatch.setenv("LOONG_FUSED", "0")
        p2 = build_pipeline(cfg, name="fused-grok-b")
        g2 = process_one(p2, lines)
        assert snapshot(g1) == snapshot(g2)

    def test_row_path_group_demotes_to_per_stage(self):
        p = build_pipeline(name="fused-rows")
        sb = SourceBuffer(256)
        g = PipelineEventGroup(sb)
        ev = g.add_log_event(1700000002)
        ev.set_content(b"content", sb.copy_string(b"abc 123"))
        fin = p.process_begin([g])
        if fin is not None:
            fin()
        # per-stage path applied the same semantics on the row group
        evs = g.events
        assert len(evs) == 1
        assert evs[0].get_content(b"word").to_bytes() == b"abc"
        assert evs[0].get_content(b"num").to_bytes() == b"123"


# ---------------------------------------------------------------------------
# 2. planning rules


class TestPlanning:
    def test_unbindable_filter_breaks_the_run(self):
        cfg = dict(THREE_STAGE)
        cfg["processors"] = [
            {"Type": "processor_parse_regex_tpu", "Regex": RX,
             "Keys": ["word", "num"]},
            {"Type": "processor_filter_native",
             "Include": {"not_a_capture": r"\d+"}},
        ]
        p = build_pipeline(cfg, name="plan-a")
        assert p._fused_runs == []

    def test_consumed_source_breaks_the_run(self):
        cfg = dict(THREE_STAGE)
        cfg["processors"] = [
            {"Type": "processor_parse_regex_tpu", "Regex": RX,
             "Keys": ["word", "num"]},
            # content was consumed by the parse: a content condition can
            # no longer bind statically
            {"Type": "processor_filter_native",
             "Include": {"content": r".*"}},
        ]
        p = build_pipeline(cfg, name="plan-b")
        assert p._fused_runs == []

    def test_multiline_spec_is_terminal(self):
        from loongcollector_tpu.pipeline.fused_chain import FusionPlanContext
        from loongcollector_tpu.processor.split_multiline import \
            ProcessorSplitMultilineLogString
        from loongcollector_tpu.pipeline.plugin.interface import PluginContext
        proc = ProcessorSplitMultilineLogString()
        assert proc.init({"Multiline": {
            "StartPattern": r"\[\d+\] .*",
            "ContinuePattern": r"\s+.*"}}, PluginContext())
        ms = proc.fused_stage_spec(FusionPlanContext())
        if ms is None:
            pytest.skip("multiline set did not device-fuse on this host")
        assert ms.spec.terminal

    def test_disabled_fusion_runs_per_stage(self, monkeypatch):
        monkeypatch.setenv("LOONG_FUSED", "0")
        p = build_pipeline(name="plan-c")
        assert p._fused_runs  # planned, not executed
        g = process_one(p, LINES)
        got = [(w, n) for w, n in zip(snapshot(g)["fields"]["word"],
                                      snapshot(g)["fields"]["num"])]
        assert got == EXPECT
        assert p._fused_runs[0].program.__self__._program is None \
            if hasattr(p._fused_runs[0].program, "__self__") else True
        assert fp.stage_fusion_status()["programs"] == []

    def test_tuner_floors_keyed_per_program(self):
        p = build_pipeline(name="plan-d")
        process_one(p, LINES)
        chosen = device_stream.auto_tuner().chosen()
        lanes = chosen.get("lane_buckets", {})
        assert any(k.startswith("fused:") for k in lanes), chosen


# ---------------------------------------------------------------------------
# 3. fault isolation / demotion


def _demotions() -> int:
    return int(fp._metrics().counter("fused_demotions_total").value)


class TestDemotion:
    def test_chaos_error_demotes_one_chunk(self):
        p = build_pipeline(name="dem-a")
        before = _demotions()
        AlarmManager.instance().flush()
        chaos.install(ChaosPlan(5, {
            "device_plane.fused_dispatch": FaultSpec(
                prob=1.0, kinds=(chaos.ACTION_ERROR,), max_faults=1)}))
        try:
            g = process_one(p, LINES)
        finally:
            chaos.uninstall()
        got = [(w, n) for w, n in zip(snapshot(g)["fields"]["word"],
                                      snapshot(g)["fields"]["num"])]
        assert got == EXPECT          # demotion never costs answers
        assert _demotions() == before + 1
        program = p._fused_runs[0].program()
        assert program.demotions == 1
        alarms = AlarmManager.instance().flush()
        assert any(a["alarm_type"] == AlarmType.FUSED_DEMOTED.value
                   for a in alarms)

    def test_chaos_delay_is_not_a_demotion(self):
        p = build_pipeline(name="dem-b")
        before = _demotions()
        chaos.install(ChaosPlan(5, {
            "device_plane.fused_dispatch": FaultSpec(
                prob=1.0, kinds=(chaos.ACTION_DELAY,),
                delay_range=(0.0, 0.002), max_faults=4)}))
        try:
            g = process_one(p, LINES)
        finally:
            chaos.uninstall()
        assert _demotions() == before
        got = [(w, n) for w, n in zip(snapshot(g)["fields"]["word"],
                                      snapshot(g)["fields"]["num"])]
        assert got == EXPECT

    def test_kernel_failure_demotes_chunk(self):
        p = build_pipeline(name="dem-c")
        program = p._fused_runs[0].program()
        before = _demotions()

        calls = {"n": 0}

        def broken(rows, lengths):
            calls["n"] += 1
            raise RuntimeError("mosaic says no")

        program.set_kernel_override(broken)
        try:
            g = process_one(p, LINES)
        finally:
            program.set_kernel_override(None)
        assert calls["n"] == 1
        assert _demotions() == before + 1
        got = [(w, n) for w, n in zip(snapshot(g)["fields"]["word"],
                                      snapshot(g)["fields"]["num"])]
        assert got == EXPECT


# ---------------------------------------------------------------------------
# 4. program cache


class TestProgramCache:
    def _hits(self):
        return int(fp._metrics().counter(
            "fused_program_cache_hit_total").value)

    def test_mem_cache_shares_programs_across_pipelines(self):
        p1 = build_pipeline(name="cache-a")
        program1 = p1._fused_runs[0].program()
        before = self._hits()
        p2 = build_pipeline(name="cache-b")
        program2 = p2._fused_runs[0].program()
        assert program1 is program2
        assert self._hits() == before + 1

    def test_disk_plan_roundtrip(self, tmp_path):
        fp.set_cache_dir(str(tmp_path))
        p1 = build_pipeline(name="cache-c")
        program1 = p1._fused_runs[0].program()
        process_one(p1, LINES)     # records the (B, L) geometry
        sig = program1.signature
        path = tmp_path / "fused_cache" / f"v{fp.CACHE_VERSION}_{sig}.json"
        assert path.exists()
        doc = json.loads(path.read_text())
        assert doc["geometries"], doc
        # fresh process model: mem cache cleared, plan reloaded from disk
        fp.reset_for_testing()
        fp.set_cache_dir(str(tmp_path))
        before = self._hits()
        p2 = build_pipeline(name="cache-d")
        program2 = p2._fused_runs[0].program()
        assert program2.signature == sig
        assert self._hits() == before + 1
        assert program2.geometries == program1.geometries

    def test_different_stage_lists_differ(self):
        p1 = build_pipeline(name="cache-e")
        cfg = dict(THREE_STAGE)
        cfg["processors"] = list(THREE_STAGE["processors"][:2])
        p2 = build_pipeline(cfg, name="cache-f")
        assert (p1._fused_runs[0].program().signature
                != p2._fused_runs[0].program().signature)


# ---------------------------------------------------------------------------
# 5. the round-trip model (the ISSUE acceptance ≥2× bound)


class TestRoundtripModel:
    def test_fused_beats_staged_by_2x_under_latency_model(self):
        p = build_pipeline(name="model-a")
        run = p._fused_runs[0]
        program = run.program()
        lines = LINES * 16
        process_one(p, lines)                       # warm fused jit
        g = make_group(lines)
        from loongcollector_tpu.processor.common import extract_source
        src = extract_source(g, run.source_key)
        from loongcollector_tpu.ops.device_batch import (pack_rows,
                                                         pick_length_bucket)
        L = pick_length_bucket(int(src.lengths.max()))
        batch = pack_rows(src.arena, src.offsets, src.lengths, L)
        program.staged_run(batch.rows, batch.lengths)   # warm staged jit

        rtt, wire = 0.004, 0.002
        n_batches = 5

        fused_kern = LatencyInjectedKernel(program._fn, rtt, serialize=True,
                                           wire_s=wire)
        program.set_kernel_override(fused_kern)
        try:
            t0 = time.perf_counter()
            dispatches = [
                fp.FusedDispatch(program, src.arena, src.offsets,
                                 src.lengths).dispatch()
                for _ in range(n_batches)]
            for d in dispatches:
                d.result()
            fused_s = time.perf_counter() - t0
        finally:
            program.set_kernel_override(None)

        # staged model: each member stage pays its own round trip, one
        # serialized execution stream per stage kernel
        orig = [s.staged for s in program.specs]
        lat = []
        for s in program.specs:
            if s.kind == "keep":
                for c in s.payload:
                    lat.append((c, c.staged,
                                LatencyInjectedKernel(c.staged, rtt,
                                                      wire_s=wire)))
            else:
                lat.append((s, s.staged,
                            LatencyInjectedKernel(s.staged, rtt,
                                                  wire_s=wire)))
        try:
            for obj, _o, k in lat:
                obj.staged = k
            t0 = time.perf_counter()
            for _ in range(n_batches):
                program.staged_run(batch.rows, batch.lengths)
            staged_s = time.perf_counter() - t0
        finally:
            for obj, o, _k in lat:
                obj.staged = o
        ratio = staged_s / fused_s
        assert ratio >= 2.0, (
            f"fused {fused_s*1e3:.1f} ms vs staged {staged_s*1e3:.1f} ms "
            f"— only {ratio:.2f}x under the round-trip model")


# ---------------------------------------------------------------------------
# 6. the 8-seed fused-dispatch storm with the live ledger


def _chunk(src_idx: int, seq: int, n: int) -> bytes:
    return b"\n".join(b"src%d %d" % (src_idx, seq + j)
                      for j in range(n)) + b"\n"


def _raw_group(payload: bytes, source: bytes) -> PipelineEventGroup:
    sb = SourceBuffer(len(payload) + 128)
    g = PipelineEventGroup(sb)
    g.add_raw_event(1700000002).set_content(sb.copy_string(payload))
    g.set_tag(b"__source__", source)
    return g


@pytest.mark.parametrize("seed", SEEDS)
def test_fused_dispatch_storm(seed, tmp_path, monkeypatch):
    monkeypatch.setenv("LOONG_FUSED", "1")
    DevicePlane.reset_for_testing(budget_bytes=2 * 1024 * 1024)
    fp.reset_for_testing()
    demote_before = _demotions()
    ledger.enable()
    ledger.reset()
    auditor = ledger.start_auditor(interval_s=0.05)
    chaos.install(ChaosPlan(seed, {
        "device_plane.fused_dispatch": FaultSpec(
            prob=0.3, kinds=(chaos.ACTION_ERROR,), max_faults=200),
        "device_plane.submit": FaultSpec(
            prob=0.2, kinds=(chaos.ACTION_DELAY,),
            delay_range=(0.0, 0.002), max_faults=50),
    }))
    name = f"fused-storm-{seed}"
    out = tmp_path / f"{name}.jsonl"
    pqm = ProcessQueueManager()
    mgr = CollectionPipelineManager(pqm, SenderQueueManager())
    runner = ProcessorRunner(pqm, mgr, thread_count=4)
    runner.init()
    sources = [b"s%d" % i for i in range(4)]
    try:
        diff = ConfigDiff()
        diff.added[name] = {
            "inputs": [{"Type": "input_static_file_onetime",
                        "FilePaths": ["/nonexistent"]}],
            "global": {"ProcessQueueCapacity": 40},
            "processors": [
                {"Type": "processor_filter_native",
                 "Include": {"content": r"src\d+ \d+"}},
                {"Type": "processor_parse_regex_tpu",
                 "Regex": r"(src\d+) (\d+)", "Keys": ["src", "seq"]},
                {"Type": "processor_filter_native",
                 "Include": {"seq": r"\d+"}},
            ],
            "flushers": [{"Type": "flusher_file", "FilePath": str(out),
                          "MinCnt": 1, "MinSizeBytes": 1}],
        }
        mgr.update_pipelines(diff)
        p = mgr.find_pipeline(name)
        assert p._fused_runs, "storm pipeline must carry a fused run"

        def push_wave(groups_per_source, seq_base):
            total = 0
            for s_i, src in enumerate(sources):
                seq = seq_base
                for _ in range(groups_per_source):
                    g = _raw_group(_chunk(s_i, seq, 8), src)
                    seq += 8
                    deadline = time.monotonic() + 30
                    while not pqm.push_queue(p.process_queue_key, g):
                        assert time.monotonic() < deadline, "push starved"
                        time.sleep(0.002)
                    total += 8
            return total

        total = push_wave(4, 0)
        ledger.assert_conserved(timeout=60, label=f"seed {seed} mid-storm")
        total += push_wave(4, 32)
        assert wait_for(pqm.all_empty, timeout=60)
        time.sleep(0.2)
        ledger.assert_conserved(timeout=60, label=f"seed {seed} post-storm")
        assert auditor.residual_alarms_total == 0
        injected = chaos.fault_counts().get(
            "device_plane.fused_dispatch", 0)
        assert _demotions() - demote_before == injected, (
            f"seed {seed}: {injected} injected errors but "
            f"{_demotions() - demote_before} demotions")
        assert injected > 0, f"seed {seed}: storm never fired"
    finally:
        runner.stop()
        mgr.stop_all()
        chaos.uninstall()
        ledger.stop_auditor()
        ledger.disable()
    per_source = {}
    for line in out.read_text().splitlines():
        obj = json.loads(line)
        if "src" in obj and "seq" in obj:
            per_source.setdefault(obj["src"], []).append(int(obj["seq"]))
    got = sum(len(v) for v in per_source.values())
    assert got == total, f"seed {seed}: lost {total - got} events"
    for src, seqs in per_source.items():
        assert seqs == sorted(seqs), f"seed {seed}: {src} reordered"


# ---------------------------------------------------------------------------
# 7. span-bound DFA match differential


class TestSpanMatch:
    def test_span_match_vs_re(self):
        import re
        from loongcollector_tpu.ops.kernels.dfa_scan import \
            build_dfa_span_match_fn
        from loongcollector_tpu.ops.regex.dfa import compile_dfa
        import jax
        pattern = r"1\d*"
        dfa = compile_dfa(pattern)
        fn = jax.jit(build_dfa_span_match_fn(dfa))
        rng = np.random.RandomState(7)
        rows = np.zeros((16, 32), np.uint8)
        lens = np.zeros(16, np.int32)
        starts = np.zeros(16, np.int32)
        spans = np.zeros(16, np.int32)
        corpus = [b"123", b"15x", b"1", b"", b"912", b"1abc", b"19"]
        ref = re.compile(pattern.encode())
        for i in range(16):
            pre = bytes(rng.randint(97, 123, rng.randint(0, 6),
                                    dtype=np.uint8))
            tok = corpus[i % len(corpus)]
            post = b"tail"[: rng.randint(0, 4)]
            row = pre + tok + post
            rows[i, :len(row)] = np.frombuffer(row, np.uint8)
            lens[i] = len(row)
            starts[i] = len(pre)
            spans[i] = len(tok) if i % 5 else -1   # some absent spans
        got = np.asarray(fn(rows, lens, starts, spans))
        for i in range(16):
            if spans[i] < 0:
                want = False
            else:
                tok = bytes(rows[i, starts[i]:starts[i] + spans[i]]
                            .tobytes())
                want = ref.fullmatch(tok) is not None
            assert bool(got[i]) == want, (i, got[i], want)


# ---------------------------------------------------------------------------
# 8. equivalence gate (the scripts/resident_equivalence.py contract,
#    run in-process on every tier-1 invocation)


class TestEquivalenceGate:
    def test_gate_passes(self, monkeypatch):
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "resident_equivalence",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "scripts",
                "resident_equivalence.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main() == 0
