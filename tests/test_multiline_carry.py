"""Multiline-aware read rollback + cross-chunk carry (round-2 VERDICT #3).

Reference semantics: LogFileReader.cpp:2128-2180 rolls the read back to the
last complete multiline RECORD, so records never split across chunks on the
normal path; ProcessorSplitMultilineLogStringNative assembles records. The
forced-split escape hatch (chunk-sized record, flush timeout) is covered by
the reader's ML_PARTIAL_TAIL / ML_CONTINUE markers + split_multiline carry.
"""

import numpy as np

from loongcollector_tpu.input.file.reader import LogFileReader
from loongcollector_tpu.models import (EventGroupMetaKey, PipelineEventGroup,
                                       SourceBuffer)
from loongcollector_tpu.pipeline.plugin.interface import PluginContext
from loongcollector_tpu.processor.split_log_string import \
    ProcessorSplitLogString
from loongcollector_tpu.processor.split_multiline import \
    ProcessorSplitMultilineLogString

START = r"\d{4}-\d{2}-\d{2} .*"

REC1 = (b"2024-01-02 03:04:05 ERROR boom\n"
        b"  at com.example.Foo(Foo.java:10)\n"
        b"  at com.example.Bar(Bar.java:20)\n")
REC2 = (b"2024-01-02 03:04:06 ERROR pow\n"
        b"  at com.example.Baz(Baz.java:30)\n")


def _strip(x: bytes) -> bytes:
    """Merged records span first-line start → last-line end; the final
    newline belongs to the line SPLIT, not the record."""
    return x.rstrip(b"\n")


def _records(group):
    cols = group.columns
    arena = group.source_buffer.as_array()
    return [bytes(arena[o:o + l].tobytes())
            for o, l in zip(cols.offsets, cols.lengths)]


def _pipeline(groups):
    """Run line-split + multiline over a sequence of reader groups with ONE
    shared processor instance (the carry lives on the instance)."""
    ctx = PluginContext("t")
    sp = ProcessorSplitLogString(); sp.init({}, ctx)
    ml = ProcessorSplitMultilineLogString()
    ml.init({"Multiline": {"StartPattern": START}}, ctx)
    out = []
    for g in groups:
        sp.process(g)
        ml.process(g)
        out.extend(_records(g))
    return out, ml


class TestReaderMultilineRollback:
    def test_holds_open_record_in_file(self, tmp_path):
        """The reader must NOT ship the trailing incomplete record — it
        stays in the file until the next start line closes it."""
        p = tmp_path / "a.log"
        # REC2 is open: no following start line yet
        p.write_bytes(REC1 + REC2)
        r = LogFileReader(str(p), multiline_start=START)
        g = r.read()
        assert g is not None
        assert g.events[0].content.to_bytes() == REC1
        assert g.get_metadata(EventGroupMetaKey.ML_PARTIAL_TAIL) is None
        # nothing more to ship until the record closes
        assert r.read() is None
        # a new start line closes REC2
        p.open("ab").write(b"2024-01-02 03:04:07 INFO ok\n")
        g2 = r.read()
        assert g2.events[0].content.to_bytes() == REC2
        # the new single-line record is itself open now
        assert r.read() is None

    def test_stacktrace_across_two_chunks_one_event(self, tmp_path):
        """THE VERDICT done-test: a stacktrace straddling two read chunks
        yields ONE event end to end."""
        p = tmp_path / "b.log"
        p.write_bytes(REC1 + REC2)
        r = LogFileReader(str(p), multiline_start=START)
        groups = []
        g = r.read()
        groups.append(g)
        p.open("ab").write(b"2024-01-02 03:04:07 INFO done\n")
        g2 = r.read()                  # ships REC2 whole
        groups.append(g2)
        records, _ = _pipeline(groups)
        assert records == [_strip(REC1), _strip(REC2)]

    def test_flush_timeout_ships_partial(self, tmp_path):
        p = tmp_path / "c.log"
        p.write_bytes(REC1 + REC2)
        r = LogFileReader(str(p), multiline_start=START, ml_flush_timeout=0.0)
        g = r.read()                   # timeout 0: first read holds nothing…
        # first read establishes the hold clock; with timeout 0 the partial
        # ships immediately (either on this read or the next)
        if g.events[0].content.to_bytes() == REC1:
            g = r.read()
        assert g.events[0].content.to_bytes().endswith(REC2)
        assert g.get_metadata(EventGroupMetaKey.ML_PARTIAL_TAIL) == "1"

    def test_end_pattern_mode(self, tmp_path):
        p = tmp_path / "d.log"
        p.write_bytes(b"part a\npart b END\npart c\n")
        r = LogFileReader(str(p), multiline_end=r".*END")
        g = r.read()
        assert g.events[0].content.to_bytes() == b"part a\npart b END\n"
        assert r.read() is None        # "part c" awaits its END

    def test_force_flush_ships_everything(self, tmp_path):
        p = tmp_path / "e.log"
        p.write_bytes(REC1 + REC2)
        r = LogFileReader(str(p), multiline_start=START)
        r.read()
        g = r.read(force_flush=True)
        assert g.events[0].content.to_bytes() == REC2


class TestProcessorCarry:
    def _group(self, data: bytes, path="/var/log/x", ino="7",
               partial=False, cont=False):
        sb = SourceBuffer(len(data) + 64)
        g = PipelineEventGroup(sb)
        g.add_raw_event(1).set_content(sb.copy_string(data))
        g.set_metadata(EventGroupMetaKey.LOG_FILE_PATH, path)
        g.set_metadata(EventGroupMetaKey.LOG_FILE_INODE, ino)
        if partial:
            g.set_metadata(EventGroupMetaKey.ML_PARTIAL_TAIL, "1")
        if cont:
            g.set_metadata(EventGroupMetaKey.ML_CONTINUE, "1")
        return g

    def test_forced_split_stitches_one_event(self):
        """Record broken mid-way by the reader (chunk-sized record): the
        carry joins both halves into ONE event."""
        lines = REC1.split(b"\n")
        half1 = lines[0] + b"\n" + lines[1] + b"\n"
        half2 = lines[2] + b"\n"
        g1 = self._group(half1, partial=True)
        g2 = self._group(half2 + REC2, cont=True, partial=True)
        records, ml = _pipeline([g1, g2])
        # REC1 stitched whole; REC2 is the open tail of g2 (partial) → stashed
        assert records == [_strip(REC1)]
        assert ml._carry  # REC2 carried
        # a final chunk with a fresh start flushes REC2 standalone
        g3 = self._group(b"2024-01-02 03:04:08 INFO end\n", cont=True,
                         partial=False)
        records3, _ = _pipeline_continue(ml, g3)
        assert records3[0] == _strip(REC2)

    def test_stale_carry_emits_standalone(self):
        g1 = self._group(REC1 + REC2[:REC2.index(b"\n") + 1], partial=True)
        g2 = self._group(b"2024-01-02 03:04:09 WARN other\n", cont=False)
        records, ml = _pipeline([g1, g2])
        # g1: REC1 emitted, partial first line of REC2 stashed; g2 arrives
        # WITHOUT the continue marker (e.g. rotation) → stash emits alone
        assert _strip(REC1) in records
        assert REC2.split(b"\n")[0] in records

    def test_carry_is_per_source(self):
        ga = self._group(REC1.split(b"\n")[0] + b"\n", path="/a",
                         partial=True)
        gb = self._group(REC2, path="/b")
        records, ml = _pipeline([ga, gb])
        assert len(ml._carry) == 1 and "/a:7" in ml._carry


def _pipeline_continue(ml, group):
    ctx = PluginContext("t")
    sp = ProcessorSplitLogString(); sp.init({}, ctx)
    sp.process(group)
    ml.process(group)
    return _records(group), ml


class TestEndModeCarry:
    """Regression tests for the round-2 review findings: end-pattern modes
    must stitch carried records too (continuations form BLOCKS there)."""

    def _group(self, data, partial=False, cont=False, path="/x", ino="1"):
        sb = SourceBuffer(len(data) + 64)
        g = PipelineEventGroup(sb)
        g.add_raw_event(1).set_content(sb.copy_string(data))
        g.set_metadata(EventGroupMetaKey.LOG_FILE_PATH, path)
        g.set_metadata(EventGroupMetaKey.LOG_FILE_INODE, ino)
        if partial:
            g.set_metadata(EventGroupMetaKey.ML_PARTIAL_TAIL, "1")
        if cont:
            g.set_metadata(EventGroupMetaKey.ML_CONTINUE, "1")
        return g

    def _run(self, cfg, groups):
        ctx = PluginContext("t")
        sp = ProcessorSplitLogString(); sp.init({}, ctx)
        ml = ProcessorSplitMultilineLogString()
        ml.init({"Multiline": cfg}, ctx)
        out = []
        for g in groups:
            sp.process(g)
            ml.process(g)
            out.extend(_records(g))
        return out, ml

    def test_end_only_stitches_block_continuation(self):
        g1 = self._group(b"part a\n", partial=True)
        g2 = self._group(b"part b END\nnext END\n", cont=True)
        records, ml = self._run({"EndPattern": r".*END"}, [g1, g2])
        assert records == [b"part a\npart b END", b"next END"]
        assert not ml._carry

    def test_start_end_merge_stops_at_first_end(self):
        g1 = self._group(b"2024-01-02 03:04:05 open\n", partial=True)
        g2 = self._group(b"tail END\njunk\n2024-01-02 03:04:06 two END\n",
                         cont=True)
        records, ml = self._run(
            {"StartPattern": START, "EndPattern": r".*END"}, [g1, g2])
        # 'junk' must NOT be absorbed into the stitched record
        assert records == [b"2024-01-02 03:04:05 open\ntail END",
                          b"junk",
                          b"2024-01-02 03:04:06 two END"]

    def test_orphaned_carry_expires_through_next_group(self, monkeypatch):
        import loongcollector_tpu.processor.split_multiline as sm
        monkeypatch.setattr(sm, "CARRY_TTL_S", 0.0)
        g1 = self._group(b"2024-01-02 03:04:05 open\n", partial=True,
                         path="/gone", ino="9")
        g2 = self._group(b"2024-01-02 03:04:06 other\n", path="/live",
                         ino="2")
        records, ml = self._run({"StartPattern": START}, [g1, g2])
        # the orphaned stash (source /gone never returned) flushed via g2
        assert b"2024-01-02 03:04:05 open" in records
        assert not ml._carry


class TestCarryDrain:
    """Held records must flush on idle (timeout tick) and at shutdown
    (round-2 review finding: an idle pipeline's last record was lost)."""

    def test_drain_groups_ships_all_carries(self):
        ctx = PluginContext("t")
        ml = ProcessorSplitMultilineLogString()
        ml.init({"Multiline": {"StartPattern": START}}, ctx)
        ml._stash("/var/x:7", b"2024-01-02 03:04:05 held", 42, [])
        out = ml.drain_groups()
        assert len(out) == 1 and not ml._carry
        g = out[0]
        assert _records(g) == [b"2024-01-02 03:04:05 held"]
        assert str(g.get_metadata(EventGroupMetaKey.LOG_FILE_PATH)) == "/var/x"
        assert int(g.columns.timestamps[0]) == 42

    def test_flush_timeout_groups_respects_age(self, monkeypatch):
        import loongcollector_tpu.processor.split_multiline as sm
        ctx = PluginContext("t")
        ml = ProcessorSplitMultilineLogString()
        ml.init({"Multiline": {"StartPattern": START}}, ctx)
        ml._stash("/a:1", b"fresh", 1, [])
        assert ml.flush_timeout_groups() == []          # too young
        monkeypatch.setattr(sm, "CARRY_FLUSH_S", 0.0)
        out = ml.flush_timeout_groups()
        assert len(out) == 1 and not ml._carry

    def test_pipeline_stop_drains_carry_to_sink(self):
        from loongcollector_tpu.pipeline.pipeline import CollectionPipeline
        p = CollectionPipeline()
        assert p.init("ml-drain", {
            "inputs": [{"Type": "input_file", "FilePaths": ["/nonexistent"],
                        "Multiline": {"StartPattern": START}}],
            "processors": [],
            "flushers": [{"Type": "flusher_blackhole"}],
        })
        ml = next(i.plugin for i in p.inner_processors
                  if isinstance(i.plugin, ProcessorSplitMultilineLogString))
        ml._stash("/var/y:9", b"2024-01-02 03:04:05 last record", 7, [])
        bh = p.flushers[0].plugin
        p.stop(is_removing=True)
        assert bh.total_events == 1
        p.release()


def test_concurrent_stash_never_overwrites():
    """Round-2 stress review: two stashes for the same key (out-of-order
    chunk processing across threads) must both survive — the earlier open
    record is emitted standalone, never overwritten."""
    ctx = PluginContext("t")
    ml = ProcessorSplitMultilineLogString()
    ml.init({"Multiline": {"StartPattern": START}}, ctx)
    injected = []
    ml._stash("/s:1", b"2024 first-open", 1, injected)
    ml._stash("/s:1", b"2024 second-open", 2, injected)
    # the first record was displaced into the injected output
    assert [(d, t) for _, d, t in injected] == [(b"2024 first-open", 1)]
    held = ml.drain_groups()
    assert len(held) == 1
    assert _records(held[0]) == [b"2024 second-open"]
