"""loongshard batched-NDJSON goldens: the native zero-copy serialize fast
path must be byte-identical to the canonical per-event dict + json.dumps
loops it replaced (ISSUE 4 satellite) — for the JSON serializer and for the
clickhouse/doris/elasticsearch payload builders, across escaping, absent
fields, tag collisions and non-ASCII fallback."""

import json
from datetime import datetime, timezone

import numpy as np
import pytest

import loongcollector_tpu.native as native
from loongcollector_tpu.flusher.clickhouse import FlusherClickHouse
from loongcollector_tpu.flusher.doris import FlusherDoris
from loongcollector_tpu.flusher.elasticsearch import FlusherElasticsearch
from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
from loongcollector_tpu.pipeline.plugin.interface import PluginContext
from loongcollector_tpu.pipeline.serializer import batch_json
from loongcollector_tpu.pipeline.serializer.batch_json import (
    TS_EPOCH, TS_ISO8601, dumps_row, native_group_rows, ndjson_payload)
from loongcollector_tpu.pipeline.serializer.event_dicts import \
    iter_event_dicts
from loongcollector_tpu.pipeline.serializer.json_serializer import \
    JsonSerializer
from loongcollector_tpu.processor.parse_regex import ProcessorParseRegex
from loongcollector_tpu.processor.split_log_string import \
    ProcessorSplitLogString


def _columnar_group(lines, tags=(), regex=r"(\w+)-(\d+) (\S+)",
                    keys=("word", "num", "rest")):
    """chunk → split → regex parse: a fields-bearing columnar group, the
    shape the processing pipeline hands to the serializers."""
    data = b"\n".join(lines) + b"\n"
    sb = SourceBuffer(len(data) + 64)
    g = PipelineEventGroup(sb)
    g.add_raw_event(7).set_content(sb.copy_string(data))
    for k, v in tags:
        g.set_tag(k, v)
    ctx = PluginContext("golden")
    sp = ProcessorSplitLogString()
    sp.init({}, ctx)
    sp.process(g)
    pr = ProcessorParseRegex()
    pr.init({"Regex": regex, "Keys": list(keys)}, ctx)
    pr.process(g)
    return g


@pytest.fixture()
def no_native(monkeypatch):
    """Force every consumer onto the canonical dict path."""
    monkeypatch.setattr(native, "ndjson_serialize", lambda *a, **k: None)


LINES = [b"alpha-1 /index.html", b"beta-22 /api/v1", b"gamma-333 /x?q=1"]
TAGS = ((b"host", b"web-1"), (b"__source__", b"fileA"))


class TestJsonSerializerGolden:
    def test_fast_path_is_byte_identical(self, monkeypatch):
        ser = JsonSerializer()
        fast = bytes(ser.serialize([_columnar_group(LINES, TAGS)]))
        monkeypatch.setattr(native, "ndjson_serialize",
                            lambda *a, **k: None)
        slow = bytes(ser.serialize([_columnar_group(LINES, TAGS)]))
        assert fast == slow
        assert fast.count(b"\n") == len(LINES)

    def test_literal_golden(self):
        ser = JsonSerializer()
        out = bytes(ser.serialize([_columnar_group(LINES[:1], TAGS)]))
        assert out == (b'{"host": "web-1", "__source__": "fileA", '
                       b'"__time__": 7, "word": "alpha", "num": "1", '
                       b'"rest": "/index.html"}\n')

    def test_escapes_match_json_dumps(self, monkeypatch):
        lines = [b'esc-1 "quoted"\\back',
                 b"ctl-2 a\tb\x01c",
                 b"del-3 x\x7fy"]
        ser = JsonSerializer()
        fast = bytes(ser.serialize([_columnar_group(lines, TAGS)]))
        monkeypatch.setattr(native, "ndjson_serialize",
                            lambda *a, **k: None)
        slow = bytes(ser.serialize([_columnar_group(lines, TAGS)]))
        assert fast == slow
        assert b'\\"quoted\\"' in fast and b"\\t" in fast \
            and b"\\u0001" in fast

    def test_non_ascii_falls_back_and_matches(self, monkeypatch):
        lines = ["müller-1 ünïcode".encode(), b"plain-2 ok",
                 b"bad-3 \xff\xfe broken"]
        called = []
        orig = native.ndjson_serialize

        def spy(*a, **k):
            called.append(1)
            return orig(*a, **k)
        monkeypatch.setattr(native, "ndjson_serialize", spy)
        ser = JsonSerializer()
        fast = bytes(ser.serialize([_columnar_group(lines, TAGS)]))
        assert not called, "non-ASCII spans must stay on the codec path"
        monkeypatch.setattr(native, "ndjson_serialize",
                            lambda *a, **k: None)
        assert fast == bytes(ser.serialize([_columnar_group(lines, TAGS)]))

    def test_ts_key_collision_falls_back(self, monkeypatch):
        g = _columnar_group(LINES, ((b"__time__", b"tagged"),))
        assert native_group_rows(g, "__time__", ts_mode=TS_EPOCH,
                                 ts_first=True) is None
        ser = JsonSerializer()
        fast = bytes(ser.serialize(
            [_columnar_group(LINES, ((b"__time__", b"tagged"),))]))
        monkeypatch.setattr(native, "ndjson_serialize",
                            lambda *a, **k: None)
        slow = bytes(ser.serialize(
            [_columnar_group(LINES, ((b"__time__", b"tagged"),))]))
        assert fast == slow

    def test_absent_fields_omit_keys(self, monkeypatch):
        # second line fails the pattern → _partial_ routes or absent spans;
        # use a pattern where one group is optional-ish via alternation
        lines = [b"aa-1 x", b"zzz 9"]   # second line: no match
        ser = JsonSerializer()
        fast = bytes(ser.serialize([_columnar_group(lines, TAGS)]))
        monkeypatch.setattr(native, "ndjson_serialize",
                            lambda *a, **k: None)
        slow = bytes(ser.serialize([_columnar_group(lines, TAGS)]))
        assert fast == slow

    def test_event_groups_unchanged(self):
        g = PipelineEventGroup()
        sb = g.source_buffer
        ev = g.add_log_event(11)
        ev.set_content(sb.copy_string(b"k"), sb.copy_string(b"v"))
        g.set_tag(b"host", b"h")
        out = bytes(JsonSerializer().serialize([g]))
        assert out == b'{"host": "h", "__time__": 11, "k": "v"}\n'


class TestNdjsonPayloadGolden:
    def test_clickhouse_identical_and_golden(self, monkeypatch):
        fl = FlusherClickHouse()
        fl._init_sink({"Addresses": ["http://ch:8123"], "Table": "t"})
        fast, _ = fl.build_payload([_columnar_group(LINES[:1], TAGS)])
        monkeypatch.setattr(native, "ndjson_serialize",
                            lambda *a, **k: None)
        slow, _ = fl.build_payload([_columnar_group(LINES[:1], TAGS)])
        assert bytes(fast) == bytes(slow)
        assert bytes(fast) == (
            b'{"host": "web-1", "__source__": "fileA", "word": "alpha", '
            b'"num": "1", "rest": "/index.html", "_timestamp": 7}\n')

    def test_doris_identical(self, monkeypatch):
        fl = FlusherDoris()
        fl._init_sink({"Addresses": ["http://d:8030"], "Database": "db",
                       "Table": "t"})
        fast, _ = fl.build_payload([_columnar_group(LINES, TAGS)])
        monkeypatch.setattr(native, "ndjson_serialize",
                            lambda *a, **k: None)
        slow, _ = fl.build_payload([_columnar_group(LINES, TAGS)])
        assert bytes(fast) == bytes(slow)

    def test_elasticsearch_identical_with_iso_timestamps(self, monkeypatch):
        fl = FlusherElasticsearch()
        fl._init_sink({"Addresses": ["http://es:9200"], "Index": "logs"})
        fast, _ = fl.build_payload([_columnar_group(LINES, TAGS)])
        monkeypatch.setattr(native, "ndjson_serialize",
                            lambda *a, **k: None)
        slow, _ = fl.build_payload([_columnar_group(LINES, TAGS)])
        assert bytes(fast) == bytes(slow)
        assert bytes(fast).count(b'{"index": {"_index": "logs"}}') \
            == len(LINES)
        assert b'"@timestamp": "1970-01-01T00:00:07Z"' in bytes(fast)

    def test_mixed_fast_and_fallback_groups(self, monkeypatch):
        groups = [_columnar_group(LINES, TAGS),
                  _columnar_group(["ü-1 x".encode()], TAGS)]
        fast = ndjson_payload(groups, ts_key="_timestamp")
        monkeypatch.setattr(native, "ndjson_serialize",
                            lambda *a, **k: None)
        groups = [_columnar_group(LINES, TAGS),
                  _columnar_group(["ü-1 x".encode()], TAGS)]
        slow = ndjson_payload(groups, ts_key="_timestamp")
        assert bytes(fast) == bytes(slow)

    def test_empty_groups_yield_none(self):
        assert ndjson_payload([]) is None


class TestIso8601Native:
    @pytest.mark.parametrize("ts", [0, 7, 951868800, 1700000000,
                                    4102444799, 1583020799, 253402300799])
    def test_matches_datetime(self, ts):
        g = _columnar_group([b"aa-%d x" % (ts % 97)])
        out = native_group_rows(g, "@timestamp", ts_mode=TS_ISO8601,
                                ts_first=False)
        # group timestamps are the split timestamp (7); patch in the
        # parametrised one via the columns and re-serialize
        g.columns.timestamps = np.full(len(g.columns), ts, dtype=np.int64)
        out = native_group_rows(g, "@timestamp", ts_mode=TS_ISO8601,
                                ts_first=False)
        want = datetime.fromtimestamp(
            ts, tz=timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
        assert f'"@timestamp": "{want}"'.encode() in bytes(out)


class TestSharedRowEncoder:
    def test_dumps_row_is_canonical(self):
        obj = {"a": 1, "b": "x\ty", "c": "ünïcode"}
        assert dumps_row(obj) == json.dumps(
            obj, ensure_ascii=False).encode()

    def test_iter_event_dicts_round_trip(self):
        g = _columnar_group(LINES, TAGS)
        rows = list(iter_event_dicts(g))
        assert len(rows) == len(LINES)
        assert rows[0][1]["word"] == "alpha"
