"""Long-tail processors batch 2: Go-compat behavior tests.

Reference semantics from plugins/processor/{anchor,appender,cloudmeta,
csv,defaultone,droplastkey,gotime,logtoslsmetric,md5,otel}/.
"""

import hashlib
import os

import pytest

from loongcollector_tpu.models import PipelineEventGroup
from loongcollector_tpu.pipeline.plugin.interface import PluginContext
from loongcollector_tpu.pipeline.plugin.registry import PluginRegistry


def _proc(name, cfg):
    r = PluginRegistry.instance()
    r.load_static_plugins()
    p = r.create_processor(name)
    assert p is not None, name
    assert p.init(cfg, PluginContext("t")), (name, cfg)
    return p


def _group(rows):
    g = PipelineEventGroup()
    sb = g.source_buffer
    for row in rows:
        ev = g.add_log_event(1700000000)
        for k, v in row.items():
            ev.set_content(sb.copy_string(k.encode()),
                           sb.copy_string(v.encode()))
    return g


def _rows(g):
    return [{k.to_str(): v.to_bytes() for k, v in ev.contents}
            for ev in g.events if hasattr(ev, "contents")]


class TestAnchor:
    def test_string_extraction(self):
        p = _proc("processor_anchor", {
            "SourceKey": "content",
            "Anchors": [{"Start": "time:", "Stop": "\t",
                         "FieldName": "time"},
                        {"Start": "status:", "Stop": "",
                         "FieldName": "status"}]})
        g = _group([{"content": "time:12:01\tstatus:200"}])
        p.process(g)
        r = _rows(g)[0]
        assert r["time"] == b"12:01" and r["status"] == b"200"

    def test_json_expansion(self):
        p = _proc("processor_anchor", {
            "SourceKey": "content", "KeepSource": False,
            "Anchors": [{"Start": "json:", "Stop": "", "FieldName": "j",
                         "FieldType": "json", "ExpondJSON": True,
                         "ExpondConnecter": "_"}]})
        g = _group([{"content": 'json:{"a": {"b": 1}, "c": "x"}'}])
        p.process(g)
        r = _rows(g)[0]
        assert r["j_a_b"] == b"1" and r["j_c"] == b"x"
        assert "content" not in r


class TestAppender:
    def test_append_with_substitution(self):
        import socket
        p = _proc("processor_appender",
                  {"Key": "tags", "Value": "|host={{__hostname__}}"})
        g = _group([{"tags": "app=web"}])
        p.process(g)
        want = f"app=web|host={socket.gethostname()}".encode()
        assert _rows(g)[0]["tags"] == want

    def test_env_substitution(self, monkeypatch):
        monkeypatch.setenv("DEPLOY_ENV", "staging")
        p = _proc("processor_appender",
                  {"Key": "k", "Value": "-{{env.DEPLOY_ENV}}"})
        g = _group([{"k": "v"}])
        p.process(g)
        assert _rows(g)[0]["k"] == b"v-staging"


class TestCloudMeta:
    def test_env_metadata(self, monkeypatch):
        monkeypatch.setenv("ALIYUN_INSTANCE_ID", "i-abc123")
        monkeypatch.setenv("ALIYUN_REGION_ID", "cn-hangzhou")
        p = _proc("processor_cloud_meta",
                  {"Metadata": ["instance_id", "region", "hostname"]})
        g = _group([{"m": "1"}])
        p.process(g)
        r = _rows(g)[0]
        assert r["__cloud_instance_id__"] == b"i-abc123"
        assert r["__cloud_region__"] == b"cn-hangzhou"
        assert r["__cloud_hostname__"]


class TestCSV:
    def test_quoted_fields(self):
        p = _proc("processor_csv", {
            "SourceKey": "content",
            "SplitKeys": ["name", "city", "note"]})
        g = _group([{"content": 'alice,"hang, zhou","said ""hi"""'}])
        p.process(g)
        r = _rows(g)[0]
        assert r["name"] == b"alice"
        assert r["city"] == b"hang, zhou"
        assert r["note"] == b'said "hi"'
        assert "content" not in r

    def test_expand_others(self):
        p = _proc("processor_csv", {
            "SourceKey": "content", "SplitKeys": ["a"],
            "ExpandOthers": True, "ExpandKeyPrefix": "x_"})
        g = _group([{"content": "1,2,3"}])
        p.process(g)
        r = _rows(g)[0]
        assert (r["a"], r["x_1"], r["x_2"]) == (b"1", b"2", b"3")


class TestDropLastKey:
    def test_drops_when_parsed(self):
        p = _proc("processor_drop_last_key",
                  {"DropKey": "raw", "Include": ["raw"]})
        g = _group([{"raw": "x=1", "x": "1"},     # parsed: extra key
                    {"raw": "unparsed"}])          # not parsed: keep
        p.process(g)
        rows = _rows(g)
        assert "raw" not in rows[0]
        assert rows[1]["raw"] == b"unparsed"


class TestGotime:
    def test_layout_conversion(self):
        from loongcollector_tpu.processor.longtail2 import \
            go_layout_to_strptime
        assert go_layout_to_strptime("2006-01-02 15:04:05") \
            == "%Y-%m-%d %H:%M:%S"
        assert go_layout_to_strptime("02/Jan/2006:15:04:05 -0700") \
            == "%d/%b/%Y:%H:%M:%S %z"

    def test_parse_and_set_time(self):
        p = _proc("processor_gotime", {
            "SourceKey": "t", "SourceFormat": "2006-01-02 15:04:05",
            "SourceLocation": 0, "DestKey": "iso",
            "DestFormat": "2006-01-02", "SetTime": True})
        g = _group([{"t": "2023-11-14 22:13:20"}])
        p.process(g)
        ev = g.events[0]
        assert ev.timestamp == 1700000000
        assert _rows(g)[0]["iso"] == b"2023-11-14"

    def test_fixed_milliseconds(self):
        p = _proc("processor_gotime", {
            "SourceKey": "t", "SourceFormat": "milliseconds",
            "DestKey": "d", "DestFormat": "2006-01-02 15:04:05"})
        g = _group([{"t": "1700000000000"}])
        p.process(g)
        assert g.events[0].timestamp == 1700000000
        assert _rows(g)[0]["d"] == b"2023-11-14 22:13:20"


class TestLogToSlsMetric:
    def test_conversion(self):
        from loongcollector_tpu.models.events import MetricEvent
        p = _proc("processor_log_to_sls_metric", {
            "MetricTimeKey": "ts_nano",
            "MetricLabelKeys": ["host"],
            "MetricValues": {"mname": "mval"},
            "CustomMetricLabels": {"cluster": "c1"}})
        g = _group([{"mname": "cpu_util", "mval": "0.75",
                     "host": "web-1", "ts_nano": "1700000001000000000"}])
        p.process(g)
        [m] = g.events
        assert isinstance(m, MetricEvent)
        assert bytes(m.name) == b"cpu_util"
        assert m.value.value == 0.75
        assert m.timestamp == 1700000001
        assert bytes(m.get_tag(b"host")) == b"web-1"
        assert bytes(m.get_tag(b"cluster")) == b"c1"

    def test_bad_value_passthrough(self):
        p = _proc("processor_log_to_sls_metric",
                  {"MetricValues": {"n": "v"}})
        g = _group([{"n": "m1", "v": "not-a-number"}])
        p.process(g)
        assert len(g.events) == 0      # unparseable value emits nothing


class TestMD5:
    def test_digest(self):
        p = _proc("processor_md5", {"SourceKey": "content",
                                    "DestKey": "sig"})
        g = _group([{"content": "hello"}])
        p.process(g)
        assert _rows(g)[0]["sig"] == \
            hashlib.md5(b"hello").hexdigest().encode()


class TestOtel:
    def test_trace_conversion(self):
        from loongcollector_tpu.models.events import SpanEvent
        p = _proc("processor_otel_trace", {})
        g = _group([{"traceID": "t1", "spanID": "s1",
                     "parentSpanID": "s0", "spanName": "GET /x",
                     "startTime": "1700000000000000",
                     "endTime": "1700000000250000",
                     "kind": "server", "statusCode": "ERROR",
                     "attribute": '{"http.method": "GET"}'},
                    {"plain": "log line"}])
        p.process(g)
        span, plain = g.events
        assert isinstance(span, SpanEvent)
        assert span.trace_id == b"t1"
        assert span.start_time_ns == 1700000000000000000
        assert span.kind == SpanEvent.Kind.SERVER
        assert span.status == SpanEvent.Status.ERROR
        assert span.attributes[b"http.method"].to_bytes() == b"GET"
        assert hasattr(plain, "contents")      # non-trace row untouched

    def test_metric_conversion(self):
        from loongcollector_tpu.models.events import MetricEvent
        p = _proc("processor_otel_metric", {})
        g = _group([{"__name__": "rps", "__value__": "12.5",
                     "__labels__": "svc#$#cart|zone#$#eu",
                     "__time_nano__": "1700000002000000000"}])
        p.process(g)
        [m] = g.events
        assert isinstance(m, MetricEvent)
        assert m.value.value == 12.5
        assert m.timestamp == 1700000002
        assert bytes(m.get_tag(b"svc")) == b"cart"
        assert bytes(m.get_tag(b"zone")) == b"eu"


class TestDefault:
    def test_passthrough(self):
        p = _proc("processor_default", {})
        g = _group([{"a": "1"}])
        p.process(g)
        assert _rows(g) == [{"a": b"1"}]


class TestReviewRegressions2:
    def test_gotime_fractional_dest(self):
        p = _proc("processor_gotime", {
            "SourceKey": "t", "SourceFormat": "seconds",
            "DestKey": "d", "DestFormat": "15:04:05.000"})
        g = _group([{"t": "1700000000"}])
        p.process(g)
        out = _rows(g)[0]["d"]
        assert b"%f" not in out and out.startswith(b"22:13:20.")

    def test_gotime_dest_location(self):
        p = _proc("processor_gotime", {
            "SourceKey": "t", "SourceFormat": "seconds",
            "DestKey": "d", "DestFormat": "2006-01-02 15:04:05",
            "DestLocation": 8})
        g = _group([{"t": "1700000000"}])
        p.process(g)
        assert _rows(g)[0]["d"] == b"2023-11-15 06:13:20"   # UTC+8

    def test_anchor_sequential_scan(self):
        p = _proc("processor_anchor", {
            "SourceKey": "content",
            "Anchors": [{"Start": "id=", "Stop": "&", "FieldName": "a"},
                        {"Start": "id=", "Stop": "&", "FieldName": "b"}]})
        g = _group([{"content": "id=1&id=2&"}])
        p.process(g)
        r = _rows(g)[0]
        assert (r["a"], r["b"]) == (b"1", b"2")

    def test_metric_conversion_columnar_no_resurrection(self):
        import numpy as np

        from loongcollector_tpu.models import (ColumnarLogs,
                                               PipelineEventGroup,
                                               SourceBuffer)
        data = b"plain line one\nplain line two\n"
        sb = SourceBuffer(len(data) + 64)
        view = sb.copy_string(data)
        g = PipelineEventGroup(sb)
        cols = ColumnarLogs(
            np.array([view.offset, view.offset + 15], dtype=np.int32),
            np.array([14, 14], dtype=np.int32),
            np.full(2, 1700000000, dtype=np.int64))
        g.set_columns(cols)
        p = _proc("processor_log_to_sls_metric",
                  {"MetricValues": {"n": "v"}})
        p.process(g)
        assert len(g) == 0      # nothing convertible; nothing resurrects
