"""Differential tests: Tier-1 segment programs + extraction kernel vs `re`.

The Tier-1 compiler promises exact equivalence with the backtracking engine
for every pattern it accepts; these tests enforce that with matching AND
non-matching inputs, mirroring the reference's per-feature + fail-path test
style (core/unittest/processor/ProcessorParseRegexNativeUnittest.cpp).
"""

import re

import numpy as np
import pytest

from loongcollector_tpu.ops.device_batch import pack_rows, pick_length_bucket
from loongcollector_tpu.ops.kernels.field_extract import ExtractKernel
from loongcollector_tpu.ops.regex import (PatternTier, Tier1Unsupported,
                                          classify_pattern, compile_tier1)

APACHE = r'(\S+) (\S+) (\S+) \[([^\]]+)\] "(\S+) (\S+) ([^"]*)" (\d{3}) (\d+)'
APACHE_LINE = (b'127.0.0.1 - frank [10/Oct/2000:13:55:36 -0700] '
               b'"GET /apache_pb.gif HTTP/1.0" 200 2326')

NGINX_TIME = r'(\d{4})-(\d{2})-(\d{2})T(\d{2}):(\d{2}):(\d{2})'
QUOTED = r'"([^"]*)" (\S+)'


def run_kernel(pattern, lines):
    prog = compile_tier1(pattern)
    kern = ExtractKernel(prog)
    arena = np.frombuffer(b"".join(lines), dtype=np.uint8)
    offsets, lengths, off = [], [], 0
    for ln in lines:
        offsets.append(off)
        lengths.append(len(ln))
        off += len(ln)
    L = pick_length_bucket(max(lengths))
    batch = pack_rows(arena, np.array(offsets), np.array(lengths), L)
    ok, coff, clen = kern(batch.rows, batch.lengths)
    ok = np.asarray(ok)[: batch.n_real]
    coff = np.asarray(coff)[: batch.n_real]
    clen = np.asarray(clen)[: batch.n_real]
    return ok, coff, clen


def assert_matches_re(pattern, lines):
    ok, coff, clen = run_kernel(pattern, lines)
    rx = re.compile(pattern.encode() if isinstance(pattern, str) else pattern)
    for i, ln in enumerate(lines):
        m = rx.fullmatch(ln)
        assert ok[i] == (m is not None), f"line {i}: {ln!r}"
        if m:
            for g in range(rx.groups):
                s, e = m.span(g + 1)
                if s < 0:  # group not matched (e.g. skipped optional)
                    assert clen[i, g] == -1, f"line {i} group {g} absent"
                else:
                    assert coff[i, g] == s, f"line {i} group {g} offset"
                    assert clen[i, g] == e - s, f"line {i} group {g} len"


class TestTierClassification:
    def test_apache_is_tier1(self):
        assert classify_pattern(APACHE) == PatternTier.SEGMENT

    def test_simple_alternation_is_tier1(self):
        assert classify_pattern(r"(?:GET|POST|PUT) /\S*") == PatternTier.SEGMENT

    def test_repeat_group_is_dfa(self):
        assert classify_pattern(r"(?:ab)+x") == PatternTier.DFA

    def test_backref_is_cpu(self):
        assert classify_pattern(r"(a+)b\1") == PatternTier.CPU

    def test_overlapping_greedy_rejected(self):
        with pytest.raises(Tier1Unsupported):
            compile_tier1(r"(\d+)(\d+)")

    def test_dot_star_then_literal_via_pivot(self):
        # previously rejected; the bidirectional pivot anchors the literal
        # at the line end, making the boundary unique — full re equivalence
        prog = compile_tier1(r"(.*)x")
        assert prog.pivot is not None
        assert_matches_re(r"(.*)x", [b"axbx", b"xx", b"x", b"", b"abc"])

    def test_fixed_repeat_same_class_ok(self):
        compile_tier1(r"(\d{4})(\d{2})")


class TestApache:
    def test_match_and_captures(self):
        assert_matches_re(APACHE, [APACHE_LINE])

    def test_mixed_match_fail(self):
        lines = [
            APACHE_LINE,
            b"not an apache line at all",
            b'10.2.3.4 - - [01/Jan/2024:00:00:00 +0000] "POST /api/v1 HTTP/1.1" 404 0',
            b"",
            b'x - - [t] "GET / HTTP/1.0" 99 1',  # status only 2 digits
        ]
        assert_matches_re(APACHE, lines)

    def test_large_batch_against_re(self):
        rng = np.random.default_rng(0)
        lines = []
        for i in range(500):
            ip = f"10.{rng.integers(256)}.{rng.integers(256)}.{rng.integers(256)}"
            meth = ["GET", "POST", "DELETE"][int(rng.integers(3))]
            url = "/" + "x" * int(rng.integers(1, 30))
            status = int(rng.integers(100, 600))
            size = int(rng.integers(0, 10**6))
            ln = (f'{ip} - u{i} [10/Oct/2000:13:55:36 -0700] '
                  f'"{meth} {url} HTTP/1.{i%2}" {status} {size}').encode()
            if i % 7 == 0:  # corrupt some
                ln = ln.replace(b'"', b"'", 1)
            lines.append(ln)
        assert_matches_re(APACHE, lines)


class TestProgramFeatures:
    def test_fixed_spans_timestamp(self):
        assert_matches_re(NGINX_TIME, [
            b"2024-01-31T09:15:59", b"2024-1-31T09:15:59", b"9999-99-99T00:00:00",
            b"2024-01-31t09:15:59", b"2024-01-31T09:15:5",
        ])

    def test_quoted_field(self):
        assert_matches_re(QUOTED, [
            b'"hello world" tail', b'"" t', b'"a"b" c', b'no quotes here',
        ])

    def test_lazy_with_excluded_stop_equals_greedy(self):
        # ([^"]*?) before a quote is forced: lazy == greedy, Tier-1 accepts
        assert_matches_re(r'"([^"]*?)" (\S+)', [
            b'"hello" x', b'"a"b" c', b'"" y',
        ])

    def test_ambiguous_lazy_via_pivot(self):
        # .*? before a quote can backtrack past quotes (`"a" "b" c`);
        # the bidirectional pivot resolves it exactly: the suffix matches
        # right-to-left from the line end, so the kernel returns the same
        # span re's backtracking finds
        prog = compile_tier1(r'"(.*?)" (\S+)')
        assert prog.pivot is not None
        assert_matches_re(r'"(.*?)" (\S+)', [
            b'"hello world" tail', b'"" t', b'"a" "b" c', b'no quotes',
            b'"x" ', b'"x" y z', b'"a" b" c',
        ])

    def test_not_literal_class(self):
        assert_matches_re(r"([^:]+):(.*)", [
            b"key:value", b"novalue:", b":leading", b"nocolon",
            b"a:b:c",
        ])

    def test_bounded_repeat(self):
        assert_matches_re(r"([a-z]{2,4})-(\d+)", [
            b"ab-1", b"abcd-22", b"abcde-3", b"a-4", b"ab-",
        ])

    def test_plus_to_end(self):
        assert_matches_re(r"(\w+) (.+)", [
            b"hello every thing else", b"hello ", b" x", b"single",
        ])

    def test_named_groups(self):
        prog = compile_tier1(r"(?P<ip>\S+) (?P<rest>.*)")
        assert prog.group_names == {0: "ip", 1: "rest"}

    def test_noncapturing_group(self):
        assert_matches_re(r"(?:ab)+x", [b"ababx"]) if False else None
        # (?:ab)+ is repeat of multi-token — Tier-1 rejects; check classification
        assert classify_pattern(r"(?:ab)+x") in (PatternTier.DFA, PatternTier.CPU)

    def test_anchors_stripped(self):
        assert_matches_re(r"^(\d+) (\w+)$", [b"12 abc", b"12 abc extra"])

    def test_padding_rows_do_not_match(self):
        ok, _, _ = run_kernel(r"(\d*)", [b"123"])
        assert ok[0]  # only real rows returned


class TestRandomDifferential:
    @pytest.mark.parametrize("pattern", [
        APACHE, NGINX_TIME, QUOTED,
        r"([^=]+)=(\S+)",
        r"\[([^\]]*)\] (\w+): (.*)",
        r"([0-9a-f]{8})-([0-9a-f]{4})",
        r"(\d+)\.(\d+)\.(\d+)\.(\d+)",
    ])
    def test_fuzz(self, pattern):
        rng = np.random.default_rng(hash(pattern) % 2**32)
        alphabet = b'abc0123456789 []"=.:-/\\xyz\n\t'
        lines = []
        for _ in range(300):
            n = int(rng.integers(0, 60))
            lines.append(bytes(alphabet[i] for i in rng.integers(0, len(alphabet), n)))
        # ensure at least some matching lines
        lines += [APACHE_LINE, b"2024-01-31T09:15:59", b'"q" t',
                  b"a=b", b"[x] w: rest", b"deadbeef-cafe", b"1.2.3.4"]
        assert_matches_re(pattern, lines)


class TestOptionalAndAlternation:
    def test_optional_group_http_version(self):
        # note [^ "] for the request: \S would need backtracking out of the
        # closing quote, which Tier-1 correctly rejects
        pattern = r'"(\w+) ([^ "]+)(?: HTTP/(\d\.\d))?" (\d{3})'
        assert_matches_re(pattern, [
            b'"GET /x HTTP/1.1" 200',
            b'"GET /x" 404',
            b'"GET /x HTTP/9" 200',      # malformed version -> no match
            b'"GET /x HTTP/1.1" 99',
        ])

    def test_alternation_literals(self):
        pattern = r"(GET|POST|DELETE) (\S+)"
        assert_matches_re(pattern, [
            b"GET /a", b"POST /b", b"DELETE /c", b"PATCH /d", b"GE /x",
        ])

    def test_alternation_class_and_literal(self):
        pattern = r"(\d+|-) (\w+)"
        assert_matches_re(pattern, [
            b"123 abc", b"- xyz", b"12- q", b" x",
        ])

    def test_literal_prefix_order_rejected(self):
        with pytest.raises(Tier1Unsupported):
            compile_tier1(r"(GET|GETX) .*")

    def test_literal_prefix_longest_first_ok(self):
        assert_matches_re(r"(GETX|GET) (\S+)", [
            b"GETX /a", b"GET /b", b"GETXY /c",
        ])

    def test_nested_optional(self):
        pattern = r"(\w+)(?:\.(\w+)(?:\.(\w+))?)? (\d+)"
        assert_matches_re(pattern, [
            b"a 1", b"a.b 2", b"a.b.c 3", b"a.b.c.d 4", b"a. 5",
        ])

    def test_capture_inside_alternation(self):
        pattern = r"(?:level=(\w+)|lvl:(\w+)) (.*)"
        assert_matches_re(pattern, [
            b"level=info started", b"lvl:warn hot", b"nope x",
        ])

    def test_common_apache_log_grok_shape(self):
        # the full COMMONAPACHELOG shape with optional HTTP version and
        # bytes-or-dash alternation — previously CPU tier, now Tier-1
        pattern = (r'(\S+) (\S+) (\S+) \[([^\]]+)\] '
                   r'"(\w+) ([^ "]+)(?: HTTP/([0-9.]+))?" (\d{3}) (\d+|-)')
        assert classify_pattern(pattern) == PatternTier.SEGMENT
        assert_matches_re(pattern, [
            APACHE_LINE,
            b'1.2.3.4 - - [t] "GET /x" 200 -',
            b'1.2.3.4 - - [t] "GET /x HTTP/1.1" 200 -',
            b'1.2.3.4 - - [t] "GET /x HTTP/1.1" 200 77',
        ])

    def test_fuzz_optional_alternation(self):
        import numpy as _np
        rng = _np.random.default_rng(7)
        alphabet = b'GETPOSDL -/19."x'
        patterns = [
            r"(GET|POST|DELETE) (\S+)",
            r"(\d+|-)",
            r'"(\w+)(?: ([^ "]+))?"',
            r"(\w+)(?:-(\d+))? end",
        ]
        for pattern in patterns:
            lines = [bytes(alphabet[i] for i in
                           rng.integers(0, len(alphabet), int(rng.integers(0, 24))))
                     for _ in range(400)]
            lines += [b"GET /a", b"-", b"9", b'"x y"', b'"x"', b"ab-1 end",
                   b"ab end"]
            assert_matches_re(pattern, lines)


class TestBidirectionalPivot:
    def test_greedy_span_before_optional_quote(self):
        # \S can eat the closing quote; the pivot + reverse suffix resolves
        pattern = r'"(\w+) (\S+)(?: HTTP/(\d\.\d))?" (\d{3})'
        prog = compile_tier1(pattern)
        assert prog.pivot is not None
        assert_matches_re(pattern, [
            b'"GET /x HTTP/1.1" 200',
            b'"GET /x" 404',
            b'"GET /x HTTP/9" 200',
            b'"GET /x.y" 301',
        ])

    def test_lazy_dot_with_digit_suffix(self):
        assert_matches_re(r"(.*?)(\d+)x", [
            b"ab123x", b"x", b"9x", b"abx", b"12x34x", b"xx9x",
        ])

    def test_greedy_pivot_trading_rejected(self):
        # greedy pivot + absorbable suffix span genuinely diverges — reject
        with pytest.raises(Tier1Unsupported):
            compile_tier1(r"(.*)(\d+)x")

    def test_split_capture_spans_pivot(self):
        assert_matches_re(r"\[(.*?)\] (\w+)", [
            b"[a] b", b"[a] [b] c", b"[] x", b"nope", b"[a][b] c",
        ])

    def test_pivot_fuzz(self):
        rng = np.random.default_rng(42)
        alphabet = b'ab1 "x[]/.'
        for pattern in [r'"(.*?)" (\S+)', r"(.*?)(\d+)x",
                        r"\[(.*?)\] (\w+)", r'(\S+) "(.*?)"']:
            lines = [bytes(alphabet[i] for i in
                           rng.integers(0, len(alphabet),
                                        int(rng.integers(0, 24))))
                     for _ in range(500)]
            lines += [b'"a" b', b'1x', b'[q] w', b'z "y"']
            assert_matches_re(pattern, lines)


class TestGrokCompositesTier1:
    def test_commonapachelog_differential(self):
        from loongcollector_tpu.ops.regex.grok import expand
        pattern = expand("%{COMMONAPACHELOG}")
        assert classify_pattern(pattern) == PatternTier.SEGMENT
        rng = np.random.default_rng(11)
        lines = []
        for i in range(300):
            ip = f"{rng.integers(1,255)}.{rng.integers(256)}.{rng.integers(256)}.{rng.integers(255)}"
            ver = ["", " HTTP/1.0", " HTTP/1.1", " HTTP/2"][int(rng.integers(4))]
            size = ["-", str(int(rng.integers(0, 10**6)))][int(rng.integers(2))]
            ln = (f'{ip} - u{i} [{int(rng.integers(1,32))}/Oct/2000:13:55:36 -0700] '
                  f'"GET /p{i}{ver}" {int(rng.integers(100,600))} {size}').encode()
            if i % 5 == 0:
                ln = ln.replace(b"Oct", b"Xxx")     # bad month
            if i % 7 == 0:
                ln = ln.replace(b'"GET', b'"GET WITH SPACE', 1)
            lines.append(ln)
        assert_matches_re(pattern, lines)

    def test_timestamp_iso8601_differential(self):
        from loongcollector_tpu.ops.regex.grok import expand
        pattern = expand("%{TIMESTAMP_ISO8601}")
        assert classify_pattern(pattern) == PatternTier.SEGMENT
        assert_matches_re(pattern, [
            b"2024-01-31T09:15:59Z", b"2024-01-31 09:15:59",
            b"2024-1-31T09:15:59+08:00", b"2024-13-31T09:15:59",
            b"2024-01-31T24:15:59", b"2024-01-31T9:15", b"garbage",
            b"99-01-31T09:15:59.123Z",
        ])

    def test_counted_group_repeat(self):
        assert_matches_re(r"((?:\d\d){1,2})x", [
            b"12x", b"1234x", b"123x", b"x", b"123456x",
        ])


class TestPivotReviewRegressions:
    def test_split_capture_keeps_prefix_content(self):
        # (a.*)x: the capture opens BEFORE the pivot — its left edge is the
        # forward CapStart position, not the pivot start
        assert_matches_re(r"(a.*)x", [b"abx", b"ax", b"aXYZx", b"bx"])

    def test_nested_branch_span_respects_continuation(self):
        # a+ at a branch tail must not steal the 'a' of the preceding '!a'
        for pat in [r"(.*?)!a(?:a+x|y)", r"(.*?)!a(?:(?:a|)x|y)"]:
            import re as _re
            try:
                prog = compile_tier1(pat)
            except Tier1Unsupported:
                continue  # rejection is also sound
            assert_matches_re(pat, [b"!aax", b"!ax", b"!ay", b"z!aax", b"!a"])
