"""loongstruct: structural-index JSON & delimiter parsing.

Layers under test (ISSUE 12):

1. mask equivalence — native `lct_struct_index`, the numpy twin, and the
   device kernel agree bit-for-bit with a brute-force Python reference
   (escape-carry across 64-bit word boundaries included);
2. differential goldens — parse_json vs Python `json`, parse_delimiter
   quote-mode vs the reference FSM and Python `csv`, on native AND
   numpy-fallback execution, over adversarial corpora;
3. the device kernel indexes a whole batch in ONE dispatch;
4. parse-fallback observability: counters, the one-shot
   PARSE_FALLBACK_DEGRADED alarm, /debug/status `parse` section;
5. an 8-seed chaos storm on a json→kafka chain with the live
   conservation ledger asserting residual == 0.
"""

import csv
import io
import json
import os
import time

import numpy as np
import pytest

from loongcollector_tpu import native as nat
from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
from loongcollector_tpu.monitor.alarms import AlarmManager, AlarmType
from loongcollector_tpu.ops.kernels import struct_index as si
from loongcollector_tpu.pipeline.plugin.interface import PluginContext
from loongcollector_tpu.processor import parse_telemetry
from loongcollector_tpu.processor.parse_delimiter import (
    ProcessorParseDelimiter, _csv_fsm_split)
from loongcollector_tpu.processor.parse_json import ProcessorParseJson
from loongcollector_tpu.processor.split_log_string import \
    ProcessorSplitLogString

NATIVE = nat.get_lib() is not None

pytestmark = []


@pytest.fixture(autouse=True)
def _telemetry_clean():
    parse_telemetry.reset_for_testing()
    AlarmManager.instance().flush()
    yield
    parse_telemetry.reset_for_testing()
    AlarmManager.instance().flush()


def pack(rows):
    blob = b"".join(rows)
    arena = np.frombuffer(blob, dtype=np.uint8) if blob \
        else np.zeros(0, np.uint8)
    lens = np.array([len(r) for r in rows], dtype=np.int32)
    offs = np.concatenate([[0], np.cumsum(lens[:-1])]).astype(np.int64) \
        if rows else np.zeros(0, np.int64)
    return blob, arena, offs, lens


def row_matrix(rows):
    lens = np.array([len(r) for r in rows], dtype=np.int32)
    L = max(1, int(lens.max()) if len(rows) else 1)
    mat = np.zeros((len(rows), L), dtype=np.uint8)
    for i, r in enumerate(rows):
        mat[i, : len(r)] = np.frombuffer(r, dtype=np.uint8)
    return mat, lens, L


def group_of(lines):
    data = b"\n".join(lines) + b"\n"
    sb = SourceBuffer(len(data) + 64)
    g = PipelineEventGroup(sb)
    g.add_raw_event(1).set_content(sb.copy_string(data))
    sp = ProcessorSplitLogString()
    sp.init({}, PluginContext("t"))
    sp.process(g)
    return g


# ---------------------------------------------------------------------------
# 1. mask equivalence vs a brute-force reference


def ref_masks(row: bytes, mode: str, sep: int = 0x2C):
    """Bit-level reference: escaped = simdjson odd-run-END semantics (a
    non-backslash byte preceded by an odd-length backslash run);
    in-string = inclusive parity of unescaped quotes."""
    n = len(row)
    esc = [False] * n
    if mode == si.MODE_JSON:
        run = 0
        for i, b in enumerate(row):
            if b != 0x5C and run % 2 == 1:
                esc[i] = True
            run = run + 1 if b == 0x5C else 0
    qreal = [row[i] == 0x22 and not esc[i] for i in range(n)]
    s = []
    par = 0
    for i in range(n):
        if qreal[i]:
            par ^= 1
        s.append(par == 1)
    structset = set(b'{}[]:,') if mode == si.MODE_JSON else {sep}
    st = [(row[i] in structset) and not s[i] for i in range(n)]

    def pack16(bits):
        W = (max(n, 1) + 15) // 16
        w = [0] * W
        for i, b in enumerate(bits):
            if b:
                w[i // 16] |= 1 << (i % 16)
        return w

    return [pack16(x) for x in (s, st, esc, qreal)]


def adversarial_rows():
    rows = [b'{"a": "b"}', b'', b'{}', b'\\"x', b'"unterm',
            b'a,b,"c,d",e', b'"a""b",c',
            b'{"k": "v\\nw", "n": [1, {"m": "x,y"}]}']
    for k in range(1, 10):
        rows.append(b'x' * (63 - k) + b'\\' * k + b'n"q"')
        rows.append(b'{"e": "' + b'x' * (55 - k) + b'\\' * k + b'n"}')
    rng = np.random.default_rng(21)
    for _ in range(250):
        L = int(rng.integers(0, 150))
        rows.append(bytes(rng.choice(
            list(b'ab\\",{}[]: \t'), size=L).astype(np.uint8)))
    return rows


class TestMaskEquivalence:
    @pytest.mark.parametrize("mode", [si.MODE_JSON, si.MODE_DELIM])
    def test_three_substrates_match_reference(self, mode):
        rows = adversarial_rows()
        mat, lens, L = row_matrix(rows)
        np16 = si.struct_index_numpy(mat, lens, mode=mode)
        kern = si.StructIndexKernel(mode=mode)
        dv = [np.asarray(x) for x in kern(mat, lens)]
        W16 = np16[0].shape[1]
        native16 = None
        if NATIVE:
            blob, arena, offs, lens2 = pack(rows)
            nm = nat.struct_index(
                arena, offs, lens2,
                mode=0 if mode == si.MODE_JSON else 1)
            native16 = [si.native_masks_as_words16(m)[:, :W16] for m in nm]
        for i, r in enumerate(rows):
            ref = ref_masks(r, mode)
            for mi, name in enumerate(
                    ("in_string", "structural", "escaped", "quote")):
                want = ref[mi]
                got_np = list(np16[mi][i][: len(want)])
                got_dv = list(dv[mi][i][: len(want)])
                assert got_np == want, (name, i, r)
                assert got_dv == want, (name, i, r)
                if native16 is not None:
                    got_nat = list(native16[mi][i][: len(want)])
                    assert got_nat == want, (name, i, r)

    def test_escape_carry_across_word_boundary(self):
        """Backslash runs ending exactly at bit 63: the carry must mark
        (or not mark) bit 0 of the next word by run parity."""
        odd = b'x' * 63 + b'\\' + b'n'       # run of 1 ends at the boundary
        even = b'x' * 62 + b'\\\\' + b'n'    # run of 2
        mat, lens, L = row_matrix([odd, even])
        _, _, esc, _ = si.struct_index_numpy(mat, lens, mode=si.MODE_JSON)
        bits = si.unpack16(esc, L)
        assert bits[0, 64] and not bits[1, 64]
        if NATIVE:
            blob, arena, offs, lens2 = pack([odd, even])
            nm = nat.struct_index(arena, offs, lens2, mode=0)
            assert int(nm[2][0, 1]) & 1 == 1
            assert int(nm[2][1, 1]) & 1 == 0


# ---------------------------------------------------------------------------
# 2. differential goldens


JSON_GOLDEN_ROWS = [
    b'{"ts": 1700000000, "level": "info", "user": "u1", "msg": "hi"}',
    b'{"ts": 1, "level": "in\\nfo", "user": "u\\u00e9", "msg": "\\"q\\""}',
    b'{"ts": 2, "level": "\\u4f60\\u597d", "user": "\\ud83d\\ude00",'
    b' "msg": "\\\\net\\\\share"}',
    b'{"ts": 3, "drifted_key": "boom", "level": "x"}',
    b'{"nested": {"a": [1, 2, {"b": "c,{}"}]}, "ts": 4}',
    b'{"ts": bad}', b'not json', b'{}', b'{"a": "unterminated',
    b'{"dup": 1, "dup": 2}', b'{"a": true, "b": null, "c": false}',
    b'{"e": "' + b'\\\\' * 33 + b'"}',
    b'{"e": "' + b'x' * 55 + b'\\\\\\"' + b'"}',
    b'{"sp" :  "v"  ,  "n" : -1.5e3  }',
    b'{"a": 1} trailing', b'{"a": 01}', b'{"a"::1}',
]


def _parse_json_group(lines, pipeline="gold"):
    g = group_of(lines)
    pj = ProcessorParseJson()
    pj.init({}, PluginContext(pipeline))
    pj.process(g)
    return [{str(k): str(v) for k, v in ev.contents if str(k) != "rawLog"}
            for ev in g.events]


def _assert_json_golden(got_rows, lines):
    for i, r in enumerate(lines):
        got = got_rows[i]
        try:
            obj = json.loads(r)
            ok = isinstance(obj, dict)
        except Exception:  # noqa: BLE001
            ok = False
        if not ok:
            assert not got, (i, r, got)
            continue
        assert set(got) == {str(k) for k in obj}, (i, r, got)
        for k, v in obj.items():
            if isinstance(v, str):
                assert got[k] == v, (i, r, k)
            elif isinstance(v, bool):
                assert got[k] == ("true" if v else "false")
            elif v is None:
                assert got[k] == "null"
            elif isinstance(v, (dict, list)):
                assert json.loads(got[k]) == v, (i, r, k)


class TestJsonGoldens:
    def test_struct_plane_matches_python_json(self):
        lines = [r for r in JSON_GOLDEN_ROWS if b"\n" not in r]
        _assert_json_golden(_parse_json_group(lines), lines)

    def test_numpy_fallback_execution_matches(self, monkeypatch):
        """Without the native library the processor runs the r09-style /
        per-row tier — output must be identical."""
        lines = [r for r in JSON_GOLDEN_ROWS if b"\n" not in r]
        want = _parse_json_group(lines)
        monkeypatch.setenv("LOONG_DISABLE_NATIVE", "1")
        monkeypatch.setattr(nat, "_lib", None)
        monkeypatch.setattr(nat, "_load_attempted", False)
        try:
            got = _parse_json_group(lines)
        finally:
            monkeypatch.setenv("LOONG_DISABLE_NATIVE", "")
            monkeypatch.setattr(nat, "_lib", None)
            monkeypatch.setattr(nat, "_load_attempted", False)

        def norm(rows):
            # numbers: the struct plane keeps raw source spelling, the
            # fallback canonicalises via str() — the documented contract;
            # compare them numerically, everything else byte-exact
            out = []
            for row in rows:
                nr = {}
                for k, v in row.items():
                    try:
                        nr[k] = float(v)
                    except ValueError:
                        nr[k] = v
                out.append(nr)
            return out

        assert norm(got) == norm(want)

    @pytest.mark.skipif(not NATIVE, reason="native library unavailable")
    def test_side_arena_appended_once_not_per_event(self):
        """Escape-bearing rows stay columnar: decoded bytes land in ONE
        side-arena append, and the group never materializes."""
        from loongcollector_tpu import models as models_mod
        lines = [b'{"m": "a\\n%d"}' % i for i in range(64)]
        g = group_of(lines)
        sb_size_before = g.source_buffer.size
        models_mod.reset_churn_stats()
        pj = ProcessorParseJson()
        pj.init({}, PluginContext("side"))
        pj.process(g)
        churn = models_mod.churn_stats()
        assert churn["materialized_events"] == 0
        # decoded values live in the arena tail, one allocation's worth
        cols = g.columns
        offs, lens = cols.fields["m"]
        assert (lens >= 0).all()
        assert (offs >= sb_size_before).all()
        vals = [bytes(g.source_buffer.raw[int(o): int(o) + int(ln)])
                for o, ln in zip(offs, lens)]
        assert vals == [b"a\n%d" % i for i in range(64)]

    @pytest.mark.skipif(not NATIVE, reason="native library unavailable")
    def test_schema_drift_stays_columnar(self):
        from loongcollector_tpu import models as models_mod
        lines = [b'{"a": "x", "b": "y"}'] * 8 + \
                [b'{"a": "x", "b": "y", "c%d": "z"}' % i for i in range(4)]
        g = group_of(lines)
        models_mod.reset_churn_stats()
        pj = ProcessorParseJson()
        pj.init({}, PluginContext("drift"))
        pj.process(g)
        assert models_mod.churn_stats()["materialized_events"] == 0
        cols = g.columns
        assert cols.parse_ok.all()
        for i in range(4):
            offs, lens = cols.fields["c%d" % i]
            assert int(lens[8 + i]) == 1
        st = parse_telemetry.status()
        row = st["processor_parse_json_tpu/drift"]
        assert row["drift_rows"] == 4 and row["fallback_rows"] == 0


CSV_GOLDEN_ROWS = [
    b'a,b,c', b'"a,b",c,d', b'"a""b",c,x', b'a"b,c"d,e', b'"x"tail,y,z',
    b'"unterminated, z', b'', b',', b'a,,b', b'"",x,y', b'""a,b,c',
    b'"a","b","c","d"', b'"dq""""x",y,w', b'p,q,r,s,extra1,extra2',
]


def _parse_delim_group(lines, keys=("k1", "k2", "k3"), pipeline="csv"):
    g = group_of(lines)
    pd = ProcessorParseDelimiter()
    pd.init({"Keys": list(keys), "Mode": "quote"}, PluginContext(pipeline))
    pd.process(g)
    return [{str(k): str(v) for k, v in ev.contents if str(k) != "rawLog"}
            for ev in g.events]


class TestDelimiterGoldens:
    def test_quote_mode_matches_fsm_and_csv(self):
        got = _parse_delim_group(CSV_GOLDEN_ROWS)
        for i, r in enumerate(CSV_GOLDEN_ROWS):
            fields = _csv_fsm_split(r, b",")
            if len(fields) < 3:
                assert not got[i], (i, r, got[i])
                continue
            if len(fields) > 3:
                fields = fields[:2] + [b",".join(fields[2:])]
            want = {"k%d" % (j + 1): fields[j].decode("utf-8", "replace")
                    for j in range(3)}
            assert got[i] == want, (i, r)
            # python csv agreement on RFC4180-clean rows
            text = r.decode()
            if '"' not in text.replace('","', ',').strip('"'):
                try:
                    pycsv = next(csv.reader(io.StringIO(text)))
                except (csv.Error, StopIteration):
                    continue
                if len(pycsv) == len(_csv_fsm_split(r, b",")):
                    merged = pycsv[:2] + [",".join(pycsv[2:])] \
                        if len(pycsv) > 3 else pycsv
                    assert [want["k%d" % (j + 1)] for j in range(3)] \
                        == merged[:3], (i, r)

    def test_numpy_tier_matches_native(self, monkeypatch):
        want = _parse_delim_group(CSV_GOLDEN_ROWS)
        monkeypatch.setenv("LOONG_DISABLE_NATIVE", "1")
        monkeypatch.setattr(nat, "_lib", None)
        monkeypatch.setattr(nat, "_load_attempted", False)
        try:
            got = _parse_delim_group(CSV_GOLDEN_ROWS)
        finally:
            monkeypatch.setenv("LOONG_DISABLE_NATIVE", "")
            monkeypatch.setattr(nat, "_lib", None)
            monkeypatch.setattr(nat, "_load_attempted", False)
        assert got == want

    @pytest.mark.skipif(not NATIVE, reason="native library unavailable")
    def test_well_formed_input_zero_per_row_python(self):
        """Clean quote-mode CSV through the native plane: zero fallback
        rows counted, zero per-event materialization."""
        from loongcollector_tpu import models as models_mod
        lines = [b'srv%d,"us-east,1a",GET,"p%d"' % (i % 9, i)
                 for i in range(256)]
        g = group_of(lines)
        models_mod.reset_churn_stats()
        pd = ProcessorParseDelimiter()
        pd.init({"Keys": ["a", "b", "c", "d"], "Mode": "quote"},
                PluginContext("clean"))
        pd.process(g)
        assert models_mod.churn_stats()["materialized_events"] == 0
        assert g.columns.parse_ok.all()
        st = parse_telemetry.status()
        assert st["processor_parse_delimiter_tpu/clean"][
            "fallback_rows"] == 0


# ---------------------------------------------------------------------------
# 3. device: one dispatch per batch


class TestDeviceSingleDispatch:
    def test_index_batch_is_one_kernel_invocation(self):
        lines = [b'{"a": "v%d", "n": %d}' % (i, i) for i in range(128)]
        blob, arena, offs, lens = pack(lines)
        kern = si.StructIndexKernel(mode=si.MODE_JSON)
        out = kern.index_batch(arena, offs, lens)
        assert out is not None
        masks, L = out
        assert kern.dispatch_count == 1, (
            "a batch structural index must be ONE device dispatch")
        assert masks[0].shape[0] == len(lines)
        # and the dispatched masks equal the numpy twin's
        mat = np.zeros((len(lines), L), dtype=np.uint8)
        for i, r in enumerate(lines):
            mat[i, : len(r)] = np.frombuffer(r, dtype=np.uint8)
        np16 = si.struct_index_numpy(mat, lens, mode=si.MODE_JSON)
        for a, b in zip(masks, np16):
            assert np.array_equal(np.asarray(a), b)


# ---------------------------------------------------------------------------
# 4. fallback observability


class TestFallbackObservability:
    @pytest.mark.skipif(not NATIVE, reason="native library unavailable")
    def test_counters_and_one_shot_alarm(self, monkeypatch):
        monkeypatch.setattr(parse_telemetry, "MIN_ROWS", 64)
        good = b'{"a": "x", "b": 1}'
        bad = b'{"a": broken'
        lines = [good if i % 2 else bad for i in range(128)]
        g = group_of(lines)
        pj = ProcessorParseJson()
        pj.init({}, PluginContext("storm-pipe"))
        pj.process(g)
        st = parse_telemetry.status()
        row = st["processor_parse_json_tpu/storm-pipe"]
        assert row["rows"] == 128
        assert row["fallback_rows"] == 64
        assert row["degraded"] is True
        alarms = [a for a in AlarmManager.instance().flush()
                  if a["alarm_type"]
                  == AlarmType.PARSE_FALLBACK_DEGRADED.value]
        assert len(alarms) == 1
        assert alarms[0]["pipeline"] == "storm-pipe"
        assert "processor_parse_json_tpu" in alarms[0]["alarm_message"]
        # one-shot: a second degraded group must not re-alarm
        g2 = group_of(lines)
        pj.process(g2)
        assert not [a for a in AlarmManager.instance().flush()
                    if a["alarm_type"]
                    == AlarmType.PARSE_FALLBACK_DEGRADED.value]

    def test_status_page_section(self):
        parse_telemetry.note_rows("processor_parse_json_tpu", "p1", 100, 3)
        from loongcollector_tpu.monitor.exposition import collect_status
        doc = collect_status()
        assert "parse" in doc
        row = doc["parse"]["processor_parse_json_tpu/p1"]
        assert row == {"rows": 100, "fallback_rows": 3, "drift_rows": 0,
                       "degraded": False}


# ---------------------------------------------------------------------------
# 5. equivalence gate (the scripts/struct_equivalence.py contract, run
#    in-process on every tier-1 invocation)


class TestEquivalenceGate:
    def test_gate_passes(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "struct_equivalence",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "scripts",
                "struct_equivalence.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main() == 0


# ---------------------------------------------------------------------------
# 6. 8-seed chaos storm: json → kafka with the live ledger


STORM_SEEDS = (3, 7, 11, 23, 42, 97, 1337, 20240804)


def _drive_json_kafka_storm(seed, broker_port, n_groups=6, rows_per=16):
    from loongcollector_tpu import chaos
    from loongcollector_tpu.chaos import ChaosPlan, FaultSpec
    from loongcollector_tpu.monitor import ledger
    from loongcollector_tpu.pipeline.pipeline_manager import (
        CollectionPipelineManager, ConfigDiff)
    from loongcollector_tpu.pipeline.queue.process_queue_manager import \
        ProcessQueueManager
    from loongcollector_tpu.pipeline.queue.sender_queue import \
        SenderQueueManager
    from loongcollector_tpu.runner.processor_runner import ProcessorRunner

    ledger.enable()
    ledger.reset()
    pqm = ProcessQueueManager()
    mgr = CollectionPipelineManager(pqm, SenderQueueManager())
    runner = ProcessorRunner(pqm, mgr, thread_count=2)
    runner.init()
    name = f"jk{seed}"
    diff = ConfigDiff()
    diff.added[name] = {
        "inputs": [{"Type": "input_static_file_onetime",
                    "FilePaths": ["/nonexistent"]}],
        "global": {"ProcessQueueCapacity": 64},
        "processors": [{"Type": "processor_parse_json_tpu"}],
        "flushers": [{"Type": "flusher_kafka",
                      "Brokers": [f"127.0.0.1:{broker_port}"],
                      "Topic": "logs", "MinCnt": 4, "MinSizeBytes": 1,
                      "MaxRetries": 8}],
    }
    mgr.update_pipelines(diff)
    p = mgr.find_pipeline(name)
    total = 0
    try:
        chaos.install(ChaosPlan(seed, {
            "kafka_client.produce": FaultSpec(
                prob=0.4, kinds=(chaos.ACTION_ERROR, chaos.ACTION_DELAY),
                delay_range=(0.001, 0.004), max_faults=10)}))
        for gi in range(n_groups):
            lines = b"\n".join(
                b'{"seq": %d, "msg": "m\\n%d", "src": "s%d"}'
                % (gi * rows_per + j, j, seed)
                for j in range(rows_per)) + b"\n"
            sb = SourceBuffer(len(lines) + 64)
            g = PipelineEventGroup(sb)
            g.add_raw_event(1).set_content(sb.copy_string(lines))
            deadline = time.monotonic() + 20
            while not pqm.push_queue(p.process_queue_key, g):
                assert time.monotonic() < deadline
                time.sleep(0.002)
            total += rows_per
        snap = ledger.assert_conserved(timeout=45,
                                       label=f"seed {seed} json→kafka")
        row = snap[name]
        assert row[ledger.B_SEND_OK]["events"] == total
        assert ledger.B_DROP not in row
        assert ledger.residual_of(row) == 0
    finally:
        chaos.uninstall()
        runner.stop()
        mgr.stop_all()
        ledger.disable()
    return total


@pytest.mark.parametrize("seed", STORM_SEEDS)
def test_json_kafka_storm_conserves(seed):
    from test_kafka import FakeBroker
    broker = FakeBroker()
    broker.start()
    try:
        total = _drive_json_kafka_storm(seed, broker.port)
        assert total > 0
        assert len(broker.produced) > 0
    finally:
        broker.stop()
