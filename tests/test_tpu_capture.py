"""DEAD→ALIVE capture trigger + instant watcher-fed backend routing.

VERDICT r4 #2: the /tmp/tpu_alive liveness signal must DO something —
`ensure_live_backend` answers instantly from it, and the watcher's
DEAD→ALIVE transition drives the full capture (pallas smoke + bench +
dryrun) with no human.  These tests dry-run that whole trigger path with
injected subprocess runners — no hardware needed.
"""

import json
import os
import subprocess
import time

import pytest

from loongcollector_tpu.utils import backend
from loongcollector_tpu.utils.tpu_capture import (PALLAS_SMOKE_CODE,
                                                  TransitionTracker, capture,
                                                  pallas_smoke, run_bench)


class TestTransitionTracker:
    def test_fires_on_dead_to_alive(self):
        t = TransitionTracker()
        assert not t.update(False)
        assert t.update(True)          # dead -> alive
        assert not t.update(True)      # still alive: no refire
        assert not t.update(False)
        assert t.update(True)          # second window fires again

    def test_first_observation_alive_fires(self):
        # a watcher restarted INSIDE an availability window must not waste it
        t = TransitionTracker()
        assert t.update(True)


class TestWatcherVerdict:
    @pytest.fixture(autouse=True)
    def fresh_probe_cache(self, monkeypatch, tmp_path):
        monkeypatch.setattr(backend, "_probe_result", None)
        monkeypatch.setenv("LOONG_TPU_ALIVE_FILE", str(tmp_path / "alive"))
        monkeypatch.setenv("LOONG_TPU_WATCH_LOG", str(tmp_path / "watch.log"))
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        monkeypatch.delenv("LOONG_BACKEND", raising=False)
        yield tmp_path

    def test_verdicts(self, fresh_probe_cache):
        tmp = fresh_probe_cache
        assert backend.watcher_verdict() == "unknown"
        (tmp / "watch.log").write_text("12:00:00 DEAD\n")
        assert backend.watcher_verdict() == "dead"
        (tmp / "alive").touch()
        assert backend.watcher_verdict() == "alive"
        # stale alive file + fresh log -> dead again
        old = time.time() - 3600
        os.utime(tmp / "alive", (old, old))
        assert backend.watcher_verdict() == "dead"

    def test_probe_instant_when_watcher_alive(self, fresh_probe_cache,
                                              monkeypatch):
        (fresh_probe_cache / "alive").touch()

        def forbidden(*a, **k):  # pragma: no cover - must not be reached
            raise AssertionError("subprocess probe ran despite alive file")

        monkeypatch.setattr(backend.subprocess, "run", forbidden)
        t0 = time.perf_counter()
        assert backend.probe_default_backend() is True
        assert time.perf_counter() - t0 < 0.5

    def test_probe_instant_when_watcher_dead(self, fresh_probe_cache,
                                             monkeypatch):
        (fresh_probe_cache / "watch.log").write_text("12:00:00 DEAD\n")

        def forbidden(*a, **k):  # pragma: no cover
            raise AssertionError("90s probe ran despite dead verdict")

        monkeypatch.setattr(backend.subprocess, "run", forbidden)
        t0 = time.perf_counter()
        assert backend.probe_default_backend() is False
        assert time.perf_counter() - t0 < 0.5


class _FakeRun:
    """Records subprocess invocations; scripted stdout per matcher."""

    def __init__(self, outputs):
        self.outputs = outputs     # list of (substr, rc, stdout)
        self.calls = []

    def __call__(self, argv, **kw):
        self.calls.append(argv)
        joined = " ".join(argv)
        for substr, rc, stdout in self.outputs:
            if substr in joined:
                return subprocess.CompletedProcess(argv, rc, stdout, "")
        return subprocess.CompletedProcess(argv, 1, "", "unmatched")


class TestCaptureDryRun:
    def test_full_capture_payload(self, tmp_path):
        fake = _FakeRun([
            ("PallasExtractKernel", 0, 'PALLAS_OK {"MBps": 512.5}\n'),
            ("bench.py", 0, json.dumps(
                {"metric": "regex_parse_throughput", "value": 700.0,
                 "unit": "MB/s", "vs_baseline": 10.0,
                 "extra": {"device": "TPU v5 lite0",
                           "device_degraded": False}}) + "\n"),
            ("dryrun_multichip", 0, "DRYRUN_OK\n"),
        ])
        logs = []
        summary = capture(run=fake, log=logs.append, repo=str(tmp_path))
        assert summary["pallas"] == {"ok": True, "MBps": 512.5}
        assert summary["bench"]["ok"] and not summary["bench"]["degraded"]
        assert summary["bench"]["value"] == 700.0
        assert summary["dryrun_multichip"]["ok"]
        # all three stages actually invoked
        assert len(fake.calls) == 3
        persisted = json.loads((tmp_path / "TPU_CAPTURE_LAST.json").read_text())
        assert persisted["pallas"]["MBps"] == 512.5

    def test_pallas_failure_recorded_not_fatal(self, tmp_path):
        fake = _FakeRun([
            ("PallasExtractKernel", 1, ""),
            ("bench.py", 0, json.dumps(
                {"value": 1.0, "extra": {"device_degraded": True}}) + "\n"),
            ("dryrun_multichip", 0, "DRYRUN_OK\n"),
        ])
        summary = capture(run=fake, log=lambda *_: None, repo=str(tmp_path))
        assert summary["pallas"]["ok"] is False
        assert summary["bench"]["degraded"] is True
        assert summary["dryrun_multichip"]["ok"]

    def test_smoke_code_is_valid_python(self):
        compile(PALLAS_SMOKE_CODE, "<pallas-smoke>", "exec")

    def test_pallas_smoke_timeout_is_soft(self):
        def hang(*a, **k):
            raise subprocess.TimeoutExpired("x", 900)

        out = pallas_smoke(run=hang)
        assert out["ok"] is False and "TimeoutExpired" in out["error"]

    def test_bench_parse_rejects_garbage(self):
        fake = _FakeRun([("bench.py", 0, "not json at all\n")])
        out = run_bench(run=fake)
        assert out["ok"] is False
