"""Native C++ data plane: differential tests vs the Python fallbacks."""

import numpy as np
import pytest

import loongcollector_tpu.native as native
from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
from loongcollector_tpu.pipeline.serializer.sls_serializer import \
    SLSEventGroupSerializer

pytestmark = pytest.mark.skipif(native.get_lib() is None,
                                reason="native library unavailable")


class TestSplitLines:
    @pytest.mark.parametrize("data", [
        b"a\nbb\nccc\n", b"a\nbb", b"\n\n", b"a\n\nb\n", b"single",
        b"trailing\n",
    ])
    def test_matches_python(self, data):
        seg = np.frombuffer(data, dtype=np.uint8)
        offs, lens = native.split_lines(seg, ord("\n"), 100)
        # python reference
        nl = np.nonzero(seg == ord("\n"))[0].astype(np.int64)
        starts = np.concatenate([[0], nl + 1])
        ends = np.concatenate([nl, [len(seg)]])
        if len(starts) > 1 and starts[-1] >= len(seg):
            starts, ends = starts[:-1], ends[:-1]
        assert list(offs) == list(starts + 100)
        assert list(lens) == list(ends - starts)


class TestPackRows:
    def test_matches_numpy(self):
        rng = np.random.default_rng(3)
        arena = rng.integers(1, 255, 1000, dtype=np.uint8)
        offsets = np.array([0, 100, 950], dtype=np.int64)
        lengths = np.array([50, 300, 50], dtype=np.int32)
        rows = native.pack_rows(arena, offsets, lengths, L=128, B=8)
        assert rows.shape == (8, 128)
        assert bytes(rows[0, :50].tobytes()) == bytes(arena[:50].tobytes())
        assert (rows[0, 50:] == 0).all()
        # length > L truncates
        assert bytes(rows[1].tobytes()) == bytes(arena[100:228].tobytes())
        # padding rows zero
        assert (rows[3:] == 0).all()


class TestSlsSerialize:
    def test_matches_python_serializer(self, monkeypatch):
        g = PipelineEventGroup()
        sb = g.source_buffer
        data = b"alpha beta\ngamma delta\n"
        sb.copy_string(data)
        from loongcollector_tpu.models import ColumnarLogs
        cols = ColumnarLogs(np.array([0, 11]), np.array([10, 11]),
                            np.array([1700000001, 1700000002]))
        v = sb.copy_string(b"value-x")
        cols.set_field("f1", np.array([0, v.offset]), np.array([5, v.length]))
        cols.set_field("f2", np.array([6, 0]), np.array([4, -1]))  # absent 2nd
        cols.content_consumed = True
        g.set_columns(cols)
        ser = SLSEventGroupSerializer()
        native_bytes = ser.serialize([g])
        # force the python fallback and compare
        monkeypatch.setattr(native, "sls_serialize",
                            lambda *a, **k: None)
        python_bytes = ser.serialize([g])
        assert native_bytes == python_bytes

    def test_content_column_included(self, monkeypatch):
        g = PipelineEventGroup()
        sb = g.source_buffer
        sb.copy_string(b"line-one\n")
        from loongcollector_tpu.models import ColumnarLogs
        cols = ColumnarLogs(np.array([0]), np.array([8]), np.array([1700000000]))
        v = sb.copy_string(b"extra")
        cols.set_field("tagf", np.array([v.offset]), np.array([v.length]))
        g.set_columns(cols)  # content NOT consumed
        ser = SLSEventGroupSerializer()
        native_bytes = ser.serialize([g])
        monkeypatch.setattr(native, "sls_serialize", lambda *a, **k: None)
        assert native_bytes == ser.serialize([g])
        assert b"line-one" in native_bytes
