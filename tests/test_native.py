"""Native C++ data plane: differential tests vs the Python fallbacks."""

import numpy as np
import pytest

import loongcollector_tpu.native as native
from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
from loongcollector_tpu.pipeline.serializer.sls_serializer import \
    SLSEventGroupSerializer

pytestmark = pytest.mark.skipif(native.get_lib() is None,
                                reason="native library unavailable")


class TestSplitLines:
    @pytest.mark.parametrize("data", [
        b"a\nbb\nccc\n", b"a\nbb", b"\n\n", b"a\n\nb\n", b"single",
        b"trailing\n",
    ])
    def test_matches_python(self, data):
        seg = np.frombuffer(data, dtype=np.uint8)
        offs, lens = native.split_lines(seg, ord("\n"), 100)
        # python reference
        nl = np.nonzero(seg == ord("\n"))[0].astype(np.int64)
        starts = np.concatenate([[0], nl + 1])
        ends = np.concatenate([nl, [len(seg)]])
        if len(starts) > 1 and starts[-1] >= len(seg):
            starts, ends = starts[:-1], ends[:-1]
        assert list(offs) == list(starts + 100)
        assert list(lens) == list(ends - starts)


class TestPackRows:
    def test_matches_numpy(self):
        rng = np.random.default_rng(3)
        arena = rng.integers(1, 255, 1000, dtype=np.uint8)
        offsets = np.array([0, 100, 950], dtype=np.int64)
        lengths = np.array([50, 300, 50], dtype=np.int32)
        rows = native.pack_rows(arena, offsets, lengths, L=128, B=8)
        assert rows.shape == (8, 128)
        assert bytes(rows[0, :50].tobytes()) == bytes(arena[:50].tobytes())
        assert (rows[0, 50:] == 0).all()
        # length > L truncates
        assert bytes(rows[1].tobytes()) == bytes(arena[100:228].tobytes())
        # padding rows zero
        assert (rows[3:] == 0).all()


class TestSlsSerialize:
    def test_matches_python_serializer(self, monkeypatch):
        g = PipelineEventGroup()
        sb = g.source_buffer
        data = b"alpha beta\ngamma delta\n"
        sb.copy_string(data)
        from loongcollector_tpu.models import ColumnarLogs
        cols = ColumnarLogs(np.array([0, 11]), np.array([10, 11]),
                            np.array([1700000001, 1700000002]))
        v = sb.copy_string(b"value-x")
        cols.set_field("f1", np.array([0, v.offset]), np.array([5, v.length]))
        cols.set_field("f2", np.array([6, 0]), np.array([4, -1]))  # absent 2nd
        cols.content_consumed = True
        g.set_columns(cols)
        ser = SLSEventGroupSerializer()
        native_bytes = ser.serialize([g])
        # force the python fallback and compare
        monkeypatch.setattr(native, "sls_serialize",
                            lambda *a, **k: None)
        python_bytes = ser.serialize([g])
        assert native_bytes == python_bytes

    def test_content_column_included(self, monkeypatch):
        g = PipelineEventGroup()
        sb = g.source_buffer
        sb.copy_string(b"line-one\n")
        from loongcollector_tpu.models import ColumnarLogs
        cols = ColumnarLogs(np.array([0]), np.array([8]), np.array([1700000000]))
        v = sb.copy_string(b"extra")
        cols.set_field("tagf", np.array([v.offset]), np.array([v.length]))
        g.set_columns(cols)  # content NOT consumed
        ser = SLSEventGroupSerializer()
        native_bytes = ser.serialize([g])
        monkeypatch.setattr(native, "sls_serialize", lambda *a, **k: None)
        assert native_bytes == ser.serialize([g])
        assert b"line-one" in native_bytes


class TestNativeJsonExtract:
    def _run(self, lines, keys):
        blob = b"".join(lines)
        arena = np.frombuffer(blob, np.uint8)
        lens = np.array([len(l) for l in lines], np.int32)
        offs = np.concatenate([[0], np.cumsum(lens[:-1])]).astype(np.int64)
        return native.json_extract(arena, offs, lens, keys), arena

    def test_scalar_spans(self):
        lines = [b'{"a": 1, "b": "x", "c": true, "d": null, "e": -1.5e3}']
        (offs, lens, ok, fb), arena = self._run(lines, [b"a", b"b", b"c",
                                                        b"d", b"e"])
        assert ok[0] and not fb[0]
        def val(f):
            return bytes(arena[offs[f,0]:offs[f,0]+lens[f,0]].tobytes())
        assert val(0) == b"1"
        assert val(1) == b"x"
        assert val(2) == b"true"
        assert val(3) == b"null"
        assert val(4) == b"-1.5e3"

    def test_nested_raw_span(self):
        lines = [b'{"o": {"x": [1, "}"]}, "t": "y"}']
        (offs, lens, ok, fb), arena = self._run(lines, [b"o", b"t"])
        assert ok[0]
        raw = bytes(arena[offs[0,0]:offs[0,0]+lens[0,0]].tobytes())
        assert raw == b'{"x": [1, "}"]}'

    def test_escape_falls_back(self):
        lines = [b'{"a": "has \\" quote"}', b'{"a": "plain"}']
        (offs, lens, ok, fb), arena = self._run(lines, [b"a"])
        assert fb[0] and not ok[0]
        assert ok[1] and not fb[1]

    def test_unknown_key_falls_back(self):
        lines = [b'{"a": 1, "zz": 2}']
        (offs, lens, ok, fb), _ = self._run(lines, [b"a"])
        assert fb[0]

    def test_malformed_falls_back(self):
        lines = [b'{"a": }', b'not json', b'[1,2]', b'{}']
        (offs, lens, ok, fb), _ = self._run(lines, [b"a"])
        assert fb[0] and fb[1] and fb[2]
        assert ok[3]  # empty object is fine

    def test_processor_mixed_fastpath_and_fallback(self):
        from loongcollector_tpu.pipeline.plugin.interface import PluginContext
        from loongcollector_tpu.processor.parse_json import ProcessorParseJson
        from loongcollector_tpu.processor.split_log_string import \
            ProcessorSplitLogString
        from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
        data = (b'{"k": "v1", "n": 1}\n'
                b'{"k": "esc\\"aped", "n": 2}\n'     # fallback (escape)
                b'{"k": "v3", "n": 3, "extra": 9}\n'  # fallback (new key)
                b'broken\n')
        sb = SourceBuffer(len(data) + 64)
        view = sb.copy_string(data)
        g = PipelineEventGroup(sb)
        g.add_raw_event(1).set_content(view)
        ctx = PluginContext("t")
        sp = ProcessorSplitLogString(); sp.init({}, ctx); sp.process(g)
        pj = ProcessorParseJson(); pj.init({}, ctx); pj.process(g)
        evs = g.materialize()
        assert evs[0].get_content(b"k") == b"v1"
        assert evs[1].get_content(b"k") == b'esc"aped'   # unescaped via host
        assert evs[2].get_content(b"extra") == b"9"
        assert evs[3].get_content(b"rawLog") == b"broken"

    def test_strict_rejections(self):
        lines = [b'{} trailing', b'{"a": truX}', b'{"a": {]}}',
                 b'{"a": 01}', b'{"a": 1.}', b'{"a": 1e}', b'{"a": -0.5e+2}']
        (offs, lens, ok, fb), _ = self._run(lines, [b"a"])
        assert fb[0] and fb[1] and fb[2] and fb[3] and fb[4] and fb[5]
        assert ok[6]  # valid exotic number stays fast-path

    def test_control_char_falls_back(self):
        lines = [b'{"a": "x\x01y"}', b'{"a": "clean"}']
        (offs, lens, ok, fb), _ = self._run(lines, [b"a"])
        assert fb[0] and not ok[0]  # host json.loads also rejects this
        assert ok[1]
