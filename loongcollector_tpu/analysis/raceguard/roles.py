"""Thread-role graph: which thread families can execute each function.

Entry points are seeded from every way this codebase starts concurrent
execution, classified into a small set of role families, then propagated
along the call graph: if a worker thread can execute ``f`` and ``f`` calls
``g``, a worker thread can execute ``g``.

Seed families (docs/static_analysis.md#race-detection):

  * ``threading.Thread(target=X)`` / ``threading.Timer(_, X)`` ctors —
    the target resolves through the call-graph resolver, and the family
    comes from the entry's module/name (worker loops in
    runner/processor_runner, flusher senders in runner// flusher/,
    watcher pumps in config//container_manager, timer pumps in monitor/,
    the profiler sampler in prof/, input readers in input/);
  * ``run()`` on classes deriving from ``threading.Thread``;
  * ``do_*`` methods on ``BaseHTTPRequestHandler`` subclasses (the
    exposition server and HTTP inputs are threading servers: every
    request is its own thread) — family ``http``;
  * ``signal.signal(SIG, handler)`` registrations — family ``signal``;
  * lifecycle methods (``start``/``stop``/``shutdown``) and module-level
    functions of application.py — family ``main``.

A function reached by no seed is assumed main-thread only
(``effective_roles`` returns {'main'}).  MULTI_INSTANCE families run more
than one thread at once (N worker shards, thread-per-request HTTP,
per-connection input loops), so shared state touched from a single such
family is still concurrent with itself.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Tuple

from ..core import Program, call_name
from .callgraph import CallGraph, FuncInfo, _own_nodes

ROLE_WORKER = "worker"
ROLE_FLUSHER = "flusher"
ROLE_WATCHER = "watcher"
ROLE_TIMER = "timer"
ROLE_HTTP = "http"
ROLE_PROFILER = "profiler"
ROLE_SIGNAL = "signal"
ROLE_INPUT = "input"
ROLE_THREAD = "thread"
ROLE_MAIN = "main"

#: families that run >1 thread concurrently, so shared state is racy even
#: within the single family: N worker shards, thread-per-request HTTP,
#: and the flusher plane (runner thread + retry thread + async senders).
#: ``input`` is deliberately NOT here: one reader loop per plugin
#: instance is the norm, and flagging a loop against itself drowned the
#: report in single-thread noise.
MULTI_INSTANCE = frozenset((ROLE_WORKER, ROLE_HTTP, ROLE_FLUSHER))

_THREAD_CTORS = {"threading.Thread", "Thread"}
_TIMER_CTORS = {"threading.Timer", "Timer"}
_MAIN_METHODS = {"start", "stop", "shutdown"}
_HTTP_HANDLER_BASES = {"BaseHTTPRequestHandler",
                       "SimpleHTTPRequestHandler"}


def classify_entry(fi: FuncInfo, thread_name: str = "") -> str:
    """Role family for a thread entry function, by module path first and
    entry/thread name second."""
    rel = fi.relpath
    low = (fi.qualname + " " + thread_name).lower()
    if "/prof/" in rel or rel.endswith("profiler.py") \
            or "profiler" in low or "sampler" in low:
        return ROLE_PROFILER
    if rel.endswith(("monitor/watchdog.py", "monitor/ledger.py",
                     "monitor/self_monitor.py")) \
            or "watchdog" in low or "timer" in low or "timeout" in low \
            or "flush_loop" in low:
        return ROLE_TIMER
    if "/config/" in rel or rel.endswith("container_manager.py") \
            or "watch" in low or "refresh" in low:
        return ROLE_WATCHER
    if rel.endswith("runner/processor_runner.py") or "worker" in low:
        return ROLE_WORKER
    if "/flusher/" in rel or rel.endswith(("flusher_runner.py",
                                           "http_sink.py", "kafka.py")) \
            or "sender" in low or "flusher" in low:
        return ROLE_FLUSHER
    if "serve_forever" in low or "http" in low:
        return ROLE_HTTP
    if "/input/" in rel:
        return ROLE_INPUT
    return ROLE_THREAD


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class RoleGraph:
    def __init__(self, program: Program, cg: CallGraph):
        self.cg = cg
        #: (relpath, qualname) -> role set
        self._roles: Dict[Tuple[str, str], set] = {}
        #: seeded entries for tests/debugging: (FuncInfo, role, reason)
        self.entries: List[Tuple[FuncInfo, str, str]] = []
        self._seed(program)
        self._propagate()

    # -- seeding -------------------------------------------------------

    def _add_entry(self, fi: FuncInfo, role: str, reason: str) -> None:
        self.entries.append((fi, role, reason))
        self._roles.setdefault(fi.key, set()).add(role)

    def _seed(self, program: Program) -> None:
        for fi in self.cg.functions:
            for node in _own_nodes(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                dotted = call_name(node)
                if dotted in _THREAD_CTORS:
                    target = _kw(node, "target")
                    if target is None:
                        continue
                    name_kw = _kw(node, "name")
                    tname = name_kw.value if isinstance(
                        name_kw, ast.Constant) and isinstance(
                        name_kw.value, str) else ""
                    for entry in self.cg.resolve_ref(target, fi):
                        self._add_entry(entry, classify_entry(entry, tname),
                                        "threading.Thread target")
                elif dotted in _TIMER_CTORS:
                    if len(node.args) >= 2:
                        for entry in self.cg.resolve_ref(node.args[1], fi):
                            self._add_entry(entry, ROLE_TIMER,
                                            "threading.Timer callback")
                elif dotted == "signal.signal" and len(node.args) == 2:
                    for entry in self.cg.resolve_ref(node.args[1], fi):
                        self._add_entry(entry, ROLE_SIGNAL,
                                        "signal handler")

        for ci in self.cg.classes.values():
            bases = set(ci.bases)
            if "Thread" in bases and "run" in ci.methods:
                entry = ci.methods["run"]
                self._add_entry(entry, classify_entry(entry),
                                "threading.Thread subclass run()")
            if bases & _HTTP_HANDLER_BASES:
                for name, m in ci.methods.items():
                    if name.startswith("do_"):
                        self._add_entry(m, ROLE_HTTP,
                                        "BaseHTTPRequestHandler do_*")

        # main-thread seeds: lifecycle methods + the application module
        for fi in self.cg.functions:
            if fi.parent is None and fi.name in _MAIN_METHODS:
                self._add_entry(fi, ROLE_MAIN, "lifecycle method")
            elif fi.cls_name is None and fi.parent is None and \
                    fi.relpath.endswith("application.py"):
                self._add_entry(fi, ROLE_MAIN, "application module")

    # -- propagation ---------------------------------------------------

    def _propagate(self) -> None:
        # successors = call edges + parent->nested-def edges (a closure
        # passed as a callback is approximated by its parent's roles;
        # Thread targets got their own seed already)
        succ: Dict[Tuple[str, str], List[FuncInfo]] = {
            fi.key: list(self.cg.callees(fi)) for fi in self.cg.functions}
        for fi in self.cg.functions:
            if fi.parent is not None:
                succ.setdefault(fi.parent.key, []).append(fi)
        work = [fi for fi in self.cg.functions if fi.key in self._roles]
        while work:
            fi = work.pop()
            roles = self._roles.get(fi.key, set())
            for callee in succ.get(fi.key, ()):
                have = self._roles.setdefault(callee.key, set())
                if not roles <= have:
                    have |= roles
                    work.append(callee)

    # -- queries -------------------------------------------------------

    def roles(self, fi: FuncInfo) -> FrozenSet[str]:
        return frozenset(self._roles.get(fi.key, ()))

    def effective_roles(self, key: Tuple[str, str]) -> FrozenSet[str]:
        roles = self._roles.get(key)
        return frozenset(roles) if roles else frozenset((ROLE_MAIN,))

    @staticmethod
    def concurrent(roles: FrozenSet[str]) -> bool:
        """Can code running under these roles race with itself/another
        site of the same role set?  >= 2 distinct roles, or one
        multi-instance family."""
        nonmain = roles - {ROLE_MAIN}
        if len(roles) >= 2:
            return True
        return len(nonmain) == 1 and next(iter(nonmain)) in MULTI_INSTANCE
