"""Shared-state access map: every ``self._attr`` site, with held locks.

For each class, every method (and nested def) is walked with the SAME
lock-region tracking blocking-under-lock uses (analysis/locktrack.py),
recording each attribute access as one of:

  * ``read``   — a plain Load (a single atomic op under the GIL);
  * ``iter``   — a Load in an *iterating* position (``for x in self._d``,
    ``list(self._d)``, ``self._d.items()``): the use spans many bytecodes,
    so a concurrent mutation lands mid-iteration;
  * ``write``  — a rebind (``self._x = v``) — GIL-atomic on its own, so a
    rebind only races as part of a check-then-act;
  * ``mutate`` — a single-op in-place mutation (``self._x[k] = v``,
    ``del self._x[k]``, ``self._x.append(...)``): atomic at THIS class's
    level (builtin container ops run under the GIL; a method call on a
    typed component synchronises in ITS OWN class, which raceguard
    analyses separately);
  * ``rmw``    — a compound read-modify-write that is NOT atomic:
    ``self._x += 1``, ``self._x[k] += v`` — the load and the store are
    separate bytecodes, so two threads lose updates.

Two interprocedural refinements keep the map honest:

  * private helpers (``_record``, ``_shrink_locked``, ...) inherit the
    INTERSECTION of the lock sets held at their ``self._helper()`` call
    sites, to a fixpoint — the pervasive "call with lock held" idiom;
  * in a function that constructs a thread at its top level
    (``start()``-style), accesses lexically before the first
    ``threading.Thread(...)`` statement happen before publication and are
    treated like ``__init__`` sites.

Attributes whose value is a known thread-safe type (Lock/Event/Queue/
deque/...) are marked exempt: their methods synchronise internally.
Container attributes (dict/list/set/defaultdict literals or ctors) are
marked mutable_container — those are the ones whose *reference* must not
escape a locked region.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from ..core import Program, call_name
from ..locktrack import LockRegionWalker, ModuleLocks
from .callgraph import CallGraph, FuncInfo, _own_nodes

READ = "read"
ITER = "iter"
WRITE = "write"
MUTATE = "mutate"
RMW = "rmw"

#: method names that mutate their receiver in place (non-atomic compound
#: state transitions when the receiver is shared)
MUTATORS = frozenset((
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popitem", "popleft", "remove",
    "discard", "clear", "sort", "reverse", "rotate", "subtract",
))

#: ctor tails whose instances synchronise internally — never a guarded-by
#: subject (deque's single-op append/pop are GIL-atomic, the documented
#: CPython idiom this codebase relies on)
THREADSAFE_CTORS = frozenset((
    "Lock", "RLock", "Condition", "Event", "Semaphore", "BoundedSemaphore",
    "Barrier", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "deque", "local",
))

_CONTAINER_CTORS = frozenset(("dict", "list", "set", "defaultdict",
                              "OrderedDict", "Counter", "deque"))

#: builtins whose call consumes the whole argument — an iterating use
_ITER_CONSUMERS = frozenset((
    "list", "tuple", "set", "frozenset", "sorted", "sum", "min", "max",
    "any", "all", "enumerate", "zip", "iter", "dict", "map", "filter",
))

#: receiver methods that hand out a view/copy of the whole container
_ITER_METHODS = frozenset(("items", "values", "keys", "copy"))

_THREAD_CTOR_NAMES = frozenset(("threading.Thread", "Thread",
                                "threading.Timer", "Timer"))


class Access:
    __slots__ = ("attr", "kind", "line", "col", "locks", "func_key",
                 "in_init")

    def __init__(self, attr: str, kind: str, line: int, col: int,
                 locks: FrozenSet[str], func_key: Tuple[str, str],
                 in_init: bool):
        self.attr = attr
        self.kind = kind
        self.line = line
        self.col = col
        self.locks = locks          # lock expression texts held at the site
        self.func_key = func_key    # (relpath, qualname) of enclosing func
        self.in_init = in_init

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"<Access {self.attr} {self.kind}@{self.line} "
                f"locks={sorted(self.locks)}>")


class ClassAccesses:
    """All shared-state facts for one class."""

    def __init__(self, relpath: str, cls_name: str):
        self.relpath = relpath
        self.cls_name = cls_name
        self.accesses: Dict[str, List[Access]] = {}     # attr -> sites
        self.exempt: Set[str] = set()           # thread-safe-typed attrs
        self.containers: Set[str] = set()       # mutable-container attrs
        #: the class participates in locking at all (owns a Lock/RLock/
        #: Condition or holds one at some access) — the gate for
        #: guarded-by/atomicity inference: a class with NO locking is a
        #: data-plane object whose instances are *handed off* between
        #: threads (queue transfer is the synchronisation point), not
        #: shared, and there is no candidate guard to infer
        self.uses_locks = False
        # check-then-act candidates: (attr, test_line, act_line,
        #                             test_locks, act_locks, func_key)
        self.check_acts: List[Tuple[str, int, int, FrozenSet[str],
                                    FrozenSet[str], Tuple[str, str]]] = []
        # returns of a guarded attr out of a locked region:
        # (attr, line, col, lock_text, func_key)
        self.escapes: List[Tuple[str, int, int, str, Tuple[str, str]]] = []

    def add(self, acc: Access) -> None:
        self.accesses.setdefault(acc.attr, []).append(acc)


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.X` -> 'X' (only the direct attribute; deeper chains resolve
    to their base via _base_self_attr)."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _base_self_attr(node: ast.AST) -> Optional[str]:
    """Base self-attribute of an access chain: ``self._a[k].b`` -> '_a'."""
    cur = node
    while True:
        if isinstance(cur, ast.Attribute):
            direct = _self_attr(cur)
            if direct is not None:
                return direct
            cur = cur.value
        elif isinstance(cur, ast.Subscript):
            cur = cur.value
        else:
            return None


def _iter_positions(func: ast.AST) -> Set[int]:
    """id()s of ``self.X`` Load nodes used in iterating positions."""
    ids: Set[int] = set()

    def mark(expr: ast.AST) -> None:
        if _self_attr(expr) is not None:
            ids.add(id(expr))
        elif isinstance(expr, ast.Call) and \
                isinstance(expr.func, ast.Attribute) and \
                expr.func.attr in _ITER_METHODS and \
                _self_attr(expr.func.value) is not None:
            ids.add(id(expr.func.value))

    for node in _own_nodes(func):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            mark(node.iter)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                mark(gen.iter)
        elif isinstance(node, ast.Call):
            if isinstance(node.func, ast.Name) and \
                    node.func.id in _ITER_CONSUMERS:
                for arg in node.args:
                    mark(arg)
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _ITER_METHODS:
                mark(node.func.value)
    return ids


def _prestart_line(func: ast.AST) -> Optional[int]:
    """Line of the first top-level statement constructing a Thread/Timer,
    or None.  Only TOP-LEVEL statements qualify: a ctor inside a loop
    spawns per iteration, so earlier lines do NOT happen-before every
    spawned thread."""
    for stmt in getattr(func, "body", ()):
        for node in _own_nodes(stmt):
            if isinstance(node, ast.Call) and \
                    call_name(node) in _THREAD_CTOR_NAMES:
                return stmt.lineno
    return None


class _MethodScan(LockRegionWalker):
    """Record one method's attribute accesses + atomicity/escape shapes."""

    def __init__(self, locks: ModuleLocks, ca: ClassAccesses,
                 fi: FuncInfo, cg: CallGraph):
        super().__init__(locks)
        self.ca = ca
        self.fi = fi
        self.cg = cg
        self.in_init = fi.qualname.split(".")[-1] in ("__init__",
                                                      "__new__")
        self._aug_target: Optional[ast.AST] = None
        self._iter_ids = _iter_positions(fi.node)
        self._prestart = None if self.in_init else _prestart_line(fi.node)
        #: private self-method call sites: (callee_key, held locks)
        self.calls: List[Tuple[Tuple[str, str], FrozenSet[str]]] = []
        self.walk(fi.node)

    # -- recording helpers --------------------------------------------

    def _rec(self, attr: str, kind: str, node: ast.AST,
             held: List[str]) -> None:
        if self.locks.is_lock_name(attr):
            return      # the lock itself is not shared *state*
        init_like = self.in_init or (
            self._prestart is not None and node.lineno < self._prestart)
        self.ca.add(Access(attr, kind, node.lineno, node.col_offset,
                           frozenset(self.locks.canon(h) for h in held),
                           self.fi.key, init_like))

    # -- hooks ---------------------------------------------------------

    def on_acquire(self, lock: str, held: List[str], line: int) -> None:
        self.ca.uses_locks = True

    def on_stmt(self, stmt: ast.stmt, held: List[str]) -> None:
        if isinstance(stmt, ast.AugAssign):
            self._aug_target = stmt.target
        if isinstance(stmt, (ast.If, ast.While)) and not self.in_init:
            self._scan_check_act(stmt, held)
        if isinstance(stmt, ast.Return) and held and \
                stmt.value is not None:
            for value in _return_parts(stmt.value):
                attr = _self_attr(value)
                if attr is not None and \
                        not self.locks.is_lock_name(attr):
                    self.ca.escapes.append(
                        (attr, stmt.lineno, stmt.col_offset,
                         self.locks.canon(held[-1]), self.fi.key))

    def on_expr(self, expr: ast.AST, held: List[str]) -> None:
        self._classify(expr, held)

    # -- access classification ----------------------------------------

    def _classify(self, node: ast.AST, held: List[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return      # separate FuncInfo / deferred execution
        if isinstance(node, ast.Call):
            attr = None
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in MUTATORS:
                attr = _base_self_attr(node.func.value)
            if attr is not None:
                self._rec(attr, MUTATE, node, held)
            else:
                if isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id in ("self", "cls") and \
                        node.func.attr.startswith("_") and \
                        not node.func.attr.startswith("__"):
                    callee = self.cg.resolve_self_method(
                        self.fi, node.func.attr)
                    if callee is not None:
                        self.calls.append((callee.key, frozenset(
                            self.locks.canon(h) for h in held)))
                self._classify(node.func, held)
            for arg in node.args:
                self._classify(arg, held)
            for kw in node.keywords:
                self._classify(kw.value, held)
            return
        if isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = _base_self_attr(node.value)
            if attr is not None:
                kind = RMW if node is self._aug_target else MUTATE
                self._rec(attr, kind, node, held)
            self._classify(node.slice, held)
            return
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None:
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    kind = RMW if node is self._aug_target else WRITE
                    self._rec(attr, kind, node, held)
                else:
                    kind = ITER if id(node) in self._iter_ids else READ
                    self._rec(attr, kind, node, held)
                return
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                # `self._a.b = v` / `self._a[k].b = v`: a field store on
                # the object held by _a is a mutation of shared _a state
                base = _base_self_attr(node.value)
                if base is not None:
                    self._rec(base, MUTATE, node, held)
                    return
            self._classify(node.value, held)
            return
        for child in ast.iter_child_nodes(node):
            self._classify(child, held)

    # -- check-then-act -----------------------------------------------

    def _scan_check_act(self, stmt: ast.stmt, held: List[str]) -> None:
        tested: Set[str] = set()
        for node in ast.walk(stmt.test):
            attr = _self_attr(node)
            if attr is not None and isinstance(node.ctx, ast.Load) and \
                    not self.locks.is_lock_name(attr):
                tested.add(attr)
        if not tested:
            return
        finder = _ActFinder(self.locks, tested)
        finder._walk_body(list(stmt.body), list(held))
        orelse = getattr(stmt, "orelse", None)
        if orelse:
            finder._walk_body(list(orelse), list(held))
        for attr, line, act_locks in finder.acts:
            self.ca.check_acts.append(
                (attr, stmt.lineno, line,
                 frozenset(self.locks.canon(h) for h in held),
                 frozenset(self.locks.canon(h) for h in act_locks),
                 self.fi.key))


class _ActFinder(LockRegionWalker):
    """Find writes/mutations of the tested attrs inside a check's body,
    with the lock set actually held at the act site."""

    def __init__(self, locks: ModuleLocks, attrs: Set[str]):
        super().__init__(locks)
        self.attrs = attrs
        self.acts: List[Tuple[str, int, List[str]]] = []
        self._aug_target: Optional[ast.AST] = None

    def on_stmt(self, stmt: ast.stmt, held: List[str]) -> None:
        if isinstance(stmt, ast.AugAssign):
            self._aug_target = stmt.target

    def on_expr(self, expr: ast.AST, held: List[str]) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr in self.attrs and \
                        isinstance(node.ctx, (ast.Store, ast.Del)):
                    self.acts.append((attr, node.lineno, list(held)))
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                attr = _base_self_attr(node.value)
                if attr in self.attrs:
                    self.acts.append((attr, node.lineno, list(held)))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in MUTATORS:
                attr = _base_self_attr(node.func.value)
                if attr in self.attrs:
                    self.acts.append((attr, node.lineno, list(held)))


def _return_parts(value: ast.expr) -> Iterator[ast.expr]:
    if isinstance(value, ast.Tuple):
        yield from value.elts
    else:
        yield value


class AccessMap:
    def __init__(self, program: Program, cg: CallGraph):
        #: (relpath, cls_name) -> ClassAccesses
        self.by_class: Dict[Tuple[str, str], ClassAccesses] = {}
        mod_locks = {m.relpath: ModuleLocks(m.tree)
                     for m in program.modules}
        # (callee_key, locks held at the site, caller_key)
        calls: List[Tuple[Tuple[str, str], FrozenSet[str],
                          Tuple[str, str]]] = []
        for fi in cg.functions:
            if fi.cls_name is None:
                continue
            key = (fi.relpath, fi.cls_name)
            ca = self.by_class.get(key)
            if ca is None:
                ca = self.by_class[key] = ClassAccesses(fi.relpath,
                                                        fi.cls_name)
            scan = _MethodScan(mod_locks[fi.relpath], ca, fi, cg)
            calls.extend((callee, held, fi.key)
                         for callee, held in scan.calls)
        for (relpath, cls_name), ca in self.by_class.items():
            ci = cg.classes.get((relpath, cls_name))
            if ci is not None:
                self._type_attrs(ci, ca)
        self._apply_entry_locks(self._entry_locks(calls))

    # -- interprocedural lock context ---------------------------------

    @staticmethod
    def _entry_locks(calls) -> Dict[Tuple[str, str], FrozenSet[str]]:
        """Locks a private helper is guaranteed to hold on entry: the
        intersection over all its ``self._helper()`` call sites of
        (lexically held locks | the caller's own entry locks), iterated
        to a fixpoint.  Helpers in a call cycle with no outside caller
        resolve to the empty set."""
        callers: Dict[Tuple[str, str],
                      List[Tuple[Tuple[str, str], FrozenSet[str]]]] = {}
        for callee, held, caller in calls:
            callers.setdefault(callee, []).append((caller, held))
        # None = "no information yet" (TOP); sets only ever shrink
        entry: Dict[Tuple[str, str], Optional[FrozenSet[str]]] = {
            k: None for k in callers}
        changed = True
        while changed:
            changed = False
            for callee, sites in callers.items():
                vals = []
                for caller, held in sites:
                    caller_entry = entry.get(caller)
                    if caller in entry and caller_entry is None:
                        continue    # still TOP: identity for the meet
                    vals.append(held | (caller_entry or frozenset()))
                if not vals:
                    continue
                new = vals[0]
                for v in vals[1:]:
                    new &= v
                cur = entry[callee]
                merged = new if cur is None else (cur & new)
                if merged != cur:
                    entry[callee] = merged
                    changed = True
        return {k: v for k, v in entry.items() if v}

    def _apply_entry_locks(self, entry) -> None:
        if not entry:
            return
        empty: FrozenSet[str] = frozenset()
        for ca in self.by_class.values():
            for sites in ca.accesses.values():
                for a in sites:
                    extra = entry.get(a.func_key)
                    if extra:
                        a.locks = a.locks | extra
            ca.check_acts = [
                (attr, tl, al,
                 tlk | entry.get(fk, empty), alk | entry.get(fk, empty),
                 fk)
                for (attr, tl, al, tlk, alk, fk) in ca.check_acts]

    def _type_attrs(self, ci, ca: ClassAccesses) -> None:
        """Classify attr value types from assignments in the class body:
        thread-safe ctors -> exempt; container ctors/literals ->
        mutable_container."""
        for fi in ci.methods.values():
            for node in ast.walk(fi.node):
                value: Optional[ast.expr] = None
                targets: List[ast.expr] = []
                if isinstance(node, ast.Assign):
                    value, targets = node.value, node.targets
                elif isinstance(node, ast.AnnAssign) and node.value:
                    value, targets = node.value, [node.target]
                if value is None:
                    continue
                attrs = [t.attr for t in targets
                         if isinstance(t, ast.Attribute)
                         and isinstance(t.value, ast.Name)
                         and t.value.id == "self"]
                if not attrs:
                    continue
                if isinstance(value, ast.Call):
                    tail = call_name(value).rsplit(".", 1)[-1]
                    if tail in ("Lock", "RLock", "Condition"):
                        ca.uses_locks = True
                    if tail in THREADSAFE_CTORS:
                        ca.exempt.update(attrs)
                    elif tail in _CONTAINER_CTORS:
                        ca.containers.update(attrs)
                elif isinstance(value, (ast.Dict, ast.List, ast.Set,
                                        ast.DictComp, ast.ListComp,
                                        ast.SetComp)):
                    ca.containers.update(attrs)
