"""raceguard reporting: guarded-by, atomicity, lock-scope findings.

All three reports key off the same extracted model (callgraph + roles +
access map).  Precision levers, in order of load-bearing-ness:

  * ``__init__``/``__new__`` sites never count — construction happens
    before the object is published to other threads — and neither do
    sites lexically before a top-level ``threading.Thread(...)`` ctor in
    ``start()``-style methods (pre-publication);
  * private helpers inherit the intersected lock set of their call sites
    (the "call with lock held" idiom: ``_record``, ``_shrink_locked``);
  * guarded-by fires only when the conflict set holds a NON-ATOMIC op —
    an rmw (``+=``) or an iterating read; single-op container mutations,
    rebinds, and plain loads are each GIL-atomic, and method calls on a
    typed component synchronise inside that component's own class, which
    raceguard analyses separately;
  * thread-safe-typed attrs (Lock/Event/Queue/deque/...) are exempt;
  * a conflict needs concurrent roles: >= 2 distinct thread families, or
    one multi-instance family (N worker shards, thread-per-request HTTP).

What stays inferential (documented in docs/static_analysis.md): role
propagation over-approximates (a method callable from worker AND main
carries both roles even if the program never overlaps them), and
callback indirection the resolver can't see under-approximates.  Real
hits get fixed; benign-but-unprovable ones live in the allowlist with a
pay-down note.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Tuple

from ..core import Checker, Finding, ModuleInfo, Program
from .accessmap import ITER, MUTATE, READ, RMW, WRITE, AccessMap
from .callgraph import CallGraph
from .roles import ROLE_MAIN, RoleGraph

CHECK_GUARDED_BY = "raceguard-guarded-by"
CHECK_ATOMICITY = "raceguard-atomicity"
CHECK_LOCK_SCOPE = "raceguard-lock-scope"


def _fmt_roles(roles) -> str:
    return "{" + ",".join(sorted(roles)) + "}"


class RaceGuardChecker(Checker):
    name = CHECK_GUARDED_BY
    description = ("whole-program thread-role race detection: guarded-by "
                   "inference, check-then-act atomicity, lock-scope "
                   "escapes")

    @property
    def produces(self) -> frozenset:
        return frozenset((CHECK_GUARDED_BY, CHECK_ATOMICITY,
                          CHECK_LOCK_SCOPE))

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        return iter(())

    def finalize(self, program: Program) -> Iterator[Finding]:
        cg = CallGraph(program)
        rg = RoleGraph(program, cg)
        am = AccessMap(program, cg)
        # findings are emitted in (path, line) order per class for stable
        # output; run_analysis re-sorts globally anyway
        for key in sorted(am.by_class):
            ca = am.by_class[key]
            yield from self._guarded_by(ca, rg)
            yield from self._atomicity(ca, rg)
            yield from self._lock_scope(ca, rg)

    # -- guarded-by ----------------------------------------------------

    def _guarded_by(self, ca, rg: RoleGraph) -> Iterator[Finding]:
        if not ca.uses_locks:
            return
        for attr in sorted(ca.accesses):
            if attr in ca.exempt:
                continue
            sites = [a for a in ca.accesses[attr] if not a.in_init]
            mutates = [a for a in sites if a.kind in (MUTATE, RMW)]
            if not mutates:
                continue
            # a race needs at least one NON-ATOMIC op in the conflict set:
            # an rmw (+=: load and store are separate bytecodes) or an
            # iterating read (the dict/list snapshot shape).  Single-op
            # container mutations, rebinds, and plain loads are each
            # atomic under the GIL — races among only those can't corrupt
            # anything at this class's level.
            nonatomic = [a for a in sites if a.kind in (RMW, ITER)]
            if not nonatomic:
                continue
            writes = [a for a in sites if a.kind in (WRITE, MUTATE, RMW)]
            writer_roles = frozenset().union(
                *(rg.effective_roles(a.func_key) for a in writes))
            if not rg.concurrent(writer_roles):
                continue
            # the guard must cover every write/mutate AND every iterating
            # read; plain loads stay out (GIL-atomic, benign)
            guarded = writes + [a for a in nonatomic if a.kind == ITER]
            common = frozenset(guarded[0].locks)
            for a in guarded[1:]:
                common &= a.locks
            if common:
                continue
            unlocked = [a for a in guarded if not a.locks]
            # anchor at the bug: the first unlocked non-atomic site if one
            # exists, else the first unlocked mutation, else the first
            anchor_pool = ([a for a in nonatomic if not a.locks]
                           or [a for a in mutates if not a.locks]
                           or mutates)
            site = min(anchor_pool, key=lambda a: (a.line, a.col))
            others = sorted({f"{a.kind}@{a.line}" for a in guarded
                             if a is not site})
            detail = ", ".join(others[:4]) + \
                (", ..." if len(others) > 4 else "")
            yield Finding(
                CHECK_GUARDED_BY, ca.relpath, site.line, site.col,
                f"self.{attr} is written from thread roles "
                f"{_fmt_roles(writer_roles)} but its {len(guarded)} "
                f"conflicting sites share no common lock "
                f"({len(unlocked)} hold none; {detail}) — pick one lock "
                "and hold it at every site",
                symbol=f"{ca.cls_name}.{attr}")

    # -- check-then-act ------------------------------------------------

    def _atomicity(self, ca, rg: RoleGraph) -> Iterator[Finding]:
        if not ca.uses_locks:
            return
        seen = set()
        for (attr, test_line, act_line, test_locks, act_locks,
             func_key) in ca.check_acts:
            if attr in ca.exempt:
                continue
            if test_locks & act_locks:
                continue    # check and act under one continuous region
            sites = [a for a in ca.accesses.get(attr, ())
                     if not a.in_init]
            all_roles = frozenset().union(
                frozenset(), *(rg.effective_roles(a.func_key)
                               for a in sites))
            if not rg.concurrent(all_roles):
                continue
            # single-role-single-instance functions can't interleave with
            # themselves; require the acting function itself concurrent
            # OR another function also writing the attr
            other_writers = {a.func_key for a in sites
                            if a.kind in (WRITE, MUTATE, RMW)
                            and a.func_key != func_key}
            if not rg.concurrent(rg.effective_roles(func_key)) \
                    and not other_writers:
                continue
            dkey = (attr, test_line)    # one report per check site
            if dkey in seen:
                continue
            seen.add(dkey)
            locks_txt = "no lock" if not (test_locks | act_locks) else (
                f"check holds {sorted(test_locks) or ['nothing']}, "
                f"act holds {sorted(act_locks) or ['nothing']}")
            yield Finding(
                CHECK_ATOMICITY, ca.relpath, test_line, 0,
                f"check-then-act on self.{attr} (checked at line "
                f"{test_line}, acted on at line {act_line}) is not atomic:"
                f" {locks_txt}; roles {_fmt_roles(all_roles)} can "
                "interleave between check and act",
                symbol=f"{ca.cls_name}.{attr}")

    # -- lock-scope escape ---------------------------------------------

    def _lock_scope(self, ca, rg: RoleGraph) -> Iterator[Finding]:
        for attr, line, col, lock, func_key in ca.escapes:
            if attr in ca.exempt or attr not in ca.containers:
                continue
            # only meaningful if the container is actually mutated
            # somewhere under a lock (why else guard the read?)
            locked_mut = [a for a in ca.accesses.get(attr, ())
                          if a.kind in (MUTATE, RMW) and a.locks
                          and not a.in_init]
            if not locked_mut:
                continue
            sites = [a for a in ca.accesses.get(attr, ())
                     if not a.in_init]
            all_roles = frozenset().union(
                frozenset(), *(rg.effective_roles(a.func_key)
                               for a in sites))
            if not rg.concurrent(all_roles):
                continue
            yield Finding(
                CHECK_LOCK_SCOPE, ca.relpath, line, col,
                f"returning mutable container self.{attr} out of the "
                f"{lock} region publishes the guarded reference — the "
                "caller iterates it after the lock is released while "
                f"roles {_fmt_roles(all_roles)} keep mutating it; return "
                "a copy (dict(...)/list(...)) instead",
                symbol=f"{ca.cls_name}.{attr}")
