"""raceguard: whole-program thread-role race detection (loonglint #13).

Two phases over the parsed tree (docs/static_analysis.md#race-detection):

1. model extraction — a best-effort call graph (`callgraph.py`), a
   thread-role graph seeded from every thread entry-point family and
   propagated along call edges (`roles.py`), and a per-class shared-state
   access map recording each ``self._attr`` read/write/mutation site with
   the lock set held there (`accessmap.py`, lock semantics shared with
   blocking-under-lock via ``analysis/locktrack.py``);

2. reporting (`checker.py`) — guarded-by violations (mutations from
   concurrent roles with no common lock), check-then-act atomicity
   violations, and lock-scope escapes (mutable guarded containers
   returned out of their locked region).
"""

from .accessmap import Access, AccessMap
from .callgraph import CallGraph, FuncInfo
from .checker import (CHECK_ATOMICITY, CHECK_GUARDED_BY, CHECK_LOCK_SCOPE,
                      RaceGuardChecker)
from .roles import RoleGraph

__all__ = [
    "Access", "AccessMap", "CallGraph", "FuncInfo", "RoleGraph",
    "RaceGuardChecker", "CHECK_GUARDED_BY", "CHECK_ATOMICITY",
    "CHECK_LOCK_SCOPE",
]
