"""Whole-program call graph, best effort and deliberately conservative.

Resolution rules (anything else stays unresolved — no edge — because a
wrong edge wires unrelated thread roles together and fabricates races):

  * ``self.m(...)``            -> method ``m`` on the enclosing class or a
                                  program-visible base class;
  * ``self._a.m(...)``         -> method ``m`` of the class assigned to
                                  ``self._a = ClassName(...)`` anywhere in
                                  the owning class (unique class name);
  * ``f(...)``                 -> a nested ``def f`` in the enclosing
                                  function, else a module-level function of
                                  the same module, else the unique global
                                  function of that name;
  * ``anything.m(...)``        -> the unique method named ``m`` in the
                                  whole program (blocking-under-lock's
                                  interprocedural-hop discipline).

The same resolver also resolves *callable references* (``target=self._run``
in a Thread ctor), which is how roles.py seeds thread entry points.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from ..core import Program, call_name


class FuncInfo:
    """One function/method (possibly nested) in the program."""

    __slots__ = ("relpath", "qualname", "node", "cls_name", "module",
                 "parent")

    def __init__(self, relpath: str, qualname: str, node: ast.AST,
                 cls_name: Optional[str], parent: Optional["FuncInfo"]):
        self.relpath = relpath
        self.qualname = qualname        # e.g. "Breaker.emit.inner"
        self.node = node
        self.cls_name = cls_name        # enclosing class simple name or None
        self.parent = parent            # enclosing FuncInfo for nested defs

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    @property
    def key(self) -> Tuple[str, str]:
        return (self.relpath, self.qualname)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<FuncInfo {self.relpath}:{self.qualname}>"


class ClassInfo:
    __slots__ = ("relpath", "name", "bases", "methods", "attr_types",
                 "node")

    def __init__(self, relpath: str, name: str, node: ast.ClassDef):
        self.relpath = relpath
        self.name = name                # simple name
        self.node = node
        self.bases: List[str] = []      # base simple names
        self.methods: Dict[str, FuncInfo] = {}
        self.attr_types: Dict[str, str] = {}   # self._a -> ClassName


def _own_nodes(func: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested function/class
    definitions (those bodies belong to their own FuncInfo)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class CallGraph:
    def __init__(self, program: Program):
        self.functions: List[FuncInfo] = []
        self.classes: Dict[Tuple[str, str], ClassInfo] = {}
        self._cls_by_name: Dict[str, List[ClassInfo]] = {}
        self._func_by_name: Dict[str, List[FuncInfo]] = {}
        self._method_by_name: Dict[str, List[FuncInfo]] = {}
        self._modfuncs: Dict[Tuple[str, str], FuncInfo] = {}
        self._nested: Dict[Tuple[str, str], Dict[str, FuncInfo]] = {}
        self._edges: Dict[Tuple[str, str], List[FuncInfo]] = {}

        for mod in program.modules:
            self._index_module(mod.relpath, mod.tree)
        for ci in self.classes.values():
            self._infer_attr_types(ci)
        for fi in self.functions:
            self._edges[fi.key] = self._resolve_calls(fi)

    # -- indexing ------------------------------------------------------

    def _index_module(self, relpath: str, tree: ast.AST) -> None:
        def walk(node: ast.AST, prefix: str, cls: Optional[ClassInfo],
                 parent: Optional[FuncInfo]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qn = f"{prefix}{child.name}"
                    fi = FuncInfo(relpath, qn, child,
                                  cls.name if cls else None, parent)
                    self.functions.append(fi)
                    self._func_by_name.setdefault(child.name, []).append(fi)
                    if cls is not None and parent is None:
                        cls.methods[child.name] = fi
                        self._method_by_name.setdefault(
                            child.name, []).append(fi)
                    elif cls is None and parent is None:
                        self._modfuncs[(relpath, child.name)] = fi
                    if parent is not None:
                        self._nested.setdefault(
                            parent.key, {})[child.name] = fi
                    walk(child, qn + ".", cls, fi)
                elif isinstance(child, ast.ClassDef):
                    ci = ClassInfo(relpath, child.name, child)
                    for base in child.bases:
                        text = _base_tail(base)
                        if text:
                            ci.bases.append(text)
                    self.classes[(relpath, child.name)] = ci
                    self._cls_by_name.setdefault(child.name, []).append(ci)
                    # methods of a nested class still attribute to it
                    walk(child, f"{prefix}{child.name}.", ci, None)
                else:
                    walk(child, prefix, cls, parent)

        walk(tree, "", None, None)

    def _infer_attr_types(self, ci: ClassInfo) -> None:
        for fi in ci.methods.values():
            for node in _own_nodes(fi.node):
                value: Optional[ast.expr] = None
                targets: List[ast.expr] = []
                if isinstance(node, ast.Assign):
                    value, targets = node.value, node.targets
                elif isinstance(node, ast.AnnAssign) and node.value:
                    value, targets = node.value, [node.target]
                if not isinstance(value, ast.Call):
                    continue
                ctor = call_name(value).rsplit(".", 1)[-1]
                if ctor not in self._cls_by_name:
                    continue
                for tgt in targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        self.attr_type_set(ci, tgt.attr, ctor)

    def attr_type_set(self, ci: ClassInfo, attr: str, ctor: str) -> None:
        # first assignment wins; conflicting ctors drop the mapping
        prev = ci.attr_types.get(attr)
        if prev is None:
            ci.attr_types[attr] = ctor
        elif prev != ctor:
            ci.attr_types[attr] = ""

    # -- resolution ----------------------------------------------------

    def class_of(self, fi: FuncInfo) -> Optional[ClassInfo]:
        if fi.cls_name is None:
            return None
        return self.classes.get((fi.relpath, fi.cls_name))

    def _method_on(self, ci: Optional[ClassInfo], name: str,
                   seen: Optional[set] = None) -> Optional[FuncInfo]:
        """Method lookup through program-visible bases (by unique name)."""
        if ci is None:
            return None
        if seen is None:
            seen = set()
        if id(ci) in seen:
            return None
        seen.add(id(ci))
        if name in ci.methods:
            return ci.methods[name]
        for base in ci.bases:
            cands = self._cls_by_name.get(base, [])
            if len(cands) == 1:
                hit = self._method_on(cands[0], name, seen)
                if hit is not None:
                    return hit
        return None

    def resolve_self_method(self, ctx: FuncInfo,
                            name: str) -> Optional[FuncInfo]:
        """``self.name`` on ctx's own class (base classes included)."""
        return self._method_on(self.class_of(ctx), name)

    def resolve_ref(self, expr: ast.AST, ctx: FuncInfo) -> List[FuncInfo]:
        """Resolve a callable reference/ call target to FuncInfos."""
        if isinstance(expr, ast.Name):
            # nested def in the enclosing function chain
            cur: Optional[FuncInfo] = ctx
            while cur is not None:
                hit = self._nested.get(cur.key, {}).get(expr.id)
                if hit is not None:
                    return [hit]
                cur = cur.parent
            hit = self._modfuncs.get((ctx.relpath, expr.id))
            if hit is not None:
                return [hit]
            cands = [f for f in self._func_by_name.get(expr.id, [])
                     if f.cls_name is None and f.parent is None]
            if len(cands) == 1:
                return cands
            return []
        if not isinstance(expr, ast.Attribute):
            return []
        name = expr.attr
        recv = expr.value
        if isinstance(recv, ast.Name) and recv.id in ("self", "cls"):
            hit = self._method_on(self.class_of(ctx), name)
            return [hit] if hit is not None else []
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and \
                recv.value.id in ("self", "cls"):
            ci = self.class_of(ctx)
            if ci is not None:
                tname = ci.attr_types.get(recv.attr)
                if tname:
                    cands = self._cls_by_name.get(tname, [])
                    if len(cands) == 1:
                        hit = self._method_on(cands[0], name)
                        if hit is not None:
                            return [hit]
        if isinstance(recv, ast.Name) and len(
                self._cls_by_name.get(recv.id, [])) == 1:
            # ClassName.method(...) — explicit class receiver
            hit = self._method_on(self._cls_by_name[recv.id][0], name)
            if hit is not None:
                return [hit]
        # the unique-method-name interprocedural hop
        cands = self._method_by_name.get(name, [])
        if len(cands) == 1:
            return cands
        return []

    def _resolve_calls(self, fi: FuncInfo) -> List[FuncInfo]:
        out: List[FuncInfo] = []
        seen = set()
        for node in _own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            for target in self.resolve_ref(node.func, fi):
                if target.key not in seen:
                    seen.add(target.key)
                    out.append(target)
        return out

    def callees(self, fi: FuncInfo) -> List[FuncInfo]:
        return self._edges.get(fi.key, [])


def _base_tail(base: ast.expr) -> str:
    """Simple name of a base-class expression: ``threading.Thread`` ->
    'Thread', ``Foo`` -> 'Foo', anything unresolvable -> ''."""
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    return ""
