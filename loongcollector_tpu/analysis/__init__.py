"""loonglint — AST-based invariant checker for the loongcollector-tpu tree.

The round-5 advisor found a liveness-killing budget leak by hand
(ops/regex/engine.py: PendingParse.dispatch abandoned submitted
DeviceFutures on its error path).  That class of bug — an async device
data plane whose host-side orchestration silently drops budget, blocks
under a lock, or breaks JAX tracing purity — recurs in any threaded
accelerator pipeline and is exactly what a paper-shaped "fast as the
hardware allows" system cannot tolerate.  loonglint machine-checks those
invariants on every tier-1 run.

Checkers (see docs/static_analysis.md):

  acquire-release       budget/slot/token acquisition must release on all
                        paths (try/finally, except-drain, or with)
  blocking-under-lock   no blocking call while a threading lock is held,
                        plus a whole-program lock-ordering cycle report
  tracing-hygiene       no host time/random/print/implicit-sync inside
                        @jax.jit / Pallas kernel bodies under ops/
  registry-consistency  _native/_tpu processor tier wiring is coherent and
                        every alarm site uses a type from monitor/alarms.py

Suppression: append ``# loonglint: disable=<check>[,<check>]`` to the
flagged line.  Pre-existing debt goes in the budgeted allowlist file
(analysis/allowlist.txt, <= 10 entries — enforced by tier-1).

Run: ``python -m loongcollector_tpu.analysis [--json]``.
"""

from __future__ import annotations

from .core import (AnalysisResult, Checker, Finding, ModuleInfo, Program,
                   load_allowlist, run_analysis)

__all__ = [
    "AnalysisResult",
    "Checker",
    "Finding",
    "ModuleInfo",
    "Program",
    "load_allowlist",
    "run_analysis",
]
