"""Shared lock-tracking machinery for lock-aware checkers.

Extracted from checkers/blocking_locks.py (which keeps its findings but
now builds on this module) so whole-program passes — raceguard's
guarded-by inference above all — see locks the SAME way the
blocking-under-lock checker does.  One definition of "what is a lock"
and "what region holds it" keeps the two checkers from disagreeing about
the exact sites they reason over.

Lock identification is deliberately syntactic, so the checkers need no
imports of the checked code:

  * attributes assigned from threading.Lock()/RLock()/Condition() anywhere
    in the module, plus
  * names matching the lock naming convention (_lock, _mutex, _cond,
    _freed, _not_empty, ...).

Held regions: ``with <lock>:`` bodies and ``<lock>.acquire()`` ..
``<lock>.release()`` spans within one statement list.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from .core import attr_tail, call_name, receiver_repr

_LOCK_NAME_RE = re.compile(
    r"(^|_)(lock|mutex|mtx|cond|condition|freed|cv|not_empty|not_full)$")
_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
               "Lock", "RLock", "Condition"}


def expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return ""


def tail_name(text: str) -> str:
    return text.rsplit(".", 1)[-1]


class ModuleLocks:
    """Lock attributes discovered in one module: exact names assigned from
    threading ctors, merged with the naming convention."""

    def __init__(self, tree: ast.AST):
        self.assigned: Set[str] = set()
        #: Condition-wrapping-lock aliases by tail name: ``self._freed =
        #: threading.Condition(self._lock)`` means holding _freed IS
        #: holding _lock — canon() folds the alias onto the wrapped lock
        self._alias: dict = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                if call_name(node.value) in _LOCK_CTORS:
                    names = []
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute):
                            names.append(tgt.attr)
                        elif isinstance(tgt, ast.Name):
                            names.append(tgt.id)
                    self.assigned.update(names)
                    if tail_name(call_name(node.value)) == "Condition" \
                            and node.value.args:
                        src = tail_name(expr_text(node.value.args[0]))
                        if src:
                            for name in names:
                                self._alias[name] = src

    def canon(self, text: str) -> str:
        """Canonical lock identity: Condition aliases fold onto the lock
        they wrap (``self._freed`` -> ``self._lock``)."""
        orig = tail_name(text)
        tail, seen = orig, set()
        while tail in self._alias and tail not in seen:
            seen.add(tail)
            tail = self._alias[tail]
        if tail == orig:
            return text
        return text[: len(text) - len(orig)] + tail

    def is_lock_expr(self, node: ast.AST) -> bool:
        text = expr_text(node)
        if not text or "(" in text:
            return False
        tail = tail_name(text)
        return tail in self.assigned or bool(_LOCK_NAME_RE.search(tail))

    def is_lock_name(self, name: str) -> bool:
        tail = tail_name(name)
        return tail in self.assigned or bool(_LOCK_NAME_RE.search(tail))


class LockRegionWalker:
    """Walk one function body tracking the held lock set.

    Subclass hooks (all receive ``held``, the lock-expression texts held
    at that point, innermost last):

      * ``on_acquire(lock_text, held, line)`` — a lock is being taken
        while ``held`` are already held (``with`` entry or ``.acquire()``);
      * ``on_stmt(stmt, held)`` — every statement, before descent;
      * ``on_expr(expr, held)`` — every expression field of a statement
        (assignment targets/values, call expressions, loop iterables,
        if/while tests, ...).

    Nested function/class definitions are NOT descended into: their
    bodies execute later, not under the enclosing lock.
    """

    def __init__(self, locks: ModuleLocks):
        self.locks = locks

    # -- hooks ---------------------------------------------------------

    def on_acquire(self, lock: str, held: List[str], line: int) -> None:
        pass

    def on_stmt(self, stmt: ast.stmt, held: List[str]) -> None:
        pass

    def on_expr(self, expr: ast.AST, held: List[str]) -> None:
        pass

    # -- traversal -----------------------------------------------------

    def walk(self, func: ast.AST) -> None:
        self._walk_body(list(getattr(func, "body", [])), [])

    def _lock_of_with(self, item: ast.withitem) -> Optional[str]:
        if self.locks.is_lock_expr(item.context_expr):
            return expr_text(item.context_expr)
        return None

    def _walk_body(self, body: List[ast.stmt], held: List[str]) -> None:
        linear: List[str] = []   # locks taken via .acquire() in this block
        for stmt in body:
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                         ast.Call):
                node = stmt.value
                tail = attr_tail(node)
                recv = receiver_repr(node)
                if tail == "acquire" and recv and \
                        self.locks.is_lock_expr(node.func.value):  # type: ignore[union-attr]
                    self.on_acquire(recv, held + linear, stmt.lineno)
                    linear.append(recv)
                    continue
                if tail == "release" and recv in linear:
                    linear.remove(recv)
                    continue
            self._walk_stmt(stmt, held + linear)

    def _walk_stmt(self, stmt: ast.stmt, held: List[str]) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self.on_stmt(stmt, held)
            newly = []
            for item in stmt.items:
                lk = self._lock_of_with(item)
                if lk is not None:
                    self.on_acquire(lk, held, stmt.lineno)
                    newly.append(lk)
                else:
                    self.on_expr(item.context_expr, held)
            self._walk_body(stmt.body, held + newly)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs execute later, not under this lock
        self.on_stmt(stmt, held)
        # expression fields first (loop iterables, if tests, call exprs),
        # then each nested statement list exactly once
        for name, value in ast.iter_fields(stmt):
            if name in ("body", "orelse", "finalbody", "handlers"):
                continue
            items = value if isinstance(value, list) else [value]
            for item in items:
                if isinstance(item, ast.expr):
                    self.on_expr(item, held)
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, attr, None)
            if isinstance(sub, list) and sub and \
                    isinstance(sub[0], ast.stmt):
                self._walk_body(sub, held)
        for handler in getattr(stmt, "handlers", []) or []:
            self._walk_body(handler.body, held)
