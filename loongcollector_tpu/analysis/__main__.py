"""loonglint CLI.

    python -m loongcollector_tpu.analysis              # human output
    python -m loongcollector_tpu.analysis --json       # machine output
    python -m loongcollector_tpu.analysis --list-checks
    python -m loongcollector_tpu.analysis --root path/ --allowlist file

Exit status: 0 clean (allowlisted/suppressed debt is reported but does not
fail), 1 violations or parse errors, 2 usage errors.  Tier-1 runs this via
tests/test_static_analysis.py, so a violation fails the suite.
"""

from __future__ import annotations

import argparse
import json
import sys

from .checkers import all_checkers
from .core import (ALLOWLIST_BUDGET, default_allowlist_path, default_root,
                   load_allowlist, run_analysis)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m loongcollector_tpu.analysis",
        description="loonglint: AST invariant checker for loongcollector-tpu")
    parser.add_argument("--root", default=None,
                        help="directory or file to scan (default: the "
                             "loongcollector_tpu package)")
    parser.add_argument("--allowlist", default=None,
                        help="allowlist file (default: analysis/allowlist.txt)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit one JSON document instead of text")
    parser.add_argument("--checks", default=None,
                        help="comma-separated subset of checks to run")
    parser.add_argument("--list-checks", action="store_true",
                        help="list available checks and exit")
    parser.add_argument("--budget", type=float, default=None, metavar="S",
                        help="fail if the whole scan takes longer than S "
                             "wall seconds (the lint gate caps loonglint's "
                             "own runtime so the checker suite cannot "
                             "quietly grow past its fast-feedback promise)")
    args = parser.parse_args(argv)
    if args.budget is not None and args.budget <= 0:
        print("--budget must be positive", file=sys.stderr)
        return 2

    if args.list_checks:
        for checker in all_checkers():
            print(f"{checker.name:24s} {checker.description}")
        return 0

    checkers = all_checkers()
    if args.checks:
        wanted = {c.strip() for c in args.checks.split(",") if c.strip()}
        known = set().union(*(c.produces for c in checkers))
        unknown = wanted - known
        if unknown:
            print(f"unknown checks: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        # match on produces, not name: `--checks lock-ordering` must run
        # the blocking-under-lock checker that emits those findings
        checkers = [c for c in checkers if wanted & c.produces]

    allowlist_path = args.allowlist if args.allowlist is not None \
        else default_allowlist_path()
    entries = load_allowlist(allowlist_path)
    result = run_analysis(root=args.root or default_root(),
                          checkers=checkers,
                          allowlist_path=allowlist_path)
    if args.checks:
        # a multi-check checker may emit sibling findings the user did
        # not ask for — keep only the requested check names
        result.findings = [f for f in result.findings if f.check in wanted]
        result.suppressed = [f for f in result.suppressed
                             if f.check in wanted]
        result.allowlisted = [f for f in result.allowlisted
                              if f.check in wanted]

    over_budget = len(entries) > ALLOWLIST_BUDGET
    over_time = args.budget is not None and \
        result.total_seconds > args.budget

    if args.as_json:
        doc = result.to_dict()
        doc["allowlist_entries"] = len(entries)
        doc["allowlist_budget"] = ALLOWLIST_BUDGET
        doc["allowlist_over_budget"] = over_budget
        doc["time_budget_seconds"] = args.budget
        doc["over_time_budget"] = over_time
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        for f in result.findings:
            print(f.format())
        for err in result.parse_errors:
            print(f"PARSE ERROR: {err}")
        if result.allowlisted:
            print(f"-- {len(result.allowlisted)} allowlisted finding(s) "
                  f"(budget {len(entries)}/{ALLOWLIST_BUDGET} entries):")
            for f in result.allowlisted:
                print(f"   {f.format()}")
        if result.suppressed:
            print(f"-- {len(result.suppressed)} inline-suppressed "
                  "finding(s)")
        if over_budget:
            print(f"ALLOWLIST OVER BUDGET: {len(entries)} entries > "
                  f"{ALLOWLIST_BUDGET} allowed — pay down debt before "
                  "adding more")
        if over_time:
            slowest = sorted(result.checker_seconds.items(),
                             key=lambda kv: -kv[1])[:3]
            detail = ", ".join(f"{name} {s:.2f}s" for name, s in slowest)
            print(f"RUNTIME OVER BUDGET: scan took "
                  f"{result.total_seconds:.2f}s > {args.budget:.2f}s "
                  f"allowed (slowest: {detail}) — profile the checkers "
                  "with --json checker_seconds")
        status = "clean" if result.ok and not over_budget \
            and not over_time else "FAILED"
        print(f"loonglint: {result.files_scanned} files, "
              f"{len(result.findings)} violation(s) in "
              f"{result.total_seconds:.2f}s — {status}")

    return 0 if result.ok and not over_budget and not over_time else 1


if __name__ == "__main__":
    sys.exit(main())
