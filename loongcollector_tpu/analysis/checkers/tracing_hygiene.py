"""tracing-hygiene: no host-side effects inside traced JAX code under ops/.

A ``@jax.jit`` or Pallas kernel body executes at TRACE time as ordinary
Python; anything it does outside the jnp value-flow is silently frozen
into the compiled program (time.time(), random, mutable-global reads) or
forces a device sync / trace error at the worst moment (float(x) on a
traced value, np.asarray on device buffers, host print).  The kernels
under ops/ are the hot path of the whole paper design — processor_parse_*
throughput collapses if a stray host hook rides along in a kernel.

Traced scopes recognised (syntactic, per module):

  * ``@jax.jit`` / ``@jit`` / ``@functools.partial(jax.jit, ...)``
    decorated functions;
  * functions passed to ``pl.pallas_call(...)`` / ``pallas_call(...)``;
  * ``jax.jit(f)`` call sites — for a local ``f``, the def is marked; for
    ``jax.jit(make_fn(...))`` factory shapes, every def nested inside the
    local factory is marked (the returned closure is what gets traced).

Only files under ops/ are scanned: that is where kernel code lives, and
host-side orchestration (runner/, flusher/) legitimately uses time and
randomness.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from ..core import (Checker, Finding, ModuleInfo, attr_tail, call_name,
                    iter_functions)

CHECK = "tracing-hygiene"

_TIME_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
               "time.sleep", "time.process_time"}
_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_CAST_NAMES = {"float", "int", "bool"}


def _decorator_is_jit(dec: ast.expr) -> bool:
    if isinstance(dec, ast.Call):
        name = call_name(dec)
        if name in ("functools.partial", "partial"):
            return bool(dec.args) and _decorator_is_jit(dec.args[0])
        return name in ("jax.jit", "jit", "pl.pallas_call", "pallas_call")
    try:
        name = ast.unparse(dec)
    except Exception:  # pragma: no cover
        return False
    return name in ("jax.jit", "jit")


class TracingHygieneChecker(Checker):
    name = CHECK
    description = ("no time/random/print/mutable-global/implicit-sync "
                   "inside @jax.jit or Pallas kernel bodies under ops/")

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        if "/ops/" not in "/" + mod.relpath:
            return
        funcs = dict(iter_functions(mod.tree))
        by_name: Dict[str, List[ast.AST]] = {}
        for qn, fn in funcs.items():
            by_name.setdefault(qn.rsplit(".", 1)[-1], []).append(fn)

        traced: Set[ast.AST] = set()
        for qn, fn in funcs.items():
            if any(_decorator_is_jit(d) for d in fn.decorator_list):
                traced.add(fn)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in ("pl.pallas_call", "pallas_call") and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Name):
                    traced.update(by_name.get(arg.id, ()))
            elif name in ("jax.jit", "jit") and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Name):
                    traced.update(by_name.get(arg.id, ()))
                elif isinstance(arg, ast.Call) and \
                        isinstance(arg.func, ast.Name):
                    # jax.jit(make_fn(...)): the closure returned by the
                    # local factory is traced
                    for factory in by_name.get(arg.func.id, ()):
                        for _, inner in iter_functions(factory):
                            traced.add(inner)

        mutable_globals = self._mutable_globals(mod.tree)

        seen: Set[ast.AST] = set()
        for qn, fn in funcs.items():
            if fn not in traced or fn in seen:
                continue
            # nested defs inside a traced body trace with it — mark them
            # seen so they are not reported twice
            for _, inner in iter_functions(fn):
                seen.add(inner)
            yield from self._scan_traced(mod, qn, fn, mutable_globals)

    @staticmethod
    def _mutable_globals(tree: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for stmt in getattr(tree, "body", []):
            if isinstance(stmt, ast.Assign):
                if isinstance(stmt.value, (ast.List, ast.Dict, ast.Set,
                                           ast.ListComp, ast.DictComp,
                                           ast.SetComp)):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            out.add(tgt.id)
        return out

    def _scan_traced(self, mod: ModuleInfo, qualname: str, fn: ast.AST,
                     mutable_globals: Set[str]) -> Iterator[Finding]:
        params: Set[str] = set()
        local_names: Set[str] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = sub.args
                for group in (a.posonlyargs, a.args, a.kwonlyargs):
                    for p in group:
                        local_names.add(p.arg)
                        if sub is fn:
                            params.add(p.arg)
            elif isinstance(sub, ast.Name) and isinstance(sub.ctx,
                                                          ast.Store):
                local_names.add(sub.id)

        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                yield Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    "`global` inside a traced function: writes do not "
                    "re-trace and reads are frozen at trace time",
                    symbol=qualname)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in mutable_globals and \
                    node.id not in local_names:
                yield Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    f"read of mutable module global `{node.id}` inside a "
                    "traced function is frozen at trace time",
                    symbol=qualname)
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            tail = attr_tail(node)
            if name in _TIME_CALLS:
                yield Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    f"host clock `{name}()` inside a traced function is "
                    "evaluated once at trace time, not per call",
                    symbol=qualname)
            elif name == "print":
                yield Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    "host print() inside a traced function (use "
                    "jax.debug.print for traced values)",
                    symbol=qualname)
            elif name.startswith(("random.", "np.random.",
                                  "numpy.random.")):
                yield Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    f"host RNG `{name}()` inside a traced function is "
                    "frozen at trace time (use jax.random with a key)",
                    symbol=qualname)
            elif name in _SYNC_CALLS:
                yield Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    f"`{name}()` inside a traced function forces a host "
                    "sync / constant-folds device values",
                    symbol=qualname)
            elif tail == "block_until_ready":
                yield Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    "block_until_ready() inside a traced function",
                    symbol=qualname)
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in _CAST_NAMES and node.args and \
                    isinstance(node.args[0], ast.Name) and \
                    node.args[0].id in params:
                yield Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    f"`{node.func.id}({node.args[0].id})` on a traced "
                    "argument forces a device sync (trace error under "
                    "jit)", symbol=qualname)
