"""unledgered-drop: event discards invisible to the conservation ledger.

The loongledger invariant (docs/observability.md#event-conservation-ledger)
is that every event admitted at `ingest` leaves through a counted exit —
``send_ok``, ``spill``, ``quarantine``, ``process_drop`` or a reason-tagged
``drop``.  The ConservationAuditor enforces that at runtime; this checker
is the static half of the same contract: a code path in the event-carrying
scopes (``runner/``, ``flusher/``, ``input/``, the hand-off queues in
``pipeline/queue/``, and — since loongagg made the aggregator stage a
counted N→M contraction — ``aggregator/``) that discards an event group
without any ledger
awareness in its function would show up, at runtime, as a nonzero residual
with no reason bucket — the exact silent loss the ledger exists to rule
out.

Discard-site anchors (what marks a function as "this path discards"):

  1. a logging/alarm call whose LITERAL text mentions drop/discard/
     quarantine/shed — the repo's established idiom is to log every
     intentional discard (swallowed-fault forces at least that much);
  2. an augmented increment of a counter whose name contains ``drop``
     (``self.total_dropped += 1`` — the CircularProcessQueue shape);
  3. a broad except handler whose body ends in ``continue``/``return``
     inside a loop — continue-after-except abandons the current item
     (extends swallowed-fault: logging the fault is not enough when the
     payload it carried vanishes too).

A function containing an anchor must also contain a ledger touch: a call
on a ``ledger`` receiver (``ledger.record``, ``ledger.is_on``), or a
``self._ledger*`` helper.  Function granularity is deliberate — the record
often lives in a sibling branch of the discard (verdict dispatch) — the
rule is "this discard path knows the ledger exists", not "the record is
adjacent".

Escape: ``# loonglint: disable=unledgered-drop`` with a justification,
for discards of things that are not events (metrics payloads, self-monitor
internals, replay files whose events were never admitted this run).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from ..core import Checker, Finding, ModuleInfo, attr_tail, call_name

CHECK = "unledgered-drop"

_SCOPES = ("/runner/", "/flusher/", "/input/", "/pipeline/queue/",
           "/aggregator/")
_LOG_TAILS = {"debug", "info", "warning", "error", "exception", "critical",
              "send_alarm"}
_DROP_WORDS = ("drop", "discard", "quarantin", "shed")
_BROAD_NAMES = {"Exception", "BaseException"}


def _literal_text(node: ast.AST) -> str:
    """Every string literal reachable inside an expression (plain, f-string
    parts, concatenations, %-format left sides), lowercased and joined."""
    parts: List[str] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            parts.append(sub.value.lower())
    return " ".join(parts)


def _is_drop_log(call: ast.Call) -> bool:
    if attr_tail(call) not in _LOG_TAILS:
        return False
    text = " ".join(_literal_text(a) for a in call.args)
    return any(w in text for w in _DROP_WORDS)


def _is_drop_counter(node: ast.AugAssign) -> bool:
    if not isinstance(node.op, ast.Add):
        return False
    target = node.target
    name = ""
    if isinstance(target, ast.Attribute):
        name = target.attr
    elif isinstance(target, ast.Name):
        name = target.id
    return "drop" in name.lower()


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD_NAMES
    if isinstance(t, ast.Tuple):
        return any(isinstance(el, ast.Name) and el.id in _BROAD_NAMES
                   for el in t.elts)
    return False


def _abandons_item(handler: ast.ExceptHandler, in_loop: bool) -> bool:
    """continue-after-except (or return-after-except in a loop body):
    the handler runs, then the current item is never seen again."""
    if not in_loop or not handler.body:
        return False
    last = handler.body[-1]
    return isinstance(last, (ast.Continue, ast.Return))


def _touches_ledger(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        dotted = call_name(node)
        head = dotted.split(".", 1)[0]
        if head == "ledger" or dotted.startswith("_ledger"):
            return True
        # self._ledger_pipeline() / self._ledger_error_drop(...)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr.startswith("_ledger"):
            return True
    return False


class UnledgeredDropChecker(Checker):
    name = CHECK
    description = ("event discards in runner//flusher//input//pipeline/queue/"
                   " must live in functions that record into the conservation"
                   " ledger (the static half of the zero-loss audit)")

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        relpath = "/" + mod.relpath
        if not any(scope in relpath for scope in _SCOPES):
            return
        for qn, fn in _iter_functions(mod.tree):
            anchors = list(self._anchors(fn))
            if not anchors:
                continue
            if _touches_ledger(fn):
                continue
            for line, col, what in anchors:
                yield Finding(
                    CHECK, mod.relpath, line, col,
                    f"{what} with no ledger.record/_ledger* call anywhere in "
                    "the function: this discard is invisible to the "
                    "conservation audit (an unattributed residual at "
                    "runtime)",
                    symbol=qn)

    def _anchors(self, fn: ast.AST) -> Iterator[Tuple[int, int, str]]:

        def visit(node: ast.AST, in_loop: bool) -> Iterator[
                Tuple[int, int, str]]:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue          # nested functions anchor themselves
                child_in_loop = in_loop or isinstance(
                    child, (ast.For, ast.While, ast.AsyncFor))
                if isinstance(child, ast.Call) and _is_drop_log(child):
                    yield (child.lineno, child.col_offset,
                           "discard logged here")
                elif isinstance(child, ast.AugAssign) \
                        and _is_drop_counter(child):
                    yield (child.lineno, child.col_offset,
                           "drop counter incremented here")
                elif isinstance(child, ast.ExceptHandler) \
                        and _is_broad(child) \
                        and _abandons_item(child, in_loop):
                    yield (child.lineno, child.col_offset,
                           "broad except abandons the current item "
                           "(continue/return-after-except)")
                yield from visit(child, child_in_loop)

        yield from visit(fn, False)


def _iter_functions(tree: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    from ..core import iter_functions
    return iter_functions(tree)
