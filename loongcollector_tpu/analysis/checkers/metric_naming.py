"""metric-naming: self-metric hygiene across the whole tree.

Three rules, one check name:

1. **snake_case names** — every literal name passed to ``.counter(...)`` /
   ``.gauge(...)`` / ``.histogram(...)`` must match ``[a-z][a-z0-9_]*``
   (the runtime registration in monitor/metrics.py enforces the same rule;
   this catches it before the process does).  f-string names are checked
   on their literal fragments (``f"faults_{action}_total"`` passes).

2. **one name, one kind** — a name registered as a counter in one place
   and a gauge (or histogram) in another would export the same Prometheus
   series name with two conflicting TYPEs.  Whole-program pass.

3. **record ownership** — a class that creates a ``MetricsRecord`` into a
   ``self.<attr>`` must either call ``self.<attr>.mark_deleted()``
   somewhere in the class (retiring the record when the owner stops) or
   let the record escape to an external owner (hand it to another object,
   append it to a registry — the pipeline's ``_metric_records`` pattern).
   A record that is only ever used for registration and never released
   accumulates forever in WriteMetrics across construct/stop cycles — the
   leak the FlusherRunner/SinkCircuitBreaker pair had before this PR.
   Module-level records (runtime_stats, the chaos plane) are process-
   lifetime by design and exempt.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Tuple

from ..core import (Checker, Finding, ModuleInfo, ParentMap, Program,
                    attr_tail, call_name, receiver_repr)

CHECK = "metric-naming"

_KINDS = {"counter", "gauge", "histogram"}
#: self.<attr> method calls that do not count as the record escaping
_NON_ESCAPE_TAILS = _KINDS | {"histograms", "mark_deleted", "snapshot"}

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_FRAGMENT_RE = re.compile(r"^[a-z0-9_]*$")


class _Registration:
    __slots__ = ("name", "kind", "relpath", "line", "col")

    def __init__(self, name: str, kind: str, relpath: str, line: int,
                 col: int):
        self.name = name
        self.kind = kind
        self.relpath = relpath
        self.line = line
        self.col = col


class MetricNamingChecker(Checker):
    name = CHECK
    description = ("metric names snake_case and kind-consistent; "
                   "MetricsRecords owned by a class must be mark_deleted "
                   "or escape to an owner")

    def __init__(self) -> None:
        self._registrations: List[_Registration] = []

    # -- per module ---------------------------------------------------------

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and attr_tail(node) in _KINDS
                    and node.args):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                name = arg.value
                self._registrations.append(_Registration(
                    name, attr_tail(node), mod.relpath, node.lineno,
                    node.col_offset))
                if not _NAME_RE.match(name):
                    yield Finding(
                        CHECK, mod.relpath, node.lineno, node.col_offset,
                        f"metric name {name!r} is not snake_case "
                        "([a-z][a-z0-9_]*)")
            elif isinstance(arg, ast.JoinedStr):
                for part in arg.values:
                    if isinstance(part, ast.Constant) and \
                            isinstance(part.value, str) and \
                            not _FRAGMENT_RE.match(part.value):
                        yield Finding(
                            CHECK, mod.relpath, node.lineno, node.col_offset,
                            f"metric name fragment {part.value!r} is not "
                            "snake_case ([a-z0-9_]*)")
        yield from self._check_ownership(mod)

    # -- ownership (per class) ----------------------------------------------

    def _check_ownership(self, mod: ModuleInfo) -> Iterator[Finding]:
        pm = ParentMap(mod.tree)
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            owned: Dict[str, Tuple[int, int]] = {}
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        call_name(node.value).endswith("MetricsRecord"):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self":
                            owned[tgt.attr] = (node.lineno, node.col_offset)
            if not owned:
                continue
            released, escaped = set(), set()
            for node in ast.walk(cls):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"
                        and node.attr in owned):
                    continue
                parent = pm.parent(node)
                if isinstance(parent, ast.Assign) and node in parent.targets:
                    continue                      # the creating assignment
                if isinstance(parent, ast.Attribute) and \
                        parent.value is node:
                    gp = pm.parent(parent)
                    if isinstance(gp, ast.Call) and gp.func is parent and \
                            parent.attr in _NON_ESCAPE_TAILS:
                        if parent.attr == "mark_deleted":
                            released.add(node.attr)
                        continue                  # registration/cleanup use
                escaped.add(node.attr)            # any other use: handed off
            for attr in sorted(owned):
                if attr in released or attr in escaped:
                    continue
                line, col = owned[attr]
                yield Finding(
                    CHECK, mod.relpath, line, col,
                    f"MetricsRecord in self.{attr} is never "
                    "mark_deleted()-ed and never escapes to an owner: the "
                    "record accumulates in WriteMetrics across "
                    "construct/stop cycles", symbol=cls.name)

    # -- whole program ------------------------------------------------------

    def finalize(self, program: Program) -> Iterator[Finding]:
        by_name: Dict[str, List[_Registration]] = {}
        for reg in self._registrations:
            by_name.setdefault(reg.name, []).append(reg)
        self._registrations = []
        for name, regs in sorted(by_name.items()):
            kinds = sorted({r.kind for r in regs})
            if len(kinds) <= 1:
                continue
            first = min(regs, key=lambda r: (r.relpath, r.line))
            sites = ", ".join(sorted({f"{r.relpath}:{r.line} ({r.kind})"
                                      for r in regs})[:4])
            yield Finding(
                CHECK, first.relpath, first.line, first.col,
                f"metric name {name!r} registered with conflicting kinds "
                f"{'/'.join(kinds)} — one exposition series cannot have "
                f"two types [{sites}]")
