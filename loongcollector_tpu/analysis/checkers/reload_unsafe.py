"""reload-unsafe: pipeline-owned components must be fully retirable.

The loongtenant contract (docs/static_analysis.md#reload-unsafe): a hot
reload creates generation N+1 and DRAINS generation N — so every
component a pipeline generation owns (plugins, batchers, queues, input
adapters, dispatch helpers) dies many times over one agent lifetime, not
once at exit.  A ``stop()``/``release()`` that leaves anything behind is
no longer a shutdown quirk; it is a per-reload leak that accumulates
with config churn:

  1. **registration leak** — a class that calls ``<registry>.register(...)``
     (TimeoutFlushManager hooks, input-runner jobs, JMX/telegraf
     managers) must also call ``.unregister(...)`` somewhere in the SAME
     class; otherwise the dead generation stays referenced (and keeps
     being driven) forever.
  2. **held device/ring/budget hold** — a class that parks the result of
     a ``.submit(...)`` (DeviceFuture) or ``.lease(...)`` (ring slot) in
     ``self``-held state (direct assignment, or appended/stored into a
     ``self`` container, directly or via a local variable) must contain
     a settle path — a ``.result()``, ``.release()`` or ``.take()`` call
     — or the hold outlives the generation and strands plane budget /
     ring slots (the round-5 PendingParse leak shape, cross-method).
  3. **unretirable private record** — a class with a ``stop()`` or
     ``release()`` lifecycle that creates a ``MetricsRecord`` into a
     PRIVATE attribute (``self._x``) must call ``.mark_deleted()``
     somewhere in the class: a private record cannot be retired by an
     owner, so the class itself must do it (public ``self.metrics``
     records may escape to an owning pipeline — metric-naming's
     ownership rule covers those).

Escape: ``# loonglint: disable=reload-unsafe`` with a justification, for
process-lifetime singletons that genuinely outlive every generation.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set, Tuple

from ..core import Checker, Finding, ModuleInfo, attr_tail

CHECK = "reload-unsafe"

_SCOPE = ("/pipeline/", "/runner/", "/flusher/", "/aggregator/",
          "/input/", "/processor/", "/ops/")
_HOLD_TAILS = {"submit", "lease"}
_SETTLE_TAILS = {"result", "release", "take", "mark_deleted"}
_LIFECYCLE = {"stop", "release", "close", "mark_deleted"}


def _self_attr(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return ""


def _is_hold_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and attr_tail(node) in _HOLD_TAILS


def _contains_any_name(node: ast.AST, names: Set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in names:
            return True
        if _is_hold_call(sub):
            return True
    return False


def _walk_class(cls: ast.ClassDef):
    """Walk a class WITHOUT descending into nested ClassDefs: an inner
    class's sites belong to the inner class (which is scanned on its
    own), never to the enclosing one."""
    stack = list(cls.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class _ClassScan:
    """One pass over a class body collecting the evidence all three
    rules need.  Sites are deduped: a closure nested in a method is
    reachable both from the method walk and as its own FunctionDef."""

    def __init__(self, cls: ast.ClassDef):
        self.cls = cls
        self.register_sites: List[ast.Call] = []
        self.has_unregister = False
        self.hold_sites: List[Tuple[int, int, str]] = []
        self.has_settle = False
        self.private_record_sites: List[Tuple[int, int, str]] = []
        self.has_mark_deleted = False
        self.lifecycle_methods: Set[str] = set()
        self._seen_holds: Set[Tuple[int, int, str]] = set()
        self._seen_records: Set[Tuple[int, int, str]] = set()
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in _LIFECYCLE:
                self.lifecycle_methods.add(node.name)
        for fn in _walk_class(cls):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(fn)
        for node in _walk_class(cls):
            if not isinstance(node, ast.Call):
                continue
            tail = attr_tail(node)
            if tail == "register":
                self.register_sites.append(node)
            elif tail == "unregister":
                self.has_unregister = True
            elif tail in _SETTLE_TAILS:
                self.has_settle = True
                if tail == "mark_deleted":
                    self.has_mark_deleted = True

    def _note_hold(self, line: int, col: int, attr: str) -> None:
        key = (line, col, attr)
        if key not in self._seen_holds:
            self._seen_holds.add(key)
            self.hold_sites.append(key)

    def _note_record(self, line: int, col: int, attr: str) -> None:
        key = (line, col, attr)
        if key not in self._seen_records:
            self._seen_records.add(key)
            self.private_record_sites.append(key)

    def _scan_function(self, fn: ast.AST) -> None:
        # local names assigned from a submit()/lease() call in this
        # function — a self-container storing one of them is a held hold
        hold_names: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _is_hold_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        hold_names.add(t.id)
                    attr = _self_attr(t)
                    if attr:
                        self._note_hold(node.lineno, node.col_offset,
                                        attr)
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    attr_tail(node.value) == "MetricsRecord":
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr.startswith("_"):
                        self._note_record(node.lineno, node.col_offset,
                                          attr)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            tail = attr_tail(node)
            recv = node.func.value if isinstance(node.func, ast.Attribute) \
                else None
            if tail in ("append", "appendleft", "add", "put") \
                    and recv is not None and _self_attr(recv):
                for arg in node.args:
                    if _contains_any_name(arg, hold_names):
                        self._note_hold(node.lineno, node.col_offset,
                                        _self_attr(recv))
                        break
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    # self._slots[k] = fut AND the direct
                    # self._slots[k] = plane.submit(...) both count —
                    # _contains_any_name matches hold calls too
                    if isinstance(t, ast.Subscript) and \
                            _self_attr(t.value) and \
                            _contains_any_name(node.value, hold_names):
                        self._note_hold(node.lineno, node.col_offset,
                                        _self_attr(t.value))


class ReloadUnsafeChecker(Checker):
    name = CHECK
    description = ("pipeline-owned components' stop()/release() must "
                   "unregister registry hooks, settle self-held device/"
                   "ring holds, and retire private metric records — a "
                   "hot reload retires components per generation, so "
                   "any leak here accumulates with config churn")

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        relpath = "/" + mod.relpath
        if not any(s in relpath for s in _SCOPE):
            return
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            scan = _ClassScan(cls)
            if scan.register_sites and not scan.has_unregister \
                    and not self._defines_register(cls):
                site = scan.register_sites[0]
                yield Finding(
                    CHECK, mod.relpath, site.lineno, site.col_offset,
                    f"class {cls.name} registers into a registry but "
                    "never calls .unregister(...): a retired pipeline "
                    "generation stays referenced (and driven) forever",
                    symbol=cls.name)
            if scan.hold_sites and not scan.has_settle:
                for line, col, attr in scan.hold_sites:
                    yield Finding(
                        CHECK, mod.relpath, line, col,
                        f"class {cls.name} parks a .submit()/.lease() "
                        f"hold in self.{attr} but has no "
                        ".result()/.release()/.take() settle path: the "
                        "hold outlives the generation and strands plane "
                        "budget / ring slots on every reload",
                        symbol=f"{cls.name}.{attr}")
            if scan.private_record_sites and scan.lifecycle_methods \
                    and not scan.has_mark_deleted:
                for line, col, attr in scan.private_record_sites:
                    yield Finding(
                        CHECK, mod.relpath, line, col,
                        f"class {cls.name} owns a PRIVATE MetricsRecord "
                        f"self.{attr} and has a "
                        f"{sorted(scan.lifecycle_methods)} lifecycle but "
                        "never mark_deleted()s it: every reload leaks a "
                        "live record into WriteMetrics",
                        symbol=f"{cls.name}.{attr}")

    @staticmethod
    def _defines_register(cls: ast.ClassDef) -> bool:
        """The registry CLASS itself (defines register/unregister
        methods) is the callee, not a leaking caller."""
        names = {node.name for node in cls.body
                 if isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
        return "unregister" in names or "register" in names
