"""stamp-propagation: derived event groups must carry the ingest stamp.

The loongslo invariant (docs/observability.md#freshness-slo-plane) is that
every ``PipelineEventGroup`` admitted at the ledger's ``ingest`` boundary
carries a monotonic-ns stamp in its group metadata
(``EventGroupMetaKey.INGEST_NS``), and every group DERIVED from it — split,
re-routed, re-bucketed — inherits that stamp, so the sojourn observed at
the terminal ack is ingest→flush, not last-copy→flush.  A derived group
constructed without the stamp silently exits the freshness books: its
events deliver, but the SLO plane never sees them land, so the per-pipeline
freshness watermark (and the burn-rate alerts keyed on it) go quietly
blind for that traffic slice.

What marks a construction as "derived": the argument expression of a
``PipelineEventGroup(...)`` call mentions another group's ``.source_buffer``
— borrowing an existing arena is what split/re-route/re-bucket sites do,
and is exactly the shape where events admitted under one stamp re-emerge
in a fresh group.  Constructions over a NEW ``SourceBuffer()`` (inputs
minting groups, aggregator rollups emitting at window close, the multiline
carry flush) are genuinely new admissions — they are stamped at the ingest
hook or via ``slo.ensure_stamp`` at the rollup's send boundary, and are
deliberately not this checker's business.

A function containing a derived construction must also contain a stamp
carrier — any of:

  1. a ``copy_meta_to`` call (the models-layer metadata copier: carries
     ALL group metadata, the stamp included);
  2. a ``_group_meta``/``_copy_group_meta`` helper call (the aggregator
     family's per-bucket metadata copier);
  3. a ``set_metadata`` call whose arguments mention ``INGEST_NS``
     (manual re-stamping);
  4. a call into the ``slo`` module (``slo.ensure_stamp`` /
     ``slo.stamp_ingest`` — the site mints its own stamp).

Function granularity is deliberate (the unledgered-drop argument): the
copier often runs a line or two after the constructor, sometimes behind a
helper — the rule is "this derivation path knows the stamp exists", not
"the copy is adjacent".

Escape: ``# loonglint: disable=stamp-propagation`` with a justification,
for derived groups that never cross a terminal ack (debug/test scaffolding,
groups consumed before the sender path).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Tuple

from ..core import Checker, Finding, ModuleInfo, attr_tail, call_name, \
    iter_functions

CHECK = "stamp-propagation"

_COPY_TAILS = {"copy_meta_to", "_group_meta", "_copy_group_meta"}


def _is_derived_construction(node: ast.Call) -> Optional[ast.Call]:
    """The call constructs a PipelineEventGroup over another group's
    arena: ``PipelineEventGroup(<expr involving .source_buffer>)``."""
    name = call_name(node)
    if name.rsplit(".", 1)[-1] != "PipelineEventGroup":
        return None
    for arg in list(node.args) + [kw.value for kw in node.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Attribute) \
                    and sub.attr == "source_buffer":
                return node
    return None


def _carries_stamp(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        tail = attr_tail(node)
        if tail in _COPY_TAILS:
            return True
        if tail == "set_metadata":
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Attribute) \
                            and sub.attr == "INGEST_NS":
                        return True
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str) \
                            and "ingest" in sub.value.lower():
                        return True
        dotted = call_name(node)
        if dotted.split(".", 1)[0] == "slo":
            return True
    return False


class StampPropagationChecker(Checker):
    name = CHECK
    description = ("groups constructed over another group's source_buffer"
                   " (split/re-route/re-bucket) must carry the loongslo"
                   " ingest-stamp metadata — copy_meta_to/_group_meta/"
                   "explicit re-stamp — or the derived events exit the"
                   " freshness SLO books unobserved")

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        for qn, fn in _derivation_scopes(mod.tree):
            sites = [node for node in ast.walk(fn)
                     if isinstance(node, ast.Call)
                     and _is_derived_construction(node)
                     and not _in_nested_function(fn, node)]
            if not sites:
                continue
            if _carries_stamp(fn):
                continue
            for node in sites:
                yield Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    "group derived from another group's source_buffer with "
                    "no metadata carrier (copy_meta_to/_group_meta/"
                    "set_metadata(INGEST_NS)/slo.ensure_stamp) anywhere in "
                    "the function: the ingest stamp is lost and the events "
                    "leave the freshness SLO books",
                    symbol=qn)


def _derivation_scopes(tree: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """Every function, plus the module itself for top-level code."""
    yield "<module>", tree
    yield from iter_functions(tree)


def _in_nested_function(scope: ast.AST, node: ast.Call) -> bool:
    """True when `node` lives inside a function nested under `scope`
    (including, for the module pseudo-scope, any function at all) — the
    inner function is its own derivation scope and anchors itself."""
    for fn in ast.walk(scope):
        if fn is scope or not isinstance(fn, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)):
            continue
        for sub in ast.walk(fn):
            if sub is node:
                return True
    return False
