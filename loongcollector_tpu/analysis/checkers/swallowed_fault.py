"""swallowed-fault: broad exception handlers that silently discard errors
in the send paths (flusher/ and runner/).

A bare ``except:`` / ``except Exception:`` whose body is only ``pass`` or
``continue`` eats every failure signal — including the typed faults the
loongchaos plane injects: a storm that "passes" because the faults vanished
into a silent handler proves nothing.  In the send paths specifically,
a swallowed error is also a swallowed payload: no retry verdict, no
breaker feedback, no alarm.

Flagged:   broad handler (bare, Exception, BaseException — alone or in a
           tuple) whose body contains nothing but pass/continue.
Exempt:    handlers whose ``try`` body is pure teardown (every statement a
           close/shutdown/cancel-style call) — best-effort cleanup of a
           resource that is being discarded has no signal to preserve.
Escape:    ``# loonglint: disable=swallowed-fault`` with a justification,
           for the rare deliberate fallback (e.g. the native-CRC probe).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Tuple

from ..core import Checker, Finding, ModuleInfo, attr_tail, iter_functions

CHECK = "swallowed-fault"

_SCOPES = ("/flusher/", "/runner/")
_BROAD_NAMES = {"Exception", "BaseException"}
_CLEANUP_TAILS = {"close", "shutdown", "cancel", "unlink", "stop",
                  "terminate", "kill", "remove"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD_NAMES
    if isinstance(t, ast.Tuple):
        return any(isinstance(el, ast.Name) and el.id in _BROAD_NAMES
                   for el in t.elts)
    return False


def _swallows(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(stmt, (ast.Pass, ast.Continue))
               for stmt in handler.body)


def _cleanup_only(try_body: List[ast.stmt]) -> bool:
    for stmt in try_body:
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and attr_tail(stmt.value) in _CLEANUP_TAILS):
            return False
    return bool(try_body)


class SwallowedFaultChecker(Checker):
    name = CHECK
    description = ("no broad except-pass/continue in flusher/ and runner/ "
                   "send paths (they eat injected faults silently)")

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        relpath = "/" + mod.relpath
        if not any(scope in relpath for scope in _SCOPES):
            return
        funcs: List[Tuple[str, ast.AST]] = list(iter_functions(mod.tree))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not (_is_broad(handler) and _swallows(handler)):
                    continue
                if _cleanup_only(node.body):
                    continue
                yield Finding(
                    CHECK, mod.relpath, handler.lineno, handler.col_offset,
                    "broad exception swallowed (pass/continue): failures "
                    "and injected faults die here with no retry verdict, "
                    "breaker feedback or alarm",
                    symbol=self._enclosing(funcs, handler))

    @staticmethod
    def _enclosing(funcs: List[Tuple[str, ast.AST]], node: ast.AST) -> str:
        best = ""
        for qn, fn in funcs:
            if (fn.lineno <= node.lineno
                    and node.lineno <= (fn.end_lineno or fn.lineno)):
                best = qn      # innermost wins: iteration is outside-in
        return best
