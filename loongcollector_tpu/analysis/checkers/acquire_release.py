"""acquire-release: budget/slot/token acquisition must release on all paths.

The round-5 shape this exists to catch (ops/regex/engine.py,
PendingParse.dispatch pre-fix): a loop submits device chunks through
DevicePlane.submit — each submit acquires in-flight byte budget that only
DeviceFuture.result() releases — and appends the futures to a pending
list.  If pack/submit raises mid-loop, the already-submitted futures are
abandoned, the budget never returns, and every later dispatch stalls
forever: a liveness bug with no crash.

Rule: a call to an acquire API whose returned obligation ESCAPES the
statement (stored into a container/attribute, or made in a loop) must be
lexically covered by a try that can discharge the obligation — a
``finally``, or an ``except`` handler that calls a release API (result /
release / drain / clear of the pending container) before re-raising.
A straight-line ``fut = plane.submit(...); fut.result()`` is fine: nothing
can raise between acquisition and the consume point taking ownership.

Acquire APIs (attr call + receiver filter, to stay quiet on unrelated
``.submit`` methods):

  .submit(...)    when the receiver mentions a device plane, or the call
                  passes the plane-protocol kwargs (nbytes / on_wait)
  ._acquire(...)  the raw budget primitive, same escape rules
  .lease(...)     loongstream batch-ring slots (receiver mentions a ring
                  OR a chip lane — loongmesh workers lease per-lane slots
                  on the same API): a leased BatchSlot escaping the
                  statement must be releasable on every path, exactly
                  like plane budget — a mid-loop pack/submit exception
                  (or an injected chip-lane fault raising between lease
                  and the pending append) that strands leased slots
                  starves the ring's pools and breaks the storm
                  conservation invariant (ring.leased_total() == 0)

loongfuse compile-cache handles (modules under ops/regex/): `open(...)`
and `np.load(...)` must be `with`-guarded (or try/finally-closed) — the
fused-DFA persistence path runs at pipeline (re)load, where a half-written
npz or a leaked handle survives for the process lifetime.  Stricter than
the escape rules above on purpose: cache I/O has no hot-path excuse to
hold a raw handle.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Tuple

from ..core import (Checker, Finding, ModuleInfo, ParentMap, attr_tail,
                    iter_functions, receiver_repr)

CHECK = "acquire-release"

_RELEASE_ATTRS = {
    "result", "release", "_release", "on_done", "drain", "close",
    "force_release", "_drain_one", "clear", "cancel",
}


def _is_acquire_call(node: ast.Call) -> bool:
    tail = attr_tail(node)
    if tail == "_acquire":
        return True
    if tail == "lease":
        # ring-slot leases: `ring.lease(B, L)` / `batch_ring().lease(...)`,
        # and loongmesh per-lane leases (`lane.ring.lease(...)`, a
        # lane-keyed pool, or a chip-lane wrapper exposing .lease)
        recv = receiver_repr(node).lower()
        return "ring" in recv or "lane" in recv
    if tail != "submit":
        return False
    recv = receiver_repr(node).lower()
    if "plane" in recv:
        return True
    kwargs = {kw.arg for kw in node.keywords}
    return bool(kwargs & {"nbytes", "on_wait", "should_abort"})


def _guarding_try(parents: ParentMap, node: ast.AST,
                  func: ast.AST) -> bool:
    """True when an enclosing try (inside `func`) can discharge the
    obligation: it has a finally, or an except handler whose body reaches a
    release API call."""
    for anc in parents.ancestors(node):
        if anc is func:
            return False
        if isinstance(anc, ast.Try):
            if anc.finalbody:
                return True
            for handler in anc.handlers:
                for sub in ast.walk(handler):
                    if isinstance(sub, ast.Call) \
                            and attr_tail(sub) in _RELEASE_ATTRS:
                        return True
    return False


def _escapes(parents: ParentMap, node: ast.Call, func: ast.AST) -> str:
    """Does the acquired obligation outlive the statement in a way a later
    exception would strand?  Returns a reason string, or ''. """
    in_loop = any(isinstance(a, (ast.For, ast.While, ast.AsyncFor))
                  for a in _up_to(parents, node, func))
    parent = parents.parent(node)
    # plane.submit(...) used directly as an append/add argument
    if isinstance(parent, ast.Call) and \
            attr_tail(parent) in ("append", "add", "appendleft"):
        return "stored into a pending container"
    stmt = parent
    while stmt is not None and not isinstance(stmt, ast.stmt):
        stmt = parents.parent(stmt)
    if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        for tgt in targets:
            if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                return "stored into an attribute/container"
    # `fut = submit(...)` then `pending.append(fut)` inside a loop is
    # covered by the loop rule: any iteration after the first can raise
    # while earlier futures are still owned
    if in_loop:
        return "acquired in a loop"
    return ""


def _up_to(parents: ParentMap, node: ast.AST, func: ast.AST):
    for anc in parents.ancestors(node):
        if anc is func:
            return
        yield anc


def _is_cache_handle_call(node: ast.Call) -> bool:
    """open() / np.load() in the fused compile-cache modules."""
    if isinstance(node.func, ast.Name) and node.func.id == "open":
        return True
    if attr_tail(node) == "load":
        recv = receiver_repr(node).lower()
        return recv in ("np", "numpy")
    return False


def _is_with_item(parents: ParentMap, node: ast.AST) -> bool:
    return isinstance(parents.parent(node), ast.withitem)


class AcquireReleaseChecker(Checker):
    name = CHECK
    description = ("device-budget / slot acquisition must release on all "
                   "paths (try/finally or except-drain); fuse compile-"
                   "cache file handles must be with-guarded")

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        parents = ParentMap(mod.tree)
        cache_scope = "ops/regex/" in mod.relpath
        for qualname, func in iter_functions(mod.tree):
            if cache_scope:
                for node in ast.walk(func):
                    if not (isinstance(node, ast.Call)
                            and _is_cache_handle_call(node)):
                        continue
                    owner = next(
                        (a for a in parents.ancestors(node)
                         if isinstance(a, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))), None)
                    if owner is not func:
                        continue
                    if _is_with_item(parents, node) \
                            or _guarding_try(parents, node, func):
                        continue
                    yield Finding(
                        CHECK, mod.relpath, node.lineno, node.col_offset,
                        "compile-cache file handle opened outside `with` "
                        "and without try/finally: a failure mid-write "
                        "leaks the handle (and can leave a torn cache "
                        "entry) for the process lifetime",
                        symbol=qualname)
            calls: List[Tuple[ast.Call, str]] = []
            for node in ast.walk(func):
                if isinstance(node, ast.Call) and _is_acquire_call(node):
                    # skip calls that belong to a nested def; they are
                    # reported against that def's own iteration
                    owner = next(
                        (a for a in parents.ancestors(node)
                         if isinstance(a, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))), None)
                    if owner is func:
                        calls.append((node, attr_tail(node)))
            for node, tail in calls:
                reason = _escapes(parents, node, func)
                if not reason:
                    continue
                if _guarding_try(parents, node, func):
                    continue
                what = ("ring slot leased" if tail == "lease"
                        else "budget acquired")
                stranded = ("the leased ring slot"
                            if tail == "lease" else "the in-flight budget")
                yield Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    f"{what} via .{tail}() {reason} with no "
                    "enclosing try/finally or except-drain: an exception "
                    f"here strands {stranded} (the "
                    "PendingParse.dispatch leak shape)",
                    symbol=qualname)
