"""per-row-parse: per-row Python parsing inside columnar-capable plugins.

loongstruct's contract (docs/performance.md "Structural-index parsing"):
columnar groups parse through whole-buffer passes — the native structural
index, the device kernel, or a vectorised numpy emitter.  A `json.loads`
or CSV-FSM call sitting inside a loop in a columnar-capable processor
body re-introduces exactly the per-row Python tail this plane retired
(BENCH_r09: JSON at 497 MB/s against 1328 for simple-line, because every
escape-bearing row dropped to `json.loads`).

Flagged inside any class body declaring ``supports_columnar = True``:

* ``json.loads(...)`` calls within a ``for``/``while`` loop or a
  comprehension / generator expression;
* calls to a per-row split helper (``*_fsm_split``) within the same.

Loops are what make these per-ROW: a single bounded probe (schema
discovery) outside a loop is fine.  Escape:
``# loonglint: disable=per-row-parse`` with a justification — the counted
fallback tiers (malformed rows demoted off the structural plane, deviant
rows under the numpy index) carry it, because they are the DESIGNED
slow path: counted in ``parse_fallback_rows_total`` and alarmed via
``PARSE_FALLBACK_DEGRADED`` when sustained.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Tuple

from ..core import Checker, Finding, ModuleInfo, attr_tail, iter_functions
from .hot_path_materialize import _columnar_capable_classes

CHECK = "per-row-parse"


def _is_json_loads(node: ast.Call) -> bool:
    fn = node.func
    return (isinstance(fn, ast.Attribute) and fn.attr == "loads"
            and isinstance(fn.value, ast.Name) and fn.value.id == "json")


def _is_fsm_split(node: ast.Call) -> bool:
    fn = node.func
    name = fn.id if isinstance(fn, ast.Name) else attr_tail(node)
    return bool(name) and name.endswith("_fsm_split")


class PerRowParseChecker(Checker):
    name = CHECK
    description = ("no per-row Python parsing (json.loads / CSV-FSM calls "
                   "inside loops) in columnar-capable plugin bodies — "
                   "parse from the structural index, or justify the "
                   "counted fallback tier with a disable comment")

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        funcs: List[Tuple[str, ast.AST]] = list(iter_functions(mod.tree))
        loop_nodes = (ast.For, ast.While, ast.ListComp, ast.SetComp,
                      ast.DictComp, ast.GeneratorExp)
        for cls in _columnar_capable_classes(mod.tree):
            for loop in ast.walk(cls):
                if not isinstance(loop, loop_nodes):
                    continue
                for node in ast.walk(loop):
                    if not isinstance(node, ast.Call):
                        continue
                    if _is_json_loads(node):
                        what = "json.loads"
                    elif _is_fsm_split(node):
                        what = "per-row FSM split"
                    else:
                        continue
                    yield Finding(
                        CHECK, mod.relpath, node.lineno, node.col_offset,
                        f"{what} inside a loop in a columnar-capable "
                        "plugin body: rows parse per-event here — use the "
                        "structural-index plane (native/"
                        "ops.kernels.struct_index), or justify the "
                        "counted fallback tier with a disable comment",
                        symbol=self._enclosing(funcs, node))

    @staticmethod
    def _enclosing(funcs: List[Tuple[str, ast.AST]], node: ast.AST) -> str:
        best = ""
        for qn, fn in funcs:
            if (fn.lineno <= node.lineno
                    and node.lineno <= (fn.end_lineno or fn.lineno)):
                best = qn
        return best
