"""hot-path-materialize: per-event object churn on the columnar fast path.

loongcolumn's contract (docs/performance.md "Columnar event path"): groups
flow as arena-span columns from ingest to sink, and per-event Python
objects are minted ONLY at the instance-wrapper boundary of a plugin that
declared no columnar support — explicitly, counted in
``models.churn_stats()``.  Code in the hot scopes below that touches the
materializing surface silently re-introduces exactly the per-event
allocation the columnar plane removed (BENCH_r08: the dict path spent its
time building ``_contents`` tuples, not parsing).

Flagged in ``ops/`` and ``pipeline/serializer/`` (the device + wire hot
scopes):

* ``group.events`` attribute reads — the property materializes lazily;
* ``.materialize(...)`` / ``.to_dict(...)`` calls;
* per-event object construction (``LogEvent()`` … / ``add_log_event()`` …).

Flagged inside any class body declaring ``supports_columnar = True``
(columnar-capable processor/flusher plugins, wherever they live):

* ``.materialize(...)`` / ``.to_dict(...)`` calls and per-event object
  construction — a plugin that DECLARED it keeps groups columnar must not
  mint row objects in its own body.  (Plain ``.events`` reads stay legal
  there: capable plugins carry a row-path fallback for groups that arrive
  already materialized.)

Escape: ``# loonglint: disable=hot-path-materialize`` with a
justification — the canonical dict-path fallbacks in the serializers (the
non-ASCII / event-group routes json.dumps semantics require) and the
ingest-side PB decode carry it.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Tuple

from ..core import Checker, Finding, ModuleInfo, attr_tail, iter_functions

CHECK = "hot-path-materialize"

_SCOPES = ("/ops/", "/pipeline/serializer/")
_EVENT_CTORS = {"LogEvent", "MetricEvent", "SpanEvent", "RawEvent"}
_EVENT_ADDERS = {"add_log_event", "add_metric_event", "add_span_event",
                 "add_raw_event"}
_MATERIALIZING_CALLS = {"to_dict", "materialize"}


def _is_event_construction(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in _EVENT_CTORS:
        return True
    return attr_tail(node) in _EVENT_ADDERS


def _columnar_capable_classes(tree: ast.AST) -> List[ast.ClassDef]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id == "supports_columnar"
                            for t in stmt.targets) \
                    and isinstance(stmt.value, ast.Constant) \
                    and stmt.value.value is True:
                out.append(node)
                break
    return out


class HotPathMaterializeChecker(Checker):
    name = CHECK
    description = ("no per-event object materialization (.events reads, "
                   ".to_dict()/materialize() calls, LogEvent construction) "
                   "in ops/, pipeline/serializer/, or columnar-capable "
                   "plugin bodies")

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        relpath = "/" + mod.relpath
        funcs: List[Tuple[str, ast.AST]] = list(iter_functions(mod.tree))
        if any(scope in relpath for scope in _SCOPES):
            yield from self._check_scope(mod, mod.tree, funcs,
                                         flag_events_read=True)
            return
        # columnar-capable plugin bodies anywhere else in the tree
        for cls in _columnar_capable_classes(mod.tree):
            yield from self._check_scope(mod, cls, funcs,
                                         flag_events_read=False)

    def _check_scope(self, mod: ModuleInfo, root: ast.AST, funcs,
                     flag_events_read: bool) -> Iterator[Finding]:
        for node in ast.walk(root):
            if flag_events_read and isinstance(node, ast.Attribute) \
                    and node.attr == "events" \
                    and isinstance(node.ctx, ast.Load):
                yield Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    ".events read in a hot scope: the property "
                    "materializes per-event objects lazily — read span "
                    "columns (group.columns / group._events) instead, or "
                    "justify the dict fallback with a disable comment",
                    symbol=self._enclosing(funcs, node))
                continue
            if not isinstance(node, ast.Call):
                continue
            tail = attr_tail(node)
            if tail in _MATERIALIZING_CALLS:
                yield Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    f".{tail}() in a hot scope: materialization belongs "
                    "to the instance-wrapper boundary (counted in "
                    "models.churn_stats()), never inside the columnar "
                    "fast path",
                    symbol=self._enclosing(funcs, node))
            elif _is_event_construction(node):
                yield Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    "per-event object construction in a hot scope: the "
                    "columnar plane carries rows as arena spans — build "
                    "column vectors, not LogEvent objects",
                    symbol=self._enclosing(funcs, node))

    @staticmethod
    def _enclosing(funcs: List[Tuple[str, ast.AST]], node: ast.AST) -> str:
        best = ""
        for qn, fn in funcs:
            if (fn.lineno <= node.lineno
                    and node.lineno <= (fn.end_lineno or fn.lineno)):
                best = qn      # innermost wins: iteration is outside-in
        return best
