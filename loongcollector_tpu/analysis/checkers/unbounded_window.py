"""unbounded-window: aggregator window state must be bounded AND counted.

The loongagg contract (docs/static_analysis.md#unbounded-window): any
dict/map held as WINDOW STATE by a class in ``aggregator/`` accumulates
one entry per distinct key — at production metric cardinalities that is
an unbounded heap unless the class (a) evicts under a cap or TTL and
(b) counts what it evicts.  A windowed aggregator that silently grows is
the classic slow-OOM; one that evicts silently is the classic silent
data-skew.  Both halves are therefore required, statically:

For every ``self.<attr> = {}`` assignment in a class defined under
``aggregator/``, the SAME class must contain all three of:

  1. an **eviction site** on that attribute — ``del self.<attr>[...]``,
     ``self.<attr>.pop(...)`` or ``self.<attr>.clear()``;
  2. a **bound comparison** — any comparison referencing a name/attribute
     whose (lowercased) name mentions a cap or TTL vocabulary token
     (``max``/``cap``/``ttl``/``timeout``/``lateness``) — the evidence
     that eviction is driven by a limit, not an incidental delete;
  3. a **counted metric** — a ``....add(...)`` call whose receiver is a
     ``.counter(...)`` registration or a ``self._m_*`` /
     ``*counter*``-named attribute (the repo's two counter idioms), so
     every eviction/rotation is visible in /metrics.

Escape: ``# loonglint: disable=unbounded-window`` with a justification,
for dicts that are not keyed by event-derived values (config tables,
substrate caches with their own bounds elsewhere).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Tuple

from ..core import Checker, Finding, ModuleInfo, attr_tail

CHECK = "unbounded-window"

_SCOPE = "/aggregator/"
_BOUND_TOKENS = ("max", "cap", "ttl", "timeout", "lateness")
_EVICT_TAILS = {"pop", "clear", "popitem"}


def _self_attr(node: ast.AST) -> str:
    """'attr' for a `self.attr` expression, else ''."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return ""


def _dict_state_attrs(cls: ast.ClassDef) -> List[Tuple[str, int, int]]:
    """(attr, line, col) for every `self.X = {}` / `self.X: T = {}`
    assignment anywhere in the class body (methods included)."""
    out = []
    seen = set()
    for node in ast.walk(cls):
        targets: List[ast.AST] = []
        value = None
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        if not isinstance(value, (ast.Dict,)) or value.keys:
            continue
        for t in targets:
            attr = _self_attr(t)
            if attr and attr not in seen:
                seen.add(attr)
                out.append((attr, node.lineno, node.col_offset))
    return out


def _has_evict_site(cls: ast.ClassDef, attr: str) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        _self_attr(t.value) == attr:
                    return True
        elif isinstance(node, ast.Call) and \
                attr_tail(node) in _EVICT_TAILS and \
                isinstance(node.func, ast.Attribute) and \
                _self_attr(node.func.value) == attr:
            return True
    return False


def _has_bound_compare(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if not isinstance(node, ast.Compare):
            continue
        for sub in ast.walk(node):
            name = ""
            if isinstance(sub, ast.Attribute):
                name = sub.attr
            elif isinstance(sub, ast.Name):
                name = sub.id
            low = name.lower()
            if any(tok in low for tok in _BOUND_TOKENS):
                return True
    return False


def _has_counter_add(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call) or attr_tail(node) != "add":
            continue
        recv = node.func.value if isinstance(node.func, ast.Attribute) \
            else None
        if recv is None:
            continue
        # metrics.counter("...").add(n)
        if isinstance(recv, ast.Call) and attr_tail(recv) == "counter":
            return True
        # self._m_evicted.add(1) / self.evict_counter.add(1)
        rname = _self_attr(recv) or (recv.attr if isinstance(
            recv, ast.Attribute) else "")
        if rname.startswith("_m_") or "counter" in rname.lower():
            return True
    return False


class UnboundedWindowChecker(Checker):
    name = CHECK
    description = ("dict window state in aggregator/ must have cap/TTL "
                   "eviction wired to a counted metric (slow-OOM and "
                   "silent-skew are both findings)")

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        relpath = "/" + mod.relpath
        if _SCOPE not in relpath:
            return
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            attrs = _dict_state_attrs(cls)
            if not attrs:
                continue
            bound = _has_bound_compare(cls)
            counted = _has_counter_add(cls)
            for attr, line, col in attrs:
                missing = []
                if not _has_evict_site(cls, attr):
                    missing.append("an eviction site (del/pop/clear)")
                if not bound:
                    missing.append("a cap/TTL bound comparison")
                if not counted:
                    missing.append("a counted metric (.counter(...).add)")
                if missing:
                    yield Finding(
                        CHECK, mod.relpath, line, col,
                        f"dict window state self.{attr} in aggregator "
                        f"class {cls.name} is missing "
                        + " and ".join(missing)
                        + ": unbounded key cardinality is a slow OOM, "
                        "uncounted eviction is silent data skew",
                        symbol=f"{cls.name}.{attr}")
