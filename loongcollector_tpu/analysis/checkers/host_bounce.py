"""host-bounce: host pulls between two device dispatches in one function.

The loongresident contract (docs/performance.md "Single-dispatch pipeline
fusion"): consecutive device-capable stages hand their intermediates to
each other IN HBM — one pack, one dispatch, one materialise.  A function
that dispatches a kernel, pulls the result to the host
(``np.asarray`` / ``jax.device_get`` / ``.block_until_ready()`` /
``DeviceFuture.result()``), and then dispatches again is exactly the
pack → H2D → dispatch → materialise → re-pack cycle fusion exists to
remove: each bounce costs a synchronous round trip per batch.

Flagged, in modules under ``ops/`` and in columnar-capable processor
bodies:

* a host-pull call whose statement sits BETWEEN two device-dispatch
  calls of the same function (straight-line bounce);
* a host-pull call inside a loop that also contains a device dispatch —
  the next iteration dispatches again, so the pull bounces per
  iteration.

A "device dispatch" is a call of ``donated_call`` / ``staged`` or of any
callable whose name mentions ``kernel`` (``self._dfa_kernel(...)``,
``sub_kern(...)`` …).  A single dispatch followed by one materialise is
the NORMAL end-of-pipeline shape and is never flagged.

Escape: ``# loonglint: disable=host-bounce`` with a justification — the
designed fallback tiers carry it (the per-stage demotion path a faulted
fused chunk takes, the synchronous chunked classify loops of the
degraded routes), because they are counted exception paths, not the
steady state.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Tuple

from ..core import Checker, Finding, ModuleInfo, attr_tail, iter_functions
from .hot_path_materialize import _columnar_capable_classes

CHECK = "host-bounce"

_OPS_PREFIX = "loongcollector_tpu/ops/"
_PROC_PREFIX = "loongcollector_tpu/processor/"

_PULL_TAILS = {"asarray", "device_get", "block_until_ready", "result"}
_DISPATCH_TAILS = {"donated_call", "staged"}
_DISPATCH_NAMES = {"kern", "sub_kern"}


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Name):
        return node.func.id
    return attr_tail(node)


def _is_dispatch(node: ast.Call) -> bool:
    name = _call_name(node)
    low = name.lower()
    return (name in _DISPATCH_TAILS or name in _DISPATCH_NAMES
            or "kernel" in low)


def _is_pull(node: ast.Call) -> bool:
    name = _call_name(node)
    if name not in _PULL_TAILS:
        return False
    if name == "asarray":
        # np.asarray / jnp.asarray only — a bare asarray() helper is not
        # a host pull
        fn = node.func
        return (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id in ("np", "numpy", "jnp"))
    if name == "device_get":
        fn = node.func
        return (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "jax")
    return True


class HostBounceChecker(Checker):
    name = CHECK
    description = ("no host pulls (np.asarray / jax.device_get / "
                   ".block_until_ready / future.result) between two "
                   "device dispatches in one function under ops/ or a "
                   "columnar-capable processor body — compose the stages "
                   "into a fused program (ops/fused_pipeline), or justify "
                   "the fallback tier with a disable comment")

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        if mod.relpath.startswith(_OPS_PREFIX):
            roots: List[ast.AST] = [mod.tree]
        elif mod.relpath.startswith(_PROC_PREFIX):
            roots = list(_columnar_capable_classes(mod.tree))
        else:
            return
        funcs: List[Tuple[str, ast.AST]] = []
        for root in roots:
            funcs.extend(iter_functions(root))
        seen = set()
        for qn, fn in funcs:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            yield from self._check_function(mod, qn, fn)

    def _check_function(self, mod: ModuleInfo, qualname: str,
                        fn: ast.AST) -> Iterator[Finding]:
        loops = [n for n in ast.walk(fn)
                 if isinstance(n, (ast.For, ast.While))]
        dispatch_lines: List[int] = []
        pulls: List[ast.Call] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _is_dispatch(node):
                dispatch_lines.append(node.lineno)
                # a dispatch inside a loop runs again next iteration
                for lp in loops:
                    if lp.lineno <= node.lineno <= (lp.end_lineno
                                                    or lp.lineno):
                        dispatch_lines.append(node.lineno)
                        break
            elif _is_pull(node):
                pulls.append(node)
        if len(dispatch_lines) < 2 or not pulls:
            return
        lo, hi = min(dispatch_lines), max(dispatch_lines)
        loop_spans = []
        for lp in loops:
            span = (lp.lineno, lp.end_lineno or lp.lineno)
            if any(span[0] <= dl <= span[1] for dl in dispatch_lines):
                loop_spans.append(span)
        for node in pulls:
            # flagged when a LATER dispatch exists (line < hi): its input
            # was pulled to the host and re-packed.  A pull ON the first
            # dispatch's line (`a = np.asarray(k1(...))` before `k2(a)`)
            # is the canonical straight-line bounce; a pull at/after the
            # LAST dispatch is the normal final materialise — clean.
            between = lo <= node.lineno < hi
            in_dispatch_loop = any(a <= node.lineno <= b
                                   for a, b in loop_spans)
            if not (between or in_dispatch_loop):
                continue
            yield Finding(
                CHECK, mod.relpath, node.lineno, node.col_offset,
                f"host pull ({_call_name(node)}) between device "
                "dispatches: the result bounces through the host and the "
                "next stage re-packs it — compose these stages into one "
                "fused program (ops/fused_pipeline) or justify the "
                "fallback tier with a disable comment",
                symbol=qualname)
