"""Checker registry.  Adding a checker = new module here + one entry in
_CHECKER_CLASSES (docs/static_analysis.md#adding-a-new-checker)."""

from __future__ import annotations

from typing import List

from ..core import Checker
from ..raceguard import RaceGuardChecker
from .acquire_release import AcquireReleaseChecker
from .blocking_locks import BlockingUnderLockChecker
from .host_bounce import HostBounceChecker
from .hot_path_materialize import HotPathMaterializeChecker
from .metric_naming import MetricNamingChecker
from .per_row_parse import PerRowParseChecker
from .registry_consistency import RegistryConsistencyChecker
from .reload_unsafe import ReloadUnsafeChecker
from .stamp_propagation import StampPropagationChecker
from .swallowed_fault import SwallowedFaultChecker
from .tracing_hygiene import TracingHygieneChecker
from .unbounded_window import UnboundedWindowChecker
from .unledgered_drop import UnledgeredDropChecker
from .unwatched_jit import UnwatchedJitChecker

_CHECKER_CLASSES = [
    AcquireReleaseChecker,
    BlockingUnderLockChecker,
    TracingHygieneChecker,
    RegistryConsistencyChecker,
    SwallowedFaultChecker,
    UnledgeredDropChecker,
    MetricNamingChecker,
    HotPathMaterializeChecker,
    PerRowParseChecker,
    UnboundedWindowChecker,
    HostBounceChecker,
    ReloadUnsafeChecker,
    RaceGuardChecker,
    StampPropagationChecker,
    UnwatchedJitChecker,
]


def all_checkers() -> List[Checker]:
    return [cls() for cls in _CHECKER_CLASSES]


def checker_names() -> List[str]:
    return [cls.name for cls in _CHECKER_CLASSES]
