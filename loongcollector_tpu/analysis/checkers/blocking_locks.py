"""blocking-under-lock: no blocking call while a threading lock is held,
plus a whole-program lock-ordering cycle report.

A runner thread that sleeps, joins, materialises a DeviceFuture, or does
socket I/O while holding a mutex serialises every other thread that needs
that mutex behind device/network latency — the exact anti-pattern the
device plane's "call on_wait OUTSIDE the lock" discipline exists to avoid.
And two threads that take the same two locks in opposite orders deadlock;
with runner/, pipeline/queue/ and the device plane all cross-calling each
other, that ordering is a whole-program property no single diff shows.

Lock identification and held-region tracking live in
``analysis/locktrack.py`` (shared with raceguard's whole-program
guarded-by inference, so the two checkers see locks identically):
attributes assigned from threading.Lock()/RLock()/Condition() anywhere in
the module, merged with the lock naming convention (_lock, _mutex, _cond,
...); held regions are ``with <lock>:`` bodies and ``<lock>.acquire()``
.. ``<lock>.release()`` spans within one statement list.

Blocking calls flagged under a held lock: time.sleep, Future.result,
Thread.join, blocking queue get/put, socket connect/accept/recv/sendall,
subprocess run/call/check_output, and ``.wait()`` on anything OTHER than
the held condition itself (cond.wait() releases the lock it guards — that
is the one legal blocking wait).

loongprof extends the callee set with the flight recorder: ``record()``
on a flight/recorder receiver (``flight.record``, ``self._recorder.record``,
``self.flight_recorder.record``...) must never run under a held lock —
the recorder takes its own ring lock, and wiring notable-event reporting
into arbitrary lock bodies is exactly how an observability layer becomes
a deadlock participant.  Transition sites buffer under the lock and emit
after release (runner/circuit.py's ``_emit`` pattern).

Lock ordering: edges A -> B whenever B is acquired while A is held, both
lexically nested and one interprocedural hop (a call made under A to a
method that acquires B, resolved by unique method name).  Cycles in that
graph are reported on the finalize pass.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import (Checker, Finding, ModuleInfo, Program, attr_tail,
                    call_name, iter_functions, receiver_repr)
from ..locktrack import (LockRegionWalker, ModuleLocks, expr_text,
                         tail_name)

CHECK = "blocking-under-lock"
CHECK_ORDER = "lock-ordering"

_BLOCKING_DOTTED = {"time.sleep", "subprocess.run", "subprocess.call",
                    "subprocess.check_output", "subprocess.check_call",
                    "select.select"}
_BLOCKING_TAILS = {"result", "join", "accept", "connect", "recv",
                   "recv_into", "sendall", "read_exact"}
_QUEUE_TAILS = {"get", "put"}

#: receivers whose .record() is the flight recorder (loongprof): the
#: module handle, a recorder attribute, or anything named for it
_FLIGHT_RECV_TAILS = {"flight", "recorder", "flight_recorder",
                      "_flight", "_recorder", "_flight_recorder"}


_expr_text = expr_text
_tail_name = tail_name


def _blocking_queue_call(node: ast.Call) -> bool:
    """Blocking-shaped queue call.  `x.get(key)` (a positional arg) is the
    dict API, not queue.Queue — never flagged; `x.get()` / `x.put(item)`
    without block=False/timeout are the blocking queue shapes."""
    tail = attr_tail(node)
    if tail == "get" and node.args:
        return False
    for kw in node.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return False
        if kw.arg == "timeout":
            # bounded wait: the repo's convention treats short timeouts as
            # polling; only unbounded blocking is flagged
            return False
    return True


def _blocking_reason(node: ast.Call, held: List[str]) -> Optional[str]:
    dotted = call_name(node)
    tail = attr_tail(node)
    recv = receiver_repr(node)
    if dotted in _BLOCKING_DOTTED:
        return f"{dotted}()"
    if tail == "wait":
        # cond.wait() on the held condition releases it — the legal shape
        if recv in held:
            return None
        return f"{recv or '?'}.wait()"
    if tail in _BLOCKING_TAILS:
        if tail == "result" and not recv:
            return None
        return f"{recv or '?'}.{tail}()"
    if tail == "record" and recv and \
            _tail_name(recv) in _FLIGHT_RECV_TAILS:
        return f"flight-recorder {recv}.record()"
    if tail in _QUEUE_TAILS:
        rl = recv.lower()
        if ("queue" in rl or rl.endswith("_q") or rl.split(".")[-1] == "q") \
                and _blocking_queue_call(node):
            return f"blocking {recv}.{tail}()"
    return None


class _FuncScan(LockRegionWalker):
    """One function's lock behaviour: findings + acquired-under-held edges
    + calls made under each held lock (for the interprocedural hop).
    Traversal and held-region tracking come from locktrack."""

    def __init__(self, mod: ModuleInfo, locks: ModuleLocks, qualname: str,
                 func: ast.AST):
        super().__init__(locks)
        self.mod = mod
        self.qualname = qualname
        self.findings: List[Finding] = []
        # (held_lock_text, acquired_lock_text, line)
        self.edges: List[Tuple[str, str, int]] = []
        # method names called while a lock is held: (held, callee, line)
        self.calls_under: List[Tuple[str, str, int]] = []
        self.acquires: Set[str] = set()
        self.walk(func)

    def on_acquire(self, lock: str, held: List[str], line: int) -> None:
        self.acquires.add(lock)
        for h in held:
            if _tail_name(h) != _tail_name(lock):
                self.edges.append((h, lock, line))

    def on_expr(self, expr: ast.AST, held: List[str]) -> None:
        if not held:
            return
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            reason = _blocking_reason(node, held)
            if reason is not None:
                self.findings.append(Finding(
                    CHECK, self.mod.relpath, node.lineno, node.col_offset,
                    f"blocking call {reason} while holding {held[-1]}",
                    symbol=self.qualname))
            tail = attr_tail(node)
            if tail and isinstance(node.func, ast.Attribute):
                self.calls_under.append((held[-1], tail, node.lineno))


class BlockingUnderLockChecker(Checker):
    name = CHECK
    description = ("no blocking calls while a threading lock is held; "
                   "whole-program lock-ordering cycle detection")

    @property
    def produces(self) -> frozenset:
        return frozenset((CHECK, CHECK_ORDER))

    def __init__(self) -> None:
        self._scans: List[Tuple[ModuleInfo, _FuncScan]] = []

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        locks = ModuleLocks(mod.tree)
        for qualname, func in iter_functions(mod.tree):
            scan = _FuncScan(mod, locks, qualname, func)
            self._scans.append((mod, scan))
            yield from scan.findings

    # -- lock-ordering graph -------------------------------------------------

    def finalize(self, program: Program) -> Iterator[Finding]:
        # canonical lock node: ClassOrModule.attr — approximate lock
        # identity by final attribute name qualified by the owning class
        def node_id(mod: ModuleInfo, qualname: str, lock_text: str) -> str:
            owner = qualname.rsplit(".", 2)[0] if "." in qualname else \
                mod.relpath
            if lock_text.startswith("self."):
                return f"{owner}.{_tail_name(lock_text)}"
            return f"{mod.relpath}:{_tail_name(lock_text)}"

        # method name -> lock node ids it acquires, for the 1-hop
        # interprocedural edges.  Only UNIQUELY-named lock-taking methods
        # resolve: a name like `get` or `close` defined on many classes
        # would wire unrelated locks together and fabricate cycles.
        name_count: Dict[str, int] = {}
        for _, scan in self._scans:
            mname = scan.qualname.rsplit(".", 1)[-1]
            name_count[mname] = name_count.get(mname, 0) + 1
        method_acquires: Dict[str, Set[str]] = {}
        for mod, scan in self._scans:
            mname = scan.qualname.rsplit(".", 1)[-1]
            if name_count.get(mname, 0) != 1 or not scan.acquires:
                continue
            for lk in scan.acquires:
                method_acquires.setdefault(mname, set()).add(
                    node_id(mod, scan.qualname, lk))

        edges: Dict[str, Set[str]] = {}
        where: Dict[Tuple[str, str], Tuple[str, int]] = {}

        def add_edge(a: str, b: str, mod: ModuleInfo, line: int) -> None:
            if a == b:
                return
            edges.setdefault(a, set()).add(b)
            where.setdefault((a, b), (mod.relpath, line))

        for mod, scan in self._scans:
            for held, acquired, line in scan.edges:
                add_edge(node_id(mod, scan.qualname, held),
                         node_id(mod, scan.qualname, acquired), mod, line)
            for held, callee, line in scan.calls_under:
                for target in method_acquires.get(callee, ()):
                    add_edge(node_id(mod, scan.qualname, held), target,
                             mod, line)

        yield from self._report_cycles(edges, where)

    def _report_cycles(self, edges: Dict[str, Set[str]],
                       where: Dict[Tuple[str, str], Tuple[str, int]]
                       ) -> Iterator[Finding]:
        # iterative Tarjan SCC; every SCC with >1 node is a potential
        # deadlock cycle
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        onstack: Set[str] = set()
        stack: List[str] = []
        counter = [0]
        sccs: List[List[str]] = []

        def strongconnect(root: str) -> None:
            work = [(root, iter(sorted(edges.get(root, ()))))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            onstack.add(root)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        onstack.add(w)
                        work.append((w, iter(sorted(edges.get(w, ())))))
                        advanced = True
                        break
                    if w in onstack:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    pv = work[-1][0]
                    low[pv] = min(low[pv], low[v])
                if low[v] == index[v]:
                    scc = []
                    while True:
                        w = stack.pop()
                        onstack.discard(w)
                        scc.append(w)
                        if w == v:
                            break
                    if len(scc) > 1:
                        sccs.append(scc)

        for v in sorted(edges):
            if v not in index:
                strongconnect(v)

        for scc in sccs:
            members = sorted(scc)
            a, b = members[0], members[1]
            relpath, line = where.get(
                (a, b), where.get((b, a), ("<program>", 1)))
            yield Finding(
                CHECK_ORDER, relpath, line, 0,
                "potential lock-order cycle: " + " <-> ".join(members),
                symbol="lock-graph")
