"""unwatched-jit: every ``jax.jit`` under ops// parallel/ must go through
``compile_watch.watched_jit``.

loongxprof's compile observability (per-family compile counts, wall-ms
histograms, cache hit/miss, the RECOMPILE_STORM alarm) only sees jit
entry points wrapped by :func:`compile_watch.watched_jit`.  A raw
``jax.jit(...)`` call or ``@jax.jit`` decorator in kernel code creates a
blind spot: a flapping geometry can storm XLA recompiles there for hours
and neither /debug/status compile accounting nor the storm alarm will
name it.  This checker keeps the watch total — a new kernel cannot land
with an invisible compile cache.

Flagged shapes (syntactic, per module, ops/ and parallel/ only):

  * ``jax.jit(f, ...)`` / ``jit(f, ...)`` call sites;
  * ``@jax.jit`` / ``@jit`` bare decorators;
  * ``functools.partial(jax.jit, ...)`` partial-application shapes.

``ops/compile_watch.py`` itself is exempt — the wrapper owns the one
legitimate raw ``jax.jit`` call.  A deliberately unwatched jit (e.g. a
one-shot capability probe whose compile is not a recurring cost) carries
an inline ``# loonglint: disable=unwatched-jit`` with a justification.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Checker, Finding, ModuleInfo, call_name

CHECK = "unwatched-jit"

_JIT_NAMES = ("jax.jit", "jit")


def _expr_is_jit(node: ast.expr) -> bool:
    try:
        return ast.unparse(node) in _JIT_NAMES
    except Exception:  # pragma: no cover
        return False


class UnwatchedJitChecker(Checker):
    name = CHECK
    description = ("every jax.jit under ops/ and parallel/ must go "
                   "through compile_watch.watched_jit so compile storms "
                   "stay observable")

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        rel = "/" + mod.relpath
        if "/ops/" not in rel and "/parallel/" not in rel:
            return
        if rel.endswith("/ops/compile_watch.py"):
            return      # the wrapper owns the one legitimate raw jax.jit
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call) and _expr_is_jit(dec):
                        yield Finding(
                            CHECK, mod.relpath, dec.lineno, dec.col_offset,
                            "`@jax.jit` decorator bypasses watched_jit — "
                            "its compile cache is invisible to compile "
                            "accounting and the RECOMPILE_STORM alarm",
                            symbol=node.name)
            elif isinstance(node, ast.Call):
                name = call_name(node)
                if name in _JIT_NAMES:
                    yield Finding(
                        CHECK, mod.relpath, node.lineno, node.col_offset,
                        "raw `jax.jit(...)` bypasses watched_jit — wrap "
                        "with compile_watch.watched_jit(fn, family) so "
                        "compiles are counted and storms alarm")
                elif name in ("functools.partial", "partial") and \
                        node.args and _expr_is_jit(node.args[0]):
                    yield Finding(
                        CHECK, mod.relpath, node.lineno, node.col_offset,
                        "`functools.partial(jax.jit, ...)` bypasses "
                        "watched_jit — wrap the jitted callable with "
                        "compile_watch.watched_jit(fn, family)")
