"""registry-consistency: plugin tier wiring and alarm taxonomy coherence.

Two whole-program properties the type system cannot see:

1. Processor tier wiring.  The reference keeps `_native` names for drop-in
   config compatibility and this repo adds `_tpu` aliases for the
   device-tier processors (processor/__init__.py docstring).  A `_tpu`
   registration without its `_native` sibling breaks config portability;
   siblings bound to DIFFERENT classes silently fork behaviour between
   tiers.

2. Alarm taxonomy.  Every `AlarmType.X` reference and every
   `send_alarm(...)` first argument must resolve to a member defined in
   monitor/alarms.py — a typo'd alarm type raises AttributeError on the
   ERROR path, exactly where it is never exercised by tests.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from ..core import (Checker, Finding, ModuleInfo, Program, attr_tail,
                    iter_functions)

CHECK = "registry-consistency"

_TIER_SUFFIXES = ("_native", "_tpu")


def _class_arg_name(node: ast.expr) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Lambda):
        return "<lambda>"
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover
        return "<expr>"


class RegistryConsistencyChecker(Checker):
    name = CHECK
    description = ("_native/_tpu processor registrations stay paired and "
                   "bound to one implementation; alarm sites use "
                   "AlarmType members defined in monitor/alarms.py")

    def finalize(self, program: Program) -> Iterator[Finding]:
        registrations: Dict[str, Tuple[str, str, int]] = {}
        alarm_members: Set[str] = set()
        alarm_defs_found = False

        for mod in program.modules:
            if mod.relpath.endswith("monitor/alarms.py"):
                alarm_members = self._alarm_members(mod)
                alarm_defs_found = True
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and \
                        attr_tail(node) == "register_processor" and \
                        len(node.args) >= 2 and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    registrations[node.args[0].value] = (
                        _class_arg_name(node.args[1]), mod.relpath,
                        node.lineno)

        yield from self._check_tiers(registrations)
        if alarm_defs_found:
            yield from self._check_alarm_sites(program, alarm_members)

    # -- tier wiring ---------------------------------------------------------

    def _check_tiers(self, regs: Dict[str, Tuple[str, str, int]]
                     ) -> Iterator[Finding]:
        for name, (cls, relpath, line) in sorted(regs.items()):
            for suffix in _TIER_SUFFIXES:
                if not name.endswith(suffix):
                    continue
                base = name[: -len(suffix)]
                other = base + ("_tpu" if suffix == "_native" else "_native")
                if other not in regs:
                    # _native without _tpu is the normal CPU-only case;
                    # _tpu without _native breaks config compatibility
                    if suffix == "_tpu":
                        yield Finding(
                            CHECK, relpath, line, 0,
                            f"processor `{name}` registered with no "
                            f"`{other}` sibling: device-tier configs "
                            "cannot fall back by rename",
                            symbol=name)
                    continue
                if suffix == "_tpu" and regs[other][0] != cls:
                    yield Finding(
                        CHECK, relpath, line, 0,
                        f"tier fork: `{name}` -> {cls} but `{other}` -> "
                        f"{regs[other][0]}; siblings must share one "
                        "implementation",
                        symbol=name)

    # -- alarm taxonomy ------------------------------------------------------

    @staticmethod
    def _alarm_members(mod: ModuleInfo) -> Set[str]:
        members: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and node.name == "AlarmType":
                for stmt in node.body:
                    if isinstance(stmt, ast.Assign):
                        for tgt in stmt.targets:
                            if isinstance(tgt, ast.Name):
                                members.add(tgt.id)
        return members

    def _check_alarm_sites(self, program: Program, members: Set[str]
                           ) -> Iterator[Finding]:
        for mod in program.modules:
            if mod.relpath.endswith("monitor/alarms.py"):
                continue
            func_of: List[Tuple[str, ast.AST]] = list(
                iter_functions(mod.tree))
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Attribute) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id == "AlarmType" and \
                        node.attr not in members:
                    yield Finding(
                        CHECK, mod.relpath, node.lineno, node.col_offset,
                        f"AlarmType.{node.attr} is not defined in "
                        "monitor/alarms.py",
                        symbol=self._enclosing(func_of, node))
                if isinstance(node, ast.Call) and \
                        attr_tail(node) == "send_alarm" and node.args and \
                        isinstance(node.args[0], ast.Constant):
                    yield Finding(
                        CHECK, mod.relpath, node.lineno, node.col_offset,
                        "send_alarm() called with a raw literal instead "
                        "of an AlarmType member",
                        symbol=self._enclosing(func_of, node))

    @staticmethod
    def _enclosing(funcs: List[Tuple[str, ast.AST]], node: ast.AST) -> str:
        best = ""
        for qn, fn in funcs:
            if fn.lineno <= node.lineno <= \
                    getattr(fn, "end_lineno", fn.lineno):
                best = qn
        return best
