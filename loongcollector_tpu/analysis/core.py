"""loonglint framework: module loading, suppressions, allowlist, runner.

Design: every checker sees each parsed module (`check_module`) and, after
the whole tree is parsed, the assembled `Program` (`finalize`) for
whole-program passes (lock-ordering graph, registry wiring).  Findings are
filtered through two suppression layers before they fail the run:

  1. inline ``# loonglint: disable=<check>`` comments on the flagged line;
  2. the budgeted allowlist file (one ``relpath::check[::substr]`` entry
     per line) for pre-existing debt that is tracked, not hidden.

The allowlist is deliberately small: tier-1 asserts it stays <= 10 entries
(ALLOWLIST_BUDGET), so debt can only be parked, never accumulated.
"""

from __future__ import annotations

import ast
import os
import re
import time
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

ALLOWLIST_BUDGET = 10

_SUPPRESS_RE = re.compile(r"#\s*loonglint:\s*disable=([A-Za-z0-9_,-]+)")

# directories never scanned inside the package tree
_SKIP_DIRS = {"__pycache__", "testdata"}


class Finding:
    """One rule violation at a source location."""

    __slots__ = ("check", "path", "line", "col", "message", "symbol")

    def __init__(self, check: str, path: str, line: int, col: int,
                 message: str, symbol: str = ""):
        self.check = check
        self.path = path          # repo-relative, forward slashes
        self.line = line
        self.col = col
        self.message = message
        self.symbol = symbol      # enclosing function/class, for allowlist

    def to_dict(self) -> dict:
        return {"check": self.check, "path": self.path, "line": self.line,
                "col": self.col, "symbol": self.symbol,
                "message": self.message}

    def format(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.check}:"
                f" {self.message}{sym}")

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<Finding {self.format()}>"


class ModuleInfo:
    """A parsed source module plus the bits ast drops (comment lines)."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set of check names disabled on that line
        self.suppressions: Dict[int, set] = {}
        self._standalone: set = set()   # comment-only suppression lines
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if m:
                self.suppressions[i] = {
                    c.strip() for c in m.group(1).split(",") if c.strip()}
                if text.lstrip().startswith("#"):
                    self._standalone.add(i)

    def suppressed(self, line: int, check: str) -> bool:
        """A trailing disable comment suppresses its own line; a
        comment-ONLY disable line suppresses the line below it — standard
        lint idiom, and the only option when the flagged expression spans
        lines."""
        banned = self.suppressions.get(line)
        if banned and (check in banned or "all" in banned):
            return True
        if line - 1 in self._standalone:
            banned = self.suppressions.get(line - 1)
            if banned and (check in banned or "all" in banned):
                return True
        return False


class Program:
    """The whole parsed tree, handed to checkers' finalize pass."""

    def __init__(self, root: str, modules: Sequence[ModuleInfo]):
        self.root = root
        self.modules = list(modules)
        self.by_relpath = {m.relpath: m for m in self.modules}


class Checker:
    """Base class: subclasses set `name`/`description` and override one or
    both passes.  Checkers must only *report* — never mutate the tree.
    A checker that emits findings under more than one check name lists
    them all in `produces` (used by the CLI's --checks filter)."""

    name = "base"
    description = ""

    @property
    def produces(self) -> frozenset:
        return frozenset((self.name,))

    def check_module(self, mod: ModuleInfo) -> Iterator[Finding]:
        return iter(())

    def finalize(self, program: Program) -> Iterator[Finding]:
        return iter(())


# ---------------------------------------------------------------------------
# shared AST helpers used by several checkers


def iter_functions(tree: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """Yield (qualname, FunctionDef/AsyncFunctionDef) for every function,
    with class nesting reflected in the qualname."""

    def walk(node: ast.AST, prefix: str) -> Iterator[Tuple[str, ast.AST]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                yield qn, child
                yield from walk(child, qn + ".")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target, best effort: `a.b.c(...)` -> 'a.b.c',
    `f(...)` -> 'f'.  Unresolvable shapes (subscripts, calls) yield ''. """
    parts: List[str] = []
    cur = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif parts:
        parts.append("?")
    else:
        return ""
    return ".".join(reversed(parts))


def attr_tail(node: ast.Call) -> str:
    """Final attribute of a method call: `x.y.submit(...)` -> 'submit'."""
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def receiver_repr(node: ast.Call) -> str:
    """Textual receiver of a method call: `self._plane.submit()` ->
    'self._plane'."""
    if not isinstance(node.func, ast.Attribute):
        return ""
    try:
        return ast.unparse(node.func.value)
    except Exception:  # pragma: no cover - unparse is total on 3.9+
        return ""


class ParentMap:
    """child -> parent links for upward walks (ast has none built in)."""

    def __init__(self, tree: ast.AST):
        self._parent: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self._parent[child] = node

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parent.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self._parent.get(node)
        while cur is not None:
            yield cur
            cur = self._parent.get(cur)


# ---------------------------------------------------------------------------
# allowlist


def load_allowlist(path: str) -> List[Tuple[str, str, str]]:
    """Parse the allowlist file: one ``relpath::check[::substr]`` entry per
    non-comment line.  Returns [(relpath, check, substr)]."""
    entries: List[Tuple[str, str, str]] = []
    if not os.path.exists(path):
        return entries
    with open(path, encoding="utf-8") as fh:
        for raw in fh:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split("::")
            if len(parts) < 2:
                raise ValueError(
                    f"{path}: malformed allowlist entry {line!r} "
                    "(want relpath::check[::substr])")
            relpath, check = parts[0], parts[1]
            substr = parts[2] if len(parts) > 2 else ""
            entries.append((relpath, check, substr))
    return entries


def _allowed(finding: Finding,
             allowlist: Sequence[Tuple[str, str, str]]) -> bool:
    for relpath, check, substr in allowlist:
        if finding.check != check and check != "all":
            continue
        # path-component boundary: `a.py` must not match `data.py`
        if finding.path != relpath \
                and not finding.path.endswith("/" + relpath):
            continue
        if substr and substr not in finding.message \
                and substr != finding.symbol:
            continue
        return True
    return False


# ---------------------------------------------------------------------------
# runner


class AnalysisResult:
    def __init__(self) -> None:
        self.findings: List[Finding] = []     # violations that fail the run
        self.suppressed: List[Finding] = []   # inline-disabled
        self.allowlisted: List[Finding] = []  # parked debt
        self.parse_errors: List[str] = []
        self.files_scanned = 0
        #: checker name -> wall seconds (check_module sweep + finalize);
        #: the lint gate budgets the scan with these (scripts/lint.sh)
        self.checker_seconds: Dict[str, float] = {}
        self.total_seconds = 0.0

    @property
    def ok(self) -> bool:
        return not self.findings and not self.parse_errors

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "allowlisted": [f.to_dict() for f in self.allowlisted],
            "parse_errors": self.parse_errors,
            "checker_seconds": {name: round(s, 4) for name, s
                                in sorted(self.checker_seconds.items())},
            "total_seconds": round(self.total_seconds, 4),
        }


def default_root() -> str:
    """The package tree itself — loonglint ships inside what it checks."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_allowlist_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "allowlist.txt")


def collect_modules(root: str,
                    errors: Optional[List[str]] = None) -> List[ModuleInfo]:
    mods: List[ModuleInfo] = []
    root = os.path.abspath(root)
    base = os.path.dirname(root)
    if os.path.isfile(root):
        paths: Iterable[str] = [root]
        # climb the package spine so a single-file scan keeps its
        # package-relative path — path-scoped checks (tracing-hygiene's
        # ops/ scope, monitor/alarms.py detection, allowlist matching)
        # must behave identically to a tree scan
        base = os.path.dirname(root)
        while os.path.exists(os.path.join(base, "__init__.py")):
            base = os.path.dirname(base)
    else:
        paths = []
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    paths.append(os.path.join(dirpath, fn))  # type: ignore[attr-defined]
    for path in paths:
        relpath = os.path.relpath(path, base)
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            mods.append(ModuleInfo(path, relpath, source))
        except (OSError, SyntaxError, ValueError) as e:
            if errors is not None:
                errors.append(f"{relpath}: {e}")
    return mods


def run_analysis(root: Optional[str] = None,
                 checkers: Optional[Sequence[Checker]] = None,
                 allowlist_path: Optional[str] = None) -> AnalysisResult:
    """Scan `root` (default: the loongcollector_tpu package) with all
    registered checkers, returning the filtered result."""
    from .checkers import all_checkers
    root = root or default_root()
    if checkers is None:
        checkers = all_checkers()
    allowlist = load_allowlist(
        allowlist_path if allowlist_path is not None
        else default_allowlist_path())

    started = time.perf_counter()
    result = AnalysisResult()
    modules = collect_modules(root, errors=result.parse_errors)
    result.files_scanned = len(modules)
    program = Program(root, modules)

    raw: List[Tuple[Finding, ModuleInfo]] = []
    for checker in checkers:
        t0 = time.perf_counter()
        for mod in modules:
            for finding in checker.check_module(mod):
                raw.append((finding, mod))
        for finding in checker.finalize(program):
            raw.append((finding, program.by_relpath.get(finding.path)))
        result.checker_seconds[checker.name] = \
            result.checker_seconds.get(checker.name, 0.0) \
            + (time.perf_counter() - t0)

    seen = set()
    for finding, mod in raw:
        key = (finding.check, finding.path, finding.line, finding.col,
               finding.message)
        if key in seen:
            continue
        seen.add(key)
        if mod is not None and mod.suppressed(finding.line, finding.check):
            result.suppressed.append(finding)
        elif _allowed(finding, allowlist):
            result.allowlisted.append(finding)
        else:
            result.findings.append(finding)
    result.findings.sort(key=lambda f: (f.path, f.line, f.check))
    result.total_seconds = time.perf_counter() - started
    return result
