"""Sampling wall-clock profiler: where does this agent spend its time?

The paper's self-monitoring pillar (PAPER.md): the reference agent ships
its own CPU profile and running status so a production operator can ask
"what is the agent doing right now" without attaching a debugger.  Here a
sampler thread wakes at ``hz`` (``LOONG_PROF_HZ``, default 29 — an odd
rate so it never phase-locks with 1 Hz/10 Hz periodic loops), walks
``sys._current_frames()`` and

  * aggregates **folded stacks** (``thread;outer;...;leaf count`` —
    flamegraph input, served at ``/debug/pprof``);
  * attributes **exclusive self-cost** to the innermost context marker of
    each thread (markers are planted by ProcessorRunner workers
    [``worker:...`` / ``pipeline:...``], ProcessorInstance
    [``plugin:...``], FlusherRunner and the device plane), exporting
    ``self_cost_ms`` counters per scope through monitor/metrics.py — so
    per-plugin CPU shows up in the Prometheus exposition and the
    self-monitor pipeline next to every other metric;
  * pushes each sampled stack set into the flight recorder's last-N ring
    (prof/flight.py), so a crash dump shows what every thread was doing.

Threads without a marker attribute to ``thread:<name>`` — the sampler
never loses cost, it only loses granularity.

The profiler is off by default; disabled hooks are one module-global
read (chaos-plane idiom, gated by scripts/prof_overhead.py).
"""

from __future__ import annotations

import re
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..monitor.metrics import MetricsRecord

DEFAULT_HZ = 29.0
_FOLDED_CAP = 50_000        # distinct folded stacks kept
_MAX_DEPTH = 64             # frames per stack
_SCOPE_CAP = 256            # distinct per-scope metric records kept

#: ephemeral-thread normalizer: default thread names carry a per-thread
#: serial ("Thread-12 (process_request_thread)"); stripping the digits
#: collapses them to one scope, or scope-record cardinality (and the
#: exposition page) would grow with every scrape-handler thread sampled
_THREAD_SERIAL_RE = re.compile(r"\d+")


def _fold_frame(frame, max_depth: int = _MAX_DEPTH) -> str:
    """Leaf-last folded stack for one thread's current frame."""
    parts: List[str] = []
    depth = 0
    while frame is not None and depth < max_depth:
        code = frame.f_code
        parts.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}"
                     f":{frame.f_lineno})")
        frame = frame.f_back
        depth += 1
    parts.reverse()
    return ";".join(parts)


def sample_stacks_once(skip_ident: Optional[int] = None
                       ) -> List[Tuple[str, str]]:
    """One-shot stack sample of every live thread — usable without an
    active profiler (the watchdog attaches this to breach alarms)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        if tid == skip_ident:
            continue
        out.append((names.get(tid, f"tid-{tid}"), _fold_frame(frame)))
    return out


_IDLE_LEAVES = ("wait (", "sleep (", "select (", "poll (", "accept (",
                "_sample_loop (", "sample_stacks_once (")

#: leaf frames of PARKED threads (blocked in a wait, not burning CPU) —
#: they accrue wall_ms but not self_cost_ms, so the top-self-cost ranking
#: answers "what burns the CPU", not "what exists"
_PARKED_LEAVES = ("wait (", "sleep (", "select (", "poll (", "accept (",
                  "get (", "recv (", "recv_into (", "read (")


def _leaf_parked(folded: str) -> bool:
    leaf = folded.rsplit(";", 1)[-1]
    return any(m in leaf for m in _PARKED_LEAVES)


def hottest_stack(stacks: Optional[List[Tuple[str, str]]] = None
                  ) -> Optional[Tuple[str, str]]:
    """Best-effort "breaching thread" heuristic: the deepest sampled
    thread whose leaf frame is NOT an idle wait (threads parked in
    sleep/wait/select are not the ones burning the CPU limit — and
    neither is this sampling call itself).  Falls back to the deepest
    stack when every thread looks idle, so the caller always gets SOME
    stack to attach."""
    if stacks is None:
        stacks = sample_stacks_once()
    busy = [s for s in stacks
            if s[1] and not any(m in s[1].rsplit(";", 1)[-1]
                                for m in _IDLE_LEAVES)]
    pool = busy or stacks
    if not pool:
        return None
    return max(pool, key=lambda s: s[1].count(";"))


class Profiler:
    """Process-wide sampling profiler.  `start()` spawns the sampler
    thread; `sample_once()` is callable directly (tests, and the dump
    path wants one final sample)."""

    def __init__(self, hz: float = DEFAULT_HZ):
        self.hz = max(1.0, float(hz))
        self.interval_s = 1.0 / self.hz
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._lock = threading.Lock()
        self._folded: Dict[str, int] = {}
        self._marker_lock = threading.Lock()
        self._markers: Dict[int, List[str]] = {}
        self._samples_total = 0
        self._records: Dict[str, MetricsRecord] = {}
        self._records_lock = threading.Lock()

    # -- context markers (planted by instrumented threads) -------------------

    def push_marker(self, kind: str, name: str = "") -> None:
        label = f"{kind}:{name}" if name else kind
        tid = threading.get_ident()
        with self._marker_lock:
            self._markers.setdefault(tid, []).append(label)

    def pop_marker(self) -> None:
        tid = threading.get_ident()
        with self._marker_lock:
            stack = self._markers.get(tid)
            if stack:
                stack.pop()
                if not stack:
                    del self._markers[tid]

    def current_marker(self, tid: Optional[int] = None) -> Optional[str]:
        if tid is None:
            tid = threading.get_ident()
        with self._marker_lock:
            stack = self._markers.get(tid)
            return stack[-1] if stack else None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._sample_loop,
                                        name="loongprof", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        # claim the thread in one load before joining: a concurrent stop
        # would otherwise None the attr between our check and the join
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2)
        # retire the per-scope records: a stopped profiler exports nothing
        # further (loonglint metric-naming ownership rule)
        with self._records_lock:
            records = list(self._records.values())
        for rec in records:
            rec.mark_deleted()

    def _sample_loop(self) -> None:
        while self._running:
            time.sleep(self.interval_s)
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — sampling must never kill
                pass           # the process it observes

    # -- sampling -----------------------------------------------------------

    def sample_once(self) -> int:
        """Sample every thread but the sampler itself.  Returns the number
        of threads sampled.  (Callable from any thread — tests and the
        dump path take one final sample directly; only the dedicated
        sampler thread is excluded, so a direct call still sees the
        caller's own stack.)"""
        own = self._thread.ident if self._thread is not None else None
        interval_ms = self.interval_s * 1000.0
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        with self._marker_lock:
            markers = {tid: stack[-1]
                       for tid, stack in self._markers.items() if stack}
            # threads that died keep no marker state behind.  Liveness is
            # re-checked HERE, under the lock — the `frames` snapshot
            # above is stale, and judging by it would delete the marker a
            # thread pushed after the snapshot (misattributing it forever)
            alive = {t.ident for t in threading.enumerate()}
            for tid in list(self._markers):
                if tid not in alive:
                    del self._markers[tid]
        stacks: List[Tuple[str, str]] = []
        costs: Dict[str, List[float]] = {}         # scope -> [wall, busy]
        for tid, frame in frames.items():
            if tid == own:
                continue
            tname = names.get(tid, f"tid-{tid}")
            folded = f"{tname};{_fold_frame(frame)}"
            stacks.append((tname, folded))
            # unmarked fallback strips thread serials: "Thread-12 (...)"
            # and "Thread-13 (...)" are one scope, not two series
            scope = markers.get(tid) or \
                f"thread:{_THREAD_SERIAL_RE.sub('*', tname)}"
            entry = costs.setdefault(scope, [0.0, 0.0])
            entry[0] += interval_ms
            if not _leaf_parked(folded):
                # a parked thread (blocked in a wait) accrues wall time
                # but no SELF cost — the top-cost ranking must surface the
                # plugin burning the CPU, not the thread-pool that exists
                entry[1] += interval_ms
        # one lock acquisition per sample, not per thread: the sampler
        # runs at up to ~100 Hz and is itself overhead-gated
        with self._lock:
            for _tname, folded in stacks:
                if folded in self._folded or len(self._folded) < _FOLDED_CAP:
                    self._folded[folded] = self._folded.get(folded, 0) + 1
            self._samples_total += 1
        for scope, (wall_ms, busy_ms) in costs.items():
            rec = self._scope_record(scope)
            rec.counter("wall_ms").add(int(round(wall_ms)))
            if busy_ms:
                rec.counter("self_cost_ms").add(int(round(busy_ms)))
        # the flight recorder keeps the last few stack sets for the
        # post-mortem dump (record_stacks takes only its own ring lock)
        from . import flight
        flight.recorder().record_stacks(stacks)
        return len(stacks)

    def _scope_record(self, scope: str) -> MetricsRecord:
        rec = self._records.get(scope)
        if rec is None:
            with self._records_lock:
                rec = self._records.get(scope)
                if rec is None:
                    if len(self._records) >= _SCOPE_CAP:
                        # cardinality backstop: past the cap, new scopes
                        # collapse into one overflow record rather than
                        # growing the registry (and every scrape) forever
                        scope = "overflow"
                        rec = self._records.get(scope)
                    if rec is None:
                        rec = MetricsRecord(category="profiler",
                                            labels={"component": "prof",
                                                    "scope": scope})
                        self._records[scope] = rec
        return rec

    # -- retrieval ----------------------------------------------------------

    def samples_total(self) -> int:
        with self._lock:
            return self._samples_total

    def folded(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._folded)

    def folded_text(self) -> str:
        """Flamegraph input: one ``stack count`` line per distinct folded
        stack, highest count first (stable tie-break on the stack text so
        two snapshots of one run diff cleanly)."""
        items = sorted(self.folded().items(), key=lambda kv: (-kv[1], kv[0]))
        return "".join(f"{stack} {count}\n" for stack, count in items)

    def self_costs_ms(self) -> Dict[str, int]:
        """scope -> accumulated exclusive SELF cost (ms): wall time of
        samples whose leaf was not parked in a wait."""
        with self._records_lock:
            records = dict(self._records)
        return {scope: rec.counter("self_cost_ms").value
                for scope, rec in records.items()}

    def wall_costs_ms(self) -> Dict[str, int]:
        """scope -> accumulated wall time (ms), parked samples included."""
        with self._records_lock:
            records = dict(self._records)
        return {scope: rec.counter("wall_ms").value
                for scope, rec in records.items()}

    def top_self_costs(self, n: int = 5) -> List[Tuple[str, int]]:
        """Busiest scopes first — ranked by non-parked self-cost (wall
        time as the tiebreak), so an idle thread pool never outranks the
        plugin actually burning the CPU."""
        walls = self.wall_costs_ms()
        costs = sorted(self.self_costs_ms().items(),
                       key=lambda kv: (-kv[1], -walls.get(kv[0], 0), kv[0]))
        return costs[:n]
