"""Crash flight recorder: the last N notable events, always on.

Reference analogue: the reference agent's self-monitor keeps enough
post-mortem state (alarms, profile data, running status) that a crashed
or killed agent can explain its final seconds.  Here a fixed-size ring
buffer records every *notable* event — alarms, chaos injections, circuit
breaker transitions, disk-buffer spills/replays/quarantines, watchdog
breaches, worker stalls — plus the last few sampled thread-stack sets
from the profiler (prof/profiler.py), and dumps deterministically to a
JSON file on SIGTERM, watchdog breach, or unhandled crash
(application.py wires the triggers).  The live ring is served at
``/debug/flight`` by monitor/exposition.py.

Contract:

  * ``record()`` is lock-cheap — one short lock around a bounded deque
    append — and MUST NEVER be called while the caller holds another
    lock (loonglint's blocking-under-lock checker enforces this
    statically: a recorder wedged behind a contended ring lock must not
    wedge the data path).  Notable events are rare by definition; the
    hot paths never call in here.
  * The ring is bounded (`capacity`); overflow drops the OLDEST events
    and counts the drop, so a crash dump always holds the newest
    history.
  * `canonicalize(doc)` strips every timing- and thread-dependent field
    so two seeded runs compare byte-stable per event stream (the same
    per-point guarantee the chaos schedule gives — global interleaving
    across threads is not deterministic, per-stream subsequences are).
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..utils.logger import get_logger

log = get_logger("flight")

RING_CAPACITY = 2048      # notable events kept
STACK_CAPACITY = 16       # last-N sampled stack sets kept
DUMP_BASENAME = "flight.json"

#: attrs whose values are timing/thread dependent — stripped by
#: `canonicalize` (mirrors trace.tracer._VOLATILE_ATTRS)
_VOLATILE_ATTRS = frozenset({"delay_s", "duration_s", "depth", "wait_s",
                             "dump", "path"})


class FlightRecorder:
    """Bounded ring of (seq, wall, kind, attrs) + last-N stack samples."""

    def __init__(self, capacity: int = RING_CAPACITY,
                 stack_capacity: int = STACK_CAPACITY):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._stacks: deque = deque(maxlen=stack_capacity)
        self._seq = itertools.count()
        self._recorded_total = 0

    # -- recording (lock-cheap; NEVER call under another lock) --------------

    def record(self, kind: str, **attrs) -> None:
        wall = time.time()
        with self._lock:
            # seq is drawn under the lock so ring order IS seq order —
            # the guarantee snapshot() documents
            self._recorded_total += 1
            self._events.append((next(self._seq), wall, kind, attrs))

    def record_stacks(self, stacks: List[Tuple[str, str]]) -> None:
        """Attach one sampled stack set [(thread_name, folded), ...] —
        the profiler pushes its latest sample here so a crash dump shows
        what every thread was doing just before the end."""
        entry = (time.time(), list(stacks))
        with self._lock:
            self._stacks.append(entry)

    # -- retrieval ----------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def recorded_total(self) -> int:
        with self._lock:
            return self._recorded_total

    def dropped_total(self) -> int:
        with self._lock:
            return max(0, self._recorded_total - len(self._events))

    def events(self) -> List[tuple]:
        with self._lock:
            return list(self._events)

    def events_by_kind(self) -> Dict[str, List[tuple]]:
        out: Dict[str, List[tuple]] = {}
        for ev in self.events():
            out.setdefault(ev[2], []).append(ev)
        return out

    def reset(self) -> None:
        """Tests only: forget everything (a previous test's storm must
        not leak into this one's dump)."""
        with self._lock:
            self._events.clear()
            self._stacks.clear()
            self._recorded_total = 0

    # -- snapshot / dump ----------------------------------------------------

    def snapshot(self, reason: str = "") -> dict:
        """The dump document: newest-history ring + last stack samples.
        Deterministic ordering (ring order = seq order); `canonicalize`
        strips the volatile fields for byte-stable comparison."""
        with self._lock:
            events = list(self._events)
            stacks = list(self._stacks)
            total = self._recorded_total
        return {
            "reason": reason,
            "time": int(time.time()),
            "pid": os.getpid(),
            "recorded_total": total,
            "dropped": max(0, total - len(events)),
            "capacity": self.capacity,
            "events": [
                {"seq": seq, "wall": wall, "kind": kind, "attrs": attrs}
                for (seq, wall, kind, attrs) in events
            ],
            "stacks": [
                {"wall": wall,
                 "threads": [{"thread": name, "stack": folded}
                             for name, folded in sample]}
                for (wall, sample) in stacks
            ],
        }

    def dump(self, path: Optional[str] = None, reason: str = "",
             to_log: bool = True) -> Optional[str]:
        """Write the snapshot to `path` (default: <dump_dir>/flight.json)
        atomically, and mirror a short form to the log.  Returns the
        written path, or None when writing failed (the dump must never
        raise — it runs on crash paths)."""
        doc = self.snapshot(reason=reason)
        if path is None:
            path = os.path.join(_dump_dir, DUMP_BASENAME)
        tmp = None
        try:
            # unique tmp per dump: concurrent dumpers (watchdog breach +
            # SIGTERM racing on the crash path) must never truncate each
            # other's half-written file — last os.replace wins atomically
            fd, tmp = tempfile.mkstemp(
                prefix=os.path.basename(path) + ".",
                suffix=".tmp", dir=os.path.dirname(path) or ".")
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, sort_keys=True, separators=(",", ":"))
                f.write("\n")
            os.replace(tmp, path)
        except OSError as e:
            log.error("flight dump to %s failed: %s", path, e)
            if tmp is not None:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
            path = None
        if to_log:
            tail = doc["events"][-20:]
            log.warning(
                "flight recorder dump (%s): %d events (%d dropped), "
                "last %d: %s", reason or "unsolicited", len(doc["events"]),
                doc["dropped"], len(tail),
                "; ".join(f"{e['kind']}{e['attrs']}" for e in tail))
        return path


# ---------------------------------------------------------------------------
# canonicalization (shared by tests and operators diffing two dumps)


def _stable(v):
    if isinstance(v, float):
        return round(v, 9)
    return v


def canonicalize(doc: dict, kinds: Optional[frozenset] = None) -> bytes:
    """Reduce a dump document to its timing-independent structure:
    per-kind event subsequences in ring order, kinds sorted, wall/seq and
    volatile attrs stripped, stacks dropped.  Per-kind subsequences are
    deterministic for a seeded single-source stream (the chaos-schedule
    guarantee); pass `kinds` to restrict comparison to the streams that
    are seed-deterministic (e.g. ``frozenset({"chaos.inject"})`` — alarm
    and breaker timing varies across runs even under one seed)."""
    by_kind: Dict[str, List[tuple]] = {}
    for ev in doc.get("events", []):
        kind = ev["kind"]
        if kinds is not None and kind not in kinds:
            continue
        attrs = tuple(sorted((k, _stable(v)) for k, v in ev["attrs"].items()
                             if k not in _VOLATILE_ATTRS))
        by_kind.setdefault(kind, []).append(attrs)
    out = [(k,) + tuple(v) for k in sorted(by_kind) for v in by_kind[k]]
    return json.dumps(out, sort_keys=True, separators=(",", ":"),
                      default=str).encode("utf-8")


# ---------------------------------------------------------------------------
# module-level recorder: always on (events are rare; the ring is bounded)

_recorder = FlightRecorder()
# default: the system temp dir — a bare-library breach must never litter
# the process cwd; the Application points this at its data dir on init
# (the dump path is always logged, so the file stays discoverable)
_dump_dir = tempfile.gettempdir()


def recorder() -> FlightRecorder:
    return _recorder


def record(kind: str, **attrs) -> None:
    """Record one notable event into the process flight ring.  NEVER call
    while holding a lock (loonglint: blocking-under-lock)."""
    _recorder.record(kind, **attrs)


def set_dump_dir(path: str) -> None:
    """Where unsolicited dumps (signals, crashes, watchdog) land —
    the Application points this at its data dir."""
    global _dump_dir
    _dump_dir = path


def dump(path: Optional[str] = None, reason: str = "") -> Optional[str]:
    return _recorder.dump(path=path, reason=reason)
