"""loongprof: continuous self-profiling + crash flight recorder.

Off by default; ``enable()`` / ``LOONG_PROF=1`` turns the sampler on
(``LOONG_PROF_HZ`` shapes the rate).  Every hook in this package is a
single module-global read + branch when disabled — the chaos-plane idiom,
gated by scripts/prof_overhead.py the same way scripts/trace_overhead.py
gates loongtrace.

The flight recorder (prof/flight.py) is ALWAYS on: notable events are
rare by definition, the ring is bounded, and a crash dump that says
"flight recording was disabled" helps nobody.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

from . import flight
from .profiler import (DEFAULT_HZ, Profiler, hottest_stack,
                       sample_stacks_once)

ENV_ENABLE = "LOONG_PROF"
ENV_HZ = "LOONG_PROF_HZ"

__all__ = [
    "DEFAULT_HZ", "ENV_ENABLE", "ENV_HZ", "Profiler", "active",
    "active_profiler", "disable", "enable", "flight", "hottest_stack",
    "install_from_env", "is_active", "pop_marker", "push_marker",
    "sample_stacks_once",
]

_profiler: Optional[Profiler] = None


def is_active() -> bool:
    return _profiler is not None


def active_profiler() -> Optional[Profiler]:
    """THE disabled-path hook: call sites read this once; None means
    profiling is off and nothing else may run."""
    return _profiler


def enable(hz: float = DEFAULT_HZ, autostart: bool = True) -> Profiler:
    global _profiler
    disable()
    p = Profiler(hz=hz)
    _profiler = p
    if autostart:
        p.start()
    return p


def disable() -> None:
    global _profiler
    p, _profiler = _profiler, None
    if p is not None:
        p.stop()


@contextlib.contextmanager
def active(hz: float = DEFAULT_HZ, autostart: bool = True):
    """Scoped activation for tests: ``with prof.active() as p: ...``."""
    p = enable(hz=hz, autostart=autostart)
    try:
        yield p
    finally:
        disable()


def install_from_env(env=os.environ) -> bool:
    """LOONG_PROF=1 activates the sampler at application start;
    LOONG_PROF_HZ (float, default 29) shapes the sampling rate."""
    raw = env.get(ENV_ENABLE)
    if not raw or raw.strip().lower() in ("0", "false", "no", "off"):
        return False
    try:
        hz = float(env.get(ENV_HZ, str(DEFAULT_HZ)))
    except ValueError:
        hz = DEFAULT_HZ
    enable(hz=hz)
    return True


# -- hot-path hooks: each is one global read + branch when disabled ---------


def push_marker(kind: str, name: str = "") -> None:
    """Mark the calling thread's current scope (``kind:name``) for
    sample attribution.  Pass the label in two pieces so the disabled
    path never concatenates strings."""
    p = _profiler
    if p is None:
        return
    p.push_marker(kind, name)


def pop_marker() -> None:
    p = _profiler
    if p is None:
        return
    p.pop_marker()
