"""Own-connection sinks: a per-flusher sender thread with retry/backoff.

Non-HTTP flushers (Pulsar's binary protocol, gRPC channels) cannot ride
the HttpSink event loop.  The reference runs each Go flusher on its own
goroutines (pluginmanager/plugin_runner_v1.go flusher goroutine group);
this mirror gives such flushers one dedicated sender thread:

  batcher flush → bounded in-memory queue → sender thread →
  deliver() with exponential backoff until TTL → drop with error

so a down broker never blocks the pipeline's processing thread, and
transient outages are retried far longer than any inline attempt could.
A configured RequestBreaker extension gates deliveries; drain happens on
stop() with a deadline.

Each async sink also carries the unified per-sink circuit breaker
(runner/circuit.py): persistent delivery failure OPENs the circuit, the
pending queue spills to the shared DiskBufferWriter instead of aging
toward the TTL drop, and a successful half-open probe re-closes the
circuit and replays the spilled payloads through this same sink — the
identical degradation policy FlusherRunner applies to HTTP-family sinks.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..monitor import ledger, slo
from ..pipeline.plugin.interface import PluginContext
from ..pipeline.queue.sender_queue import SenderQueueItem
from ..runner import ack_watermark
from ..runner.circuit import BreakerState, SinkCircuitBreaker
from ..utils.logger import get_logger
from .http_base import HttpSinkFlusher

log = get_logger("async_sink")

QUEUE_CAP = 256              # pending payloads per flusher
RETRY_TTL_S = 300.0          # give up on a payload after this long
RETRY_MAX_DELAY_S = 10.0

_default_disk_buffer = None


def set_default_disk_buffer(disk_buffer) -> None:
    """Process-wide spill target for async sinks (the Application passes
    its DiskBufferWriter; tests pass a scratch one).  Sinks initialized
    before this call keep running without spill-on-open."""
    global _default_disk_buffer
    _default_disk_buffer = disk_buffer


class _ReplayTarget:
    """Adapter letting DiskBufferWriter.replay() feed an async sink: the
    replayed SenderQueueItem's bytes re-enter the sink's own in-memory
    queue (async sinks do not drain a SenderQueue)."""

    def __init__(self, flusher: "AsyncSinkFlusher"):
        self._flusher = flusher
        self.sender_queue = self
        self.queue_key = flusher.queue_key

    def push(self, item: SenderQueueItem) -> bool:
        return self._flusher._requeue_payload(item.data, item.event_cnt)


class AsyncSinkFlusher(HttpSinkFlusher):
    """Subclasses implement deliver(payload: bytes) -> None (raise on
    failure) plus the usual _init_sink/build_payload."""

    def __init__(self) -> None:
        super().__init__()
        self._queue: collections.deque = collections.deque()
        self._qlock = threading.Lock()
        self._qcv = threading.Condition(self._qlock)
        # events claimed out of the queue but still mid-spill (the disk
        # write can block for seconds): occupancy for inflight_events()
        self._spilling_events = 0
        self._sender: Optional[threading.Thread] = None
        self._running = False
        self.circuit: Optional[SinkCircuitBreaker] = None
        self.disk_buffer = None
        self._replay_pending = threading.Event()

    # -- subclass surface ---------------------------------------------------

    def deliver(self, payload: bytes) -> None:
        raise NotImplementedError

    def retryable(self, exc: Exception) -> bool:
        return True

    # -- framework ----------------------------------------------------------

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        if not super().init(config, context):
            return False
        if self.disk_buffer is None:
            self.disk_buffer = _default_disk_buffer
        self.circuit = SinkCircuitBreaker(
            f"{context.pipeline_name}/{self.name}",
            failure_threshold=int(config.get("BreakerFailureThreshold", 5)),
            error_rate=float(config.get("BreakerErrorRate", 0.5)),
            cooldown_s=float(config.get("BreakerCooldownSecs", 5.0)),
            on_close=self._replay_pending.set,
            pipeline=context.pipeline_name)
        self._running = True
        self._sender = threading.Thread(target=self._sender_loop,
                                        name=f"{self.name}-sender",
                                        daemon=True)
        self._sender.start()
        return True

    def _serialize_and_push(self, groups: List[PipelineEventGroup]) -> None:
        n_events = sum(len(g) for g in groups)
        built = self.build_payload(groups)
        if built is None:
            self._ledger_drop("payload_skipped", n_events)
            return
        body, _ = built
        if ledger.is_on():
            ledger.record(self._ledger_pipeline(), ledger.B_SERIALIZE,
                          n_events, len(body))
        spans = ack_watermark.spans_of(groups)
        stamps = slo.stamps_of(groups)
        shed = None
        with self._qcv:
            if len(self._queue) >= QUEUE_CAP:
                shed = self._queue.popleft()      # oldest-first shedding
            self._queue.append((body, time.monotonic(), n_events, spans,
                                stamps))
            self._qcv.notify()
        if shed is not None:
            # ledger + log OUTSIDE the queue lock (the ledger takes its
            # own lock).  The popper is the terminal authority for a
            # payload, so this drop is the shed entry's ONLY terminal —
            # the sender loop skips its record when the head it delivered
            # was shed from under it
            log.error("%s queue full; dropping oldest payload (%d bytes)",
                      self.name, len(shed[0]))
            self._ledger_drop("queue_shed", shed[2], len(shed[0]))
            ack_watermark.ack_spans(shed[3])    # terminal for this copy
            slo.observe_stamps(self._ledger_pipeline(), shed[4],
                               slo.OUTCOME_DROP)

    def _requeue_payload(self, body: bytes, event_cnt: int = 0) -> bool:
        """Replayed disk-buffer payload re-enters the send queue with a
        fresh TTL (its on-disk wait must not count against it).  At
        capacity the replay is REFUSED (False) — shedding a live queued
        payload to admit a replayed one would trade one loss for another;
        the disk file stays put for a later round instead."""
        with self._qcv:
            if len(self._queue) >= QUEUE_CAP:
                return False
            # replayed payloads carry no spans and no stamps: their spill
            # was already the terminal for both planes
            self._queue.append((body, time.monotonic(), event_cnt, (), ()))
            self._qcv.notify()
            return True

    # -- spill / replay ------------------------------------------------------

    def _spill_queue_on_open(self) -> bool:
        """Move every pending payload to the disk buffer (open circuit).
        Returns True when at least one payload moved; payloads the buffer
        refuses (full) stay queued for the backoff path."""
        if self.disk_buffer is None:
            return False
        moved = 0
        identity = self.spill_identity()
        while True:
            with self._qcv:
                if not self._queue:
                    break
                # claim the head BEFORE spilling: queue-full shedding must
                # never race the buffer write into a double terminal
                # (drop(queue_shed) + spill) for the same payload.  The
                # claimed payload moves to _spilling_events under the SAME
                # lock — during the buffer write (which can block on fsync
                # for whole auditor intervals) it is in no queue, and
                # without this anchor a stable ledger + empty queue would
                # read as a quiesce with a nonzero residual (false
                # CONSERVATION_RESIDUAL alarm)
                entry = self._queue.popleft()
                self._spilling_events += entry[2]
            body, born, events, spans, stamps = entry
            item = SenderQueueItem(body, len(body), flusher=self,
                                   queue_key=self.queue_key,
                                   event_cnt=events, spans=spans,
                                   stamps=stamps)
            if not self.disk_buffer.spill(item, identity):
                with self._qcv:
                    self._queue.appendleft(entry)   # buffer full: restore
                    self._spilling_events -= events
                break
            ack_watermark.ack_spans(spans)    # durable spill = terminal
            slo.observe_stamps(self._ledger_pipeline(), stamps,
                               slo.OUTCOME_SPILL)
            with self._qcv:
                # B_SPILL was recorded inside spill() — the terminal is on
                # the books before the occupancy anchor drops
                self._spilling_events -= events
            moved += 1
            if self.circuit is not None:
                self.circuit.note_spilled()
        if moved:
            log.warning("%s circuit open: spilled %d pending payloads to "
                        "disk buffer", self.name, moved)
        return moved > 0

    def _replay_spilled(self) -> None:
        if self.disk_buffer is None:
            return
        me = self.spill_identity()
        target = _ReplayTarget(self)

        def resolve(identity: dict):
            if all(identity.get(k) == v for k, v in me.items()):
                return target
            return None

        try:
            self.disk_buffer.replay(resolve)
        except Exception:  # noqa: BLE001
            log.exception("%s circuit-close replay failed; files kept",
                          self.name)

    # -- sender loop ---------------------------------------------------------

    def _sender_loop(self) -> None:
        delay = 0.2
        last_probe_replay = 0.0
        while True:
            if self._replay_pending.is_set():
                self._replay_pending.clear()
                self._replay_spilled()
            # spill-on-open empties the in-memory queue — pull payloads
            # back from disk as probe traffic once a cooldown has passed
            # (a failing probe re-spills them)
            now = time.monotonic()
            if (self.circuit is not None and self.disk_buffer is not None
                    and self.circuit.state is not BreakerState.CLOSED
                    and now - last_probe_replay >= self.circuit.cooldown_s):
                last_probe_replay = now
                self._replay_spilled()
            with self._qcv:
                # single bounded wait (not a loop): an empty-queue wakeup
                # must fall back through the outer loop so the open-circuit
                # probe replay above still runs with nothing in memory
                if self._running and not self._queue:
                    self._qcv.wait(timeout=0.5)
                if not self._running and not self._queue:
                    return
                if not self._queue:
                    continue
                item = self._queue[0]
                body, born, n_events, spans, stamps = item
            if self.breaker is not None and not self.breaker.allow():
                time.sleep(min(delay, 1.0))
                continue
            if self.circuit is not None and not self.circuit.allow_probe():
                # open circuit: payloads go to disk instead of aging in
                # memory toward the TTL drop; if the buffer is absent or
                # full, fall back to plain pacing
                if not self._spill_queue_on_open():
                    time.sleep(min(delay, 0.5))
                continue
            try:
                self.deliver(body)
                ok = True
            except Exception as e:  # noqa: BLE001
                ok = False
                if ledger.is_on():
                    # informational, not a conservation term: one failed
                    # attempt — the payload stays inflight
                    ledger.record(self._ledger_pipeline(),
                                  ledger.B_SEND_FAIL, n_events)
                if not self.retryable(e) \
                        or time.monotonic() - born > RETRY_TTL_S:
                    log.error("%s delivery failed permanently, dropping "
                              "%d bytes: %s", self.name, len(body), e)
                    ok = None                      # drop, don't count
                else:
                    log.warning("%s delivery failed, will retry: %s",
                                self.name, e)
            if self.breaker is not None and ok is not None:
                self.breaker.on_result(ok)
            if self.circuit is not None:
                if ok:
                    self.circuit.on_success()
                elif ok is not None:
                    self.circuit.on_failure()
                else:
                    # permanent drop (non-retryable / TTL expired): no
                    # clean health signal — release any held probe slot
                    # so the breaker cannot wedge half-open
                    self.circuit.on_inconclusive()
            if ok is False:
                # a failure that leaves the circuit open spills NOW — the
                # exponential backoff sleep outlasts the probe cooldown, so
                # waiting for the next allow_probe() would never degrade
                if (self.circuit is not None and self.circuit.is_open()
                        and self._spill_queue_on_open()):
                    delay = 0.2
                    continue
                time.sleep(delay)
                delay = min(delay * 2, RETRY_MAX_DELAY_S)
                continue
            delay = 0.2
            with self._qcv:
                # pop by IDENTITY: queue-full shedding may have removed the
                # in-flight head while the lock was released during deliver;
                # popping by position would discard an undelivered payload
                owned = bool(self._queue) and self._queue[0] is item
                if owned:
                    self._queue.popleft()
            # the POPPER is the single terminal-ledger authority for a
            # payload: if shedding raced the delivery and popped the head,
            # it already recorded drop(queue_shed) — recording send_ok too
            # would double-count the same events (negative residual, false
            # CONSERVATION_RESIDUAL alarm)
            if owned:
                # delivered OR permanently discarded: terminal for the
                # SOURCE spans — the checkpoint watermark advances
                ack_watermark.ack_spans(spans)
                slo.observe_stamps(self._ledger_pipeline(), stamps,
                                   slo.OUTCOME_SEND_OK if ok
                                   else slo.OUTCOME_DROP)
                if ledger.is_on():
                    if ok:
                        ledger.record(self._ledger_pipeline(),
                                      ledger.B_SEND_OK, n_events, len(body))
                    else:   # ok is None — permanent, reason-tagged discard
                        ledger.record(self._ledger_pipeline(), ledger.B_DROP,
                                      n_events, len(body),
                                      tag="delivery_failed")

    def inflight_events(self) -> int:
        """Events queued inside this sink's own sender hop (the payload
        mid-delivery stays at the queue head; a payload mid-spill is in
        _spilling_events) — the ledger's live-occupancy probe."""
        with self._qlock:
            return (sum(entry[2] for entry in self._queue)
                    + self._spilling_events)

    def build_request(self, item):
        raise RuntimeError(f"{self.name} sends on its own connection")

    def endpoint_url(self, item) -> str:
        return ""

    def on_send_done(self, item, status: int, body: bytes) -> str:
        return "ok"

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        super().stop(is_pipeline_removing)    # final batcher flush enqueues
        deadline = time.monotonic() + 10
        with self._qcv:
            self._running = False
            self._qcv.notify_all()
        if self._sender is not None:
            self._sender.join(timeout=max(0.1,
                                          deadline - time.monotonic()))
            self._sender = None
        if self.circuit is not None:
            # retire the breaker's metric record with its owner: a config
            # reload stops this instance and builds a fresh breaker — the
            # old record must not accumulate in WriteMetrics
            self.circuit.mark_deleted()
        return True
