"""Own-connection sinks: a per-flusher sender thread with retry/backoff.

Non-HTTP flushers (Pulsar's binary protocol, gRPC channels) cannot ride
the HttpSink event loop.  The reference runs each Go flusher on its own
goroutines (pluginmanager/plugin_runner_v1.go flusher goroutine group);
this mirror gives such flushers one dedicated sender thread:

  batcher flush → bounded in-memory queue → sender thread →
  deliver() with exponential backoff until TTL → drop with error

so a down broker never blocks the pipeline's processing thread, and
transient outages are retried far longer than any inline attempt could.
A configured RequestBreaker extension gates deliveries; drain happens on
stop() with a deadline.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext
from ..utils.logger import get_logger
from .http_base import HttpSinkFlusher

log = get_logger("async_sink")

QUEUE_CAP = 256              # pending payloads per flusher
RETRY_TTL_S = 300.0          # give up on a payload after this long
RETRY_MAX_DELAY_S = 10.0


class AsyncSinkFlusher(HttpSinkFlusher):
    """Subclasses implement deliver(payload: bytes) -> None (raise on
    failure) plus the usual _init_sink/build_payload."""

    def __init__(self) -> None:
        super().__init__()
        self._queue: collections.deque = collections.deque()
        self._qlock = threading.Lock()
        self._qcv = threading.Condition(self._qlock)
        self._sender: Optional[threading.Thread] = None
        self._running = False

    # -- subclass surface ---------------------------------------------------

    def deliver(self, payload: bytes) -> None:
        raise NotImplementedError

    def retryable(self, exc: Exception) -> bool:
        return True

    # -- framework ----------------------------------------------------------

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        if not super().init(config, context):
            return False
        self._running = True
        self._sender = threading.Thread(target=self._sender_loop,
                                        name=f"{self.name}-sender",
                                        daemon=True)
        self._sender.start()
        return True

    def _serialize_and_push(self, groups: List[PipelineEventGroup]) -> None:
        built = self.build_payload(groups)
        if built is None:
            return
        body, _ = built
        with self._qcv:
            if len(self._queue) >= QUEUE_CAP:
                dropped = self._queue.popleft()   # oldest-first shedding
                log.error("%s queue full; dropping oldest payload "
                          "(%d bytes)", self.name, len(dropped[0]))
            self._queue.append((body, time.monotonic()))
            self._qcv.notify()

    def _sender_loop(self) -> None:
        delay = 0.2
        while True:
            with self._qcv:
                while self._running and not self._queue:
                    self._qcv.wait(timeout=0.5)
                if not self._running and not self._queue:
                    return
                if not self._queue:
                    continue
                item = self._queue[0]
                body, born = item
            if self.breaker is not None and not self.breaker.allow():
                time.sleep(min(delay, 1.0))
                continue
            try:
                self.deliver(body)
                ok = True
            except Exception as e:  # noqa: BLE001
                ok = False
                if not self.retryable(e) \
                        or time.monotonic() - born > RETRY_TTL_S:
                    log.error("%s delivery failed permanently, dropping "
                              "%d bytes: %s", self.name, len(body), e)
                    ok = None                      # drop, don't count
                else:
                    log.warning("%s delivery failed, will retry: %s",
                                self.name, e)
            if self.breaker is not None and ok is not None:
                self.breaker.on_result(ok)
            if ok is False:
                time.sleep(delay)
                delay = min(delay * 2, RETRY_MAX_DELAY_S)
                continue
            delay = 0.2
            with self._qcv:
                # pop by IDENTITY: queue-full shedding may have removed the
                # in-flight head while the lock was released during deliver;
                # popping by position would discard an undelivered payload
                if self._queue and self._queue[0] is item:
                    self._queue.popleft()

    def build_request(self, item):
        raise RuntimeError(f"{self.name} sends on its own connection")

    def endpoint_url(self, item) -> str:
        return ""

    def on_send_done(self, item, status: int, body: bytes) -> str:
        return "ok"

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        super().stop(is_pipeline_removing)    # final batcher flush enqueues
        deadline = time.monotonic() + 10
        with self._qcv:
            self._running = False
            self._qcv.notify_all()
        if self._sender is not None:
            self._sender.join(timeout=max(0.1,
                                          deadline - time.monotonic()))
            self._sender = None
        return True
