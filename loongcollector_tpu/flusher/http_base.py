"""Shared machinery for HTTP-family sinks (ES, Loki, ClickHouse, OTLP,
Prometheus remote-write): batch → build payload → compress → sender queue →
FlusherRunner → HttpSink.

Reference shape: the Go flusher long tail (plugins/flusher/*) all follow
converter + HTTP client; here each sink is just `build_payload` (+ URL and
static headers) on top of the same native sender path the SLS flusher uses
(SenderQueueItem retry state, AIMD + rate gates, drain-on-exit).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..monitor import ledger, slo
from ..runner import ack_watermark
from ..pipeline.batch.batcher import Batcher
from ..pipeline.batch.flush_strategy import FlushStrategy
from ..pipeline.compression import create_compressor
from ..pipeline.plugin.interface import Flusher, PluginContext
from ..pipeline.queue.sender_queue import SenderQueueItem
from .http import HttpRequest


class HttpSinkFlusher(Flusher):
    """Subclasses implement `_init_sink` and `build_payload`; optionally
    override `endpoint_url` (e.g. address rotation) and `extra_headers`."""

    default_compression: Optional[str] = None
    content_type = "application/json"

    def __init__(self) -> None:
        super().__init__()
        self.headers: Dict[str, str] = {}
        self.compressor = None
        self.batcher: Batcher = None  # type: ignore
        self.authenticator = None     # extension refs; resolved at init
        self.breaker = None
        self.flush_interceptor = None

    # -- subclass surface ---------------------------------------------------

    def _init_sink(self, config: Dict[str, Any]) -> bool:
        raise NotImplementedError

    def build_payload(self, groups: List[PipelineEventGroup]
                      ) -> Optional[Tuple[bytes, Dict[str, str]]]:
        """Returns (body, per-item headers) or None to skip the batch."""
        raise NotImplementedError

    def endpoint_url(self, item: SenderQueueItem) -> str:
        raise NotImplementedError

    # -- framework ----------------------------------------------------------

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        if not self._init_sink(config):
            return False
        if not resolve_http_extensions(self, config, context):
            return False
        self.headers = dict(config.get("Headers", {}))
        self.compressor = create_compressor(
            config.get("Compression", self.default_compression))
        strategy = FlushStrategy(
            min_cnt=int(config.get("MinCnt", 0)),
            min_size_bytes=int(config.get("MinSizeBytes", 256 * 1024)),
            max_size_bytes=int(config.get("MaxSizeBytes", 5 * 1024 * 1024)),
            timeout_secs=float(config.get("TimeoutSecs", 1.0)))
        self.batcher = Batcher(strategy, on_flush=self._serialize_and_push,
                               flusher_id=self.name,
                               pipeline_name=context.pipeline_name)
        return True

    def send(self, group: PipelineEventGroup) -> bool:
        if self.flush_interceptor is not None \
                and not self.flush_interceptor.filter([group]):
            # filtered out, not an error — but terminal for these events
            self._ledger_drop("flush_filtered", group=group)
            return True
        self.batcher.add(group)
        return True

    def _serialize_and_push(self, groups: List[PipelineEventGroup]) -> None:
        n_events = sum(len(g) for g in groups)
        spans = ack_watermark.spans_of(groups)
        # serialization erases group identity: the ingest stamps ride the
        # item (the spans shape) so the real terminal can observe sojourn
        stamps = slo.stamps_of(groups)
        built = self.build_payload(groups)
        if built is None:
            # the sink's payload builder skipped the whole batch: terminal
            self._ledger_drop("payload_skipped", n_events)
            ack_watermark.ack_spans(spans, force=True)
            slo.observe_stamps(self._ledger_pipeline(), stamps,
                               slo.OUTCOME_DROP)
            return
        body, item_headers = built
        raw_size = len(body)
        if ledger.is_on():
            ledger.record(self._ledger_pipeline(), ledger.B_SERIALIZE,
                          n_events, raw_size)
        payload = self.compressor.compress(body)
        item = SenderQueueItem(payload, raw_size, flusher=self,
                               queue_key=self.queue_key,
                               tag={"headers": item_headers},
                               event_cnt=n_events, spans=spans,
                               stamps=stamps)
        if self.sender_queue is None:
            # no sender queue wired (flusher stopped mid-flush): terminal
            self._ledger_drop("no_sender_queue", n_events)
            ack_watermark.ack_spans(spans, force=True)
            slo.observe_stamps(self._ledger_pipeline(), stamps,
                               slo.OUTCOME_DROP)
        elif not self.sender_queue.push(item):
            # refused push (queue retired mid-hot-reload): terminal
            self._ledger_drop("queue_retired", n_events)
            ack_watermark.ack_spans(spans, force=True)
            slo.observe_stamps(self._ledger_pipeline(), stamps,
                               slo.OUTCOME_DROP)

    def build_request(self, item: SenderQueueItem) -> HttpRequest:
        check_breaker(self)
        headers = dict(self.headers)
        headers.setdefault("Content-Type", self.content_type)
        headers.update(item.tag.get("headers") or {})
        if self.compressor is not None and self.compressor.name != "none":
            enc = {"zlib": "deflate"}.get(self.compressor.name,
                                          self.compressor.name)
            headers["Content-Encoding"] = enc
        req = HttpRequest("POST", self.endpoint_url(item), headers,
                          item.data)
        if self.authenticator is not None:
            self.authenticator.apply(req)
        return req

    def on_send_done(self, item: SenderQueueItem, status: int,
                     body: bytes) -> str:
        if self.breaker is not None:
            self.breaker.on_result(200 <= status < 300)
        if 200 <= status < 300:
            return "ok"
        if status in (429, 500, 502, 503, 504) or status <= 0:
            return "retry"
        return "drop"

    def flush_all(self) -> bool:
        self.batcher.flush_all()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self.batcher.flush_all()
        self.batcher.close()
        return True


class AddressRotator:
    """Round-robin across sink addresses (the Go flushers' host pools)."""

    def __init__(self, addresses: List[str]):
        self.addresses = [a.rstrip("/") for a in addresses if a]
        self._it = itertools.cycle(self.addresses) if self.addresses else None

    def __bool__(self) -> bool:
        return self._it is not None

    def next(self) -> str:
        return next(self._it)


def resolve_http_extensions(flusher, config: Dict[str, Any],
                            context: PluginContext) -> bool:
    """Resolve Authenticator / RequestBreaker extension refs (reference:
    flushers point at named instances from the pipeline's `extensions:`
    section).  A dangling ref is a config error; no ref keeps the flusher
    extension-free."""
    flusher.authenticator = None
    flusher.breaker = None
    auth_ref = config.get("Authenticator")
    if auth_ref:
        flusher.authenticator = context.get_extension(str(auth_ref))
        if flusher.authenticator is None:
            return False
    br_ref = config.get("RequestBreaker")
    if br_ref:
        flusher.breaker = context.get_extension(str(br_ref))
        if flusher.breaker is None:
            return False
    flt_ref = config.get("FlushInterceptor")
    if flt_ref:
        flusher.flush_interceptor = context.get_extension(str(flt_ref))
        if flusher.flush_interceptor is None:
            return False
    return True


def check_breaker(flusher) -> None:
    """Fail fast when the flusher's breaker is open: build_request raises,
    FlusherRunner backs the item off without touching the endpoint."""
    br = getattr(flusher, "breaker", None)
    if br is not None and not br.allow():
        from ..pipeline.plugin.extension import BreakerOpen
        raise BreakerOpen(f"{flusher.name}: request breaker open")


def basic_auth_header(config: Dict[str, Any]) -> Dict[str, str]:
    """Authentication.PlainText.{Username,Password} → Authorization header
    (the Go flushers' shared auth extension shape)."""
    auth = (config.get("Authentication") or {}).get("PlainText") or {}
    user = auth.get("Username") or config.get("Username")
    pwd = auth.get("Password") or config.get("Password")
    if not user:
        return {}
    import base64
    token = base64.b64encode(f"{user}:{pwd or ''}".encode()).decode()
    return {"Authorization": f"Basic {token}"}
