"""flusher_blackhole — perf-testing sink (reference
core/plugin/flusher/blackhole/FlusherBlackHole.cpp): serializes then drops,
counting bytes."""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, List

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Flusher, PluginContext
from ..pipeline.serializer.sls_serializer import SLSEventGroupSerializer


class FlusherBlackHole(Flusher):
    name = "flusher_blackhole"
    supports_columnar = True
    ledger_terminal = True  # loongledger: send() IS delivery

    def __init__(self) -> None:
        super().__init__()
        self.serializer = SLSEventGroupSerializer()
        self.total_bytes = 0
        self.total_events = 0
        self.serialize = True
        # loongcolumn side-by-side bench: per-group payload digests folded
        # order-independently (modular SUM — multiset-safe even when many
        # groups serialize identically, unlike XOR), so two runs of the
        # same input compare equal regardless of how the sharded runner
        # interleaved sources — the in-bench byte-identity assertion
        # between the columnar and dict paths
        self.digest = False
        self._digest_state = 0
        self._digest_groups = 0
        self._digest_lock = threading.Lock()

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.serialize = bool(config.get("Serialize", True))
        self.digest = bool(config.get("Digest", False))
        return True

    def send(self, group: PipelineEventGroup) -> bool:
        if self.serialize:
            # serialize_view: measure the REAL wire cost without paying a
            # payload copy the blackhole would immediately discard
            view = self.serializer.serialize_view([group])
            if self.digest:
                # digest mode: EXACT totals gate the side-by-side bench's
                # equality assertion, and sharded workers send
                # concurrently — fold and count under one lock (the hash
                # itself is computed outside it)
                h = int.from_bytes(hashlib.sha256(view).digest(), "big")
                with self._digest_lock:
                    self.total_events += len(group)
                    self.total_bytes += len(view)
                    self._digest_state = (self._digest_state + h) % (1 << 256)
                    self._digest_groups += 1
            else:
                self.total_events += len(group)
                self.total_bytes += len(view)
        else:
            self.total_events += len(group)
            self.total_bytes += group.data_size()
        return True

    def output_digest(self) -> Dict[str, object]:
        """Order-independent fingerprint of everything this sink received:
        modular sum of per-group payload SHA-256s + totals.  Equal
        digests ⇒ the same multiset of serialized group payloads
        arrived."""
        with self._digest_lock:
            return {"sum_sha256": f"{self._digest_state:064x}",
                    "groups": self._digest_groups,
                    "bytes": self.total_bytes,
                    "events": self.total_events}
