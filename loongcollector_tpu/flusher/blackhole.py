"""flusher_blackhole — perf-testing sink (reference
core/plugin/flusher/blackhole/FlusherBlackHole.cpp): serializes then drops,
counting bytes."""

from __future__ import annotations

from typing import Any, Dict, List

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Flusher, PluginContext
from ..pipeline.serializer.sls_serializer import SLSEventGroupSerializer


class FlusherBlackHole(Flusher):
    name = "flusher_blackhole"
    ledger_terminal = True  # loongledger: send() IS delivery

    def __init__(self) -> None:
        super().__init__()
        self.serializer = SLSEventGroupSerializer()
        self.total_bytes = 0
        self.total_events = 0
        self.serialize = True

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.serialize = bool(config.get("Serialize", True))
        return True

    def send(self, group: PipelineEventGroup) -> bool:
        self.total_events += len(group)
        if self.serialize:
            # serialize_view: measure the REAL wire cost without paying a
            # payload copy the blackhole would immediately discard
            self.total_bytes += len(self.serializer.serialize_view([group]))
        else:
            self.total_bytes += group.data_size()
        return True
