"""flusher_grpc — ship serialized event groups over gRPC.

Reference: plugins/flusher/grpc/ wraps a gRPC client the same way this
wraps grpcio (baked into the image; the reference links the Go library).
Default method is /loongsuite.Forward/Forward — the exact service our
input_forward exposes, so two agents chain natively: agent A's
flusher_grpc feeds agent B's input_forward (the reference's agent-to-agent
forwarding topology).

Payload formats: `sls_pb` (LogGroup wire bytes — parse_loggroup-decodable
on the receiving side) or `json` (event-group fixture JSON).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from .. import chaos
from ..chaos import ChaosFault
from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext
from ..pipeline.queue.sender_queue import SenderQueueItem
from ..utils.logger import get_logger
from .async_sink import AsyncSinkFlusher

log = get_logger("grpc_flusher")

FP_SEND = chaos.register_point("grpc_flusher.send")

try:
    import grpc
except ImportError:  # pragma: no cover
    grpc = None


class FlusherGrpc(AsyncSinkFlusher):
    name = "flusher_grpc"
    supports_columnar = True
    content_type = "application/grpc"

    def __init__(self) -> None:
        super().__init__()
        self.address = ""
        self.method = "/loongsuite.Forward/Forward"
        self.fmt = "sls_pb"
        self.timeout = 10.0
        self._channel = None
        self._call = None

    def _init_sink(self, config: Dict[str, Any]) -> bool:
        if grpc is None:
            log.error("grpcio unavailable; flusher_grpc disabled")
            return False
        self.address = config.get("Address", "")
        if not self.address:
            return False
        self.method = config.get("Method", self.method)
        self.fmt = str(config.get("Format", "sls_pb")).lower()
        self.timeout = float(config.get("TimeoutSecs", 10))
        self._channel = grpc.insecure_channel(self.address)
        self._call = self._channel.unary_unary(
            self.method,
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        return True

    def build_payload(self, groups: List[PipelineEventGroup]):
        if self.fmt in ("sls", "sls_pb"):
            from ..pipeline.serializer.sls_serializer import \
                SLSEventGroupSerializer
            return SLSEventGroupSerializer().serialize(groups), {}
        from ..pipeline.serializer.json_serializer import JsonSerializer
        return JsonSerializer().serialize(groups), {}

    def deliver(self, payload: bytes) -> None:
        chaos.faultpoint(FP_SEND)
        self._call(payload, timeout=self.timeout)

    def retryable(self, exc: Exception) -> bool:
        if isinstance(exc, ChaosFault):
            return True     # injected faults model transient channel loss
        code = exc.code() if hasattr(exc, "code") else None
        return code in (grpc.StatusCode.UNAVAILABLE,
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        grpc.StatusCode.RESOURCE_EXHAUSTED)

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        super().stop(is_pipeline_removing)
        if self._channel is not None:
            self._channel.close()
        return True
