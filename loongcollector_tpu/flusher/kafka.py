"""flusher_kafka — Kafka sink over the built-in wire-protocol producer.

Reference: core/plugin/flusher/kafka/FlusherKafka.cpp + KafkaProducer.cpp
(librdkafka; TLS/SASL/Kerberos, dynamic topics).  This implementation covers
plaintext brokers with dynamic topic selection from a field and key-hash or
round-robin partitioning; events serialize as JSON lines (one record per
event, matching the reference's default converter).
"""

from __future__ import annotations

import collections
import json
import queue as _queue
import threading
import time
from typing import Any, Dict, List, Optional

from ..models import PipelineEventGroup
from ..monitor import ledger
from ..pipeline.batch.batcher import Batcher
from ..pipeline.batch.flush_strategy import FlushStrategy
from ..pipeline.plugin.interface import Flusher, PluginContext
from ..pipeline.serializer.json_serializer import JsonSerializer
from ..runner.circuit import SinkCircuitBreaker
from ..utils.logger import get_logger
from .kafka_client import KafkaError, KafkaProducer

log = get_logger("kafka")


class FlusherKafka(Flusher):
    name = "flusher_kafka"
    supports_columnar = True
    # class-level default: test rigs (and tools) that bypass __init__ via
    # __new__ still get a gate-free _send_loop
    circuit: Optional[SinkCircuitBreaker] = None

    def __init__(self) -> None:
        super().__init__()
        self.brokers: List[str] = []
        self.topic = ""
        self.topic_field: Optional[bytes] = None
        self.key_field: Optional[bytes] = None
        self.producer: Optional[KafkaProducer] = None
        self.batcher: Batcher = None  # type: ignore
        self.serializer = JsonSerializer()
        self._send_queue: _queue.Queue = _queue.Queue(maxsize=256)
        self._worker: Optional[threading.Thread] = None
        self._running = False
        self.max_retries = 5
        self.circuit: Optional[SinkCircuitBreaker] = None
        # loongledger live-occupancy probe: records handed to the sender
        # thread but not yet terminally ledgered (send_ok or drop)
        self._inflight_records = 0
        self._inflight_lock = threading.Lock()

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.brokers = list(config.get("Brokers", []))
        self.topic = config.get("Topic", "")
        tf = config.get("TopicField")
        self.topic_field = tf.encode() if tf else None
        kf = config.get("KeyField", config.get("HashKeys", [None])[0]
                        if config.get("HashKeys") else None)
        self.key_field = kf.encode() if isinstance(kf, str) else None
        if not self.brokers or not self.topic:
            return False
        # reference KafkaProducer.cpp:41,111 — ssl.* and sasl.* settings;
        # accept both top-level TLS/SASL and the Go flushers'
        # Authentication.{TLS,SASL,PlainText} nesting
        auth = config.get("Authentication") or {}
        # presence checks, not truthiness: `TLS: {}` means "TLS with the
        # system trust store", which `or` would silently drop
        tls = config["TLS"] if "TLS" in config else auth.get("TLS")
        sasl = config["SASL"] if "SASL" in config else auth.get("SASL")
        if sasl is None and auth.get("PlainText"):
            pt = auth["PlainText"]
            sasl = {"Mechanism": "PLAIN",
                    "Username": pt.get("Username"),
                    "Password": pt.get("Password")}
        self.producer = KafkaProducer(
            self.brokers,
            acks=int(config.get("RequiredAcks", -1)),
            timeout_ms=int(config.get("TimeoutMs", 10000)),
            tls=tls, sasl=sasl,
            max_in_flight=int(config.get("MaxInFlight", 5)))
        strategy = FlushStrategy(
            min_cnt=int(config.get("MinCnt", 512)),
            min_size_bytes=int(config.get("MinSizeBytes", 256 * 1024)),
            timeout_secs=float(config.get("TimeoutSecs", 1.0)))
        self.max_retries = int(config.get("MaxRetries", 5))
        self.circuit = SinkCircuitBreaker(
            f"{context.pipeline_name}/{self.name}",
            failure_threshold=int(config.get("BreakerFailureThreshold", 5)),
            error_rate=float(config.get("BreakerErrorRate", 0.5)),
            cooldown_s=float(config.get("BreakerCooldownSecs", 5.0)),
            pipeline=context.pipeline_name)
        self.batcher = Batcher(strategy, on_flush=self._flush_groups,
                               flusher_id=self.name,
                               pipeline_name=context.pipeline_name)
        self._running = True
        self._worker = threading.Thread(target=self._send_loop,
                                        name="kafka-sender", daemon=True)
        self._worker.start()
        return True

    def send(self, group: PipelineEventGroup) -> bool:
        self.batcher.add(group)
        return True

    def _flush_groups(self, groups: List[PipelineEventGroup]) -> None:
        by_topic: Dict[str, List] = {}
        for group in groups:
            payload = self.serializer.serialize([group])
            for line in payload.splitlines():
                if not line:
                    continue
                topic = self.topic
                key = None
                if self.topic_field or self.key_field:
                    try:
                        # dynamic topic/key routing re-reads the serialized
                        # row; only active when TopicField/KeyField is set
                        # loonglint: disable=per-row-parse
                        obj = json.loads(line)
                        if self.topic_field:
                            topic = obj.get(self.topic_field.decode(), topic)
                        if self.key_field:
                            kv = obj.get(self.key_field.decode())
                            if kv is not None:
                                key = str(kv).encode()
                    except ValueError:
                        pass
                by_topic.setdefault(topic, []).append((key, line))
        # hand off to the sender thread: broker I/O must not stall the
        # processing thread (parity with the sender-queue path of the HTTP
        # flushers); bounded queue applies back-pressure at ~256 batches
        for topic, records in by_topic.items():
            if ledger.is_on():
                ledger.record(self._ledger_pipeline(), ledger.B_SERIALIZE,
                              len(records),
                              sum(len(line) for _k, line in records))
            self._note_inflight(len(records))
            self._send_queue.put((topic, records, 0))

    def _note_inflight(self, delta: int) -> None:
        # tolerate partially-constructed instances (tests build the sender
        # loop via __new__): no lock ⇒ no occupancy tracking, nothing else
        lock = getattr(self, "_inflight_lock", None)
        if lock is None:
            return
        with lock:
            self._inflight_records += delta

    def inflight_events(self) -> int:
        """Records inside the sender hop (send queue + retry deque + the
        batch mid-produce) — the ledger's live-occupancy probe."""
        with self._inflight_lock:
            return self._inflight_records

    def _send_loop(self) -> None:
        # Failed batches go to a consumer-local retry deque, drained before
        # the main queue. The consumer must NEVER block putting back into its
        # own bounded queue: under a sustained broker outage producers can
        # fill the freed slot first, deadlocking the only consumer.
        retry: collections.deque = collections.deque()
        while self._running or retry or not self._send_queue.empty():
            if retry and retry[0][3] <= time.monotonic():
                topic, records, attempt, _ = retry.popleft()
            else:
                try:
                    timeout = 0.2
                    if retry:
                        timeout = max(0.0, min(
                            timeout, retry[0][3] - time.monotonic()))
                    topic, records, attempt = self._send_queue.get(
                        timeout=timeout) if timeout > 0 else \
                        self._send_queue.get_nowait()
                except _queue.Empty:
                    continue
            if self._running and self.circuit is not None \
                    and not self.circuit.allow_probe():
                # open circuit: park the batch on the retry deque for one
                # cooldown instead of hammering a dead broker (attempt
                # count unchanged — breaker waits don't burn retries).
                # Once stop() clears _running, parking ends and batches
                # drain through the bounded attempt budget as before, so
                # shutdown stays bounded and close() never races sends.
                retry.append((topic, records, attempt,
                              time.monotonic() + self.circuit.cooldown_s))
                time.sleep(0.05)
                continue
            try:
                self.producer.send(topic, records)
                if self.circuit is not None:
                    self.circuit.on_success()
                if ledger.is_on():
                    ledger.record(self._ledger_pipeline(), ledger.B_SEND_OK,
                                  len(records),
                                  sum(len(line) for _k, line in records))
                self._note_inflight(-len(records))
            except KafkaError as e:
                if self.circuit is not None:
                    self.circuit.on_failure()
                # partial-ack aware retry: re-send ONLY what the broker
                # did not acknowledge (KafkaProduceError.unacked); acked
                # batches must not be duplicated by the retry
                failed = getattr(e, "unacked", None)
                if failed is not None:
                    n_acked = len(records) - len(failed)
                    if n_acked > 0 and ledger.is_on():
                        # ack-window cut: the acked prefix IS delivered —
                        # it ledgers as send_ok exactly once; only the
                        # unacked tail stays inflight for the retry
                        ledger.record(self._ledger_pipeline(),
                                      ledger.B_SEND_OK, n_acked,
                                      tag="partial_ack")
                    self._note_inflight(-n_acked)
                    records = failed
                if ledger.is_on():
                    ledger.record(self._ledger_pipeline(),
                                  ledger.B_SEND_FAIL, len(records))
                if not records:
                    continue
                if attempt + 1 >= self.max_retries:
                    log.error("kafka produce to %s failed after %d tries, "
                              "dropping %d records: %s",
                              topic, attempt + 1, len(records), e)
                    if ledger.is_on():
                        ledger.record(self._ledger_pipeline(), ledger.B_DROP,
                                      len(records),
                                      tag="kafka_retry_exhausted")
                    self._note_inflight(-len(records))
                    continue
                not_before = time.monotonic() + min(0.1 * (2 ** attempt), 5.0)
                retry.append((topic, records, attempt + 1, not_before))

    def flush_all(self) -> bool:
        self.batcher.flush_all()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self.batcher.flush_all()
        self.batcher.close()
        self._running = False
        if self._worker:
            self._worker.join(timeout=10)
            self._worker = None
        if self.producer:
            self.producer.close()
        if self.circuit is not None:
            # retire the breaker's metric record with its owner (a reload
            # creates a fresh breaker; the old record must not accumulate)
            self.circuit.mark_deleted()
        return True
