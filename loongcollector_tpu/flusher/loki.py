"""flusher_loki — Loki push API sink.

Reference: plugins/flusher/loki/flusher_loki.go — static + dynamic labels,
tenant header; body is /loki/api/v1/push JSON: streams of [ts_ns, line]
pairs grouped by label set.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..pipeline.serializer.event_dicts import iter_event_dicts
from .http_base import HttpSinkFlusher, basic_auth_header


def _label_name(key: str) -> str:
    """Loki label names must match [a-zA-Z_:][a-zA-Z0-9_:]* — anything else
    gets the batch 400'd (and dropped) at the push endpoint."""
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", key)
    if not name or not re.match(r"[a-zA-Z_:]", name[0]):
        name = "_" + name
    return name


class FlusherLoki(HttpSinkFlusher):
    name = "flusher_loki"
    supports_columnar = True

    def _init_sink(self, config: Dict[str, Any]) -> bool:
        self.url = (config.get("URL") or "").rstrip("/")
        self.tenant = config.get("TenantID", "")
        self.static_labels: Dict[str, str] = {
            str(k): str(v)
            for k, v in (config.get("StaticLabels") or {}).items()}
        self.dynamic_labels: List[str] = list(
            config.get("DynamicLabels") or [])
        self.auth = basic_auth_header(config)
        return bool(self.url)

    def build_payload(self, groups: List[PipelineEventGroup]
                      ) -> Optional[Tuple[bytes, Dict[str, str]]]:
        streams: Dict[Tuple, Dict] = {}
        for g in groups:
            for ts, obj in iter_event_dicts(g):
                labels = dict(self.static_labels)
                for key in self.dynamic_labels:
                    v = obj.pop(key, None)
                    if v is not None:
                        labels[_label_name(key)] = str(v)
                if "content" in obj and len(obj) == 1:
                    line = str(obj["content"])
                else:
                    # the loki stream body re-wraps the line as a JSON
                    # string value, so rows stay str here (an encode/decode
                    # round trip through the bytes helper would be waste)
                    line = json.dumps(obj, ensure_ascii=False) if obj else ""
                k = tuple(sorted(labels.items()))
                entry = streams.setdefault(k, {"stream": labels,
                                               "values": []})
                entry["values"].append([str(ts * 1_000_000_000), line])
        if not streams:
            return None
        headers = dict(self.auth)
        if self.tenant:
            headers["X-Scope-OrgID"] = self.tenant
        body = json.dumps({"streams": list(streams.values())},
                          ensure_ascii=False).encode()
        return body, headers

    def endpoint_url(self, item) -> str:
        return f"{self.url}/loki/api/v1/push"
