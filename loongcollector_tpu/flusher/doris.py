"""flusher_doris — Apache Doris stream-load sink.

Reference: plugins/flusher/doris/ (Go stream-load client). Doris ingests
over plain HTTP: `PUT /api/{db}/{table}/_stream_load` with NDJSON rows,
basic auth, and per-request headers selecting the format. Rides the shared
HttpSinkFlusher machinery; a unique label per batch gives Doris its
at-most-once dedupe handle.
"""

from __future__ import annotations

import itertools
import json
import time
from typing import Any, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..pipeline.serializer.batch_json import ndjson_payload
from .http_base import AddressRotator, HttpSinkFlusher, basic_auth_header

_label_seq = itertools.count(1)


class FlusherDoris(HttpSinkFlusher):
    name = "flusher_doris"
    supports_columnar = True
    content_type = "application/x-ndjson"

    def _init_sink(self, config: Dict[str, Any]) -> bool:
        self.rotator = AddressRotator(config.get("Addresses", []))
        self.database = config.get("Database", "")
        self.table = config.get("Table", "")
        self.auth = basic_auth_header(config)
        self.label_prefix = config.get("LabelPrefix", "loongcollector")
        return bool(self.rotator) and bool(self.database) and \
            bool(self.table)

    def build_payload(self, groups: List[PipelineEventGroup]
                      ) -> Optional[Tuple[bytes, Dict[str, str]]]:
        # shared batched serializer (loongshard) — same row bytes as the
        # old per-row json.dumps loop, assembled natively per group
        body = ndjson_payload(groups, ts_key="_timestamp")
        if body is None:
            return None
        headers = dict(self.auth)
        headers["format"] = "json"
        headers["read_json_by_line"] = "true"
        headers["Expect"] = "100-continue"
        headers["label"] = (f"{self.label_prefix}_{int(time.time())}"
                            f"_{next(_label_seq)}")
        return body, headers

    def build_request(self, item):
        req = super().build_request(item)
        req.method = "PUT"
        return req

    def on_send_done(self, item, status: int, body: bytes) -> str:
        """Doris reports load failures with HTTP 200 + Status != Success in
        the JSON body (the Go reference client parses it the same way)."""
        if 200 <= status < 300:
            try:
                resp = json.loads(body)
            except ValueError:
                return "ok"
            st = resp.get("Status", "Success")
            if st in ("Success", "Publish Timeout"):
                return "ok"
            if st == "Label Already Exists":
                return "ok"     # duplicate delivery: the load already landed
            from ..utils.logger import get_logger
            get_logger("doris").error(
                "stream load rejected: %s (%s)", st,
                resp.get("Message", ""))
            return "drop"       # schema/data errors do not heal on retry
        return super().on_send_done(item, status, body)

    def endpoint_url(self, item) -> str:
        return (f"{self.rotator.next()}/api/{self.database}/"
                f"{self.table}/_stream_load")
