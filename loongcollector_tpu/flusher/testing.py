"""Test-support flushers: checker, sleep, statistics.

Reference: plugins/flusher/{checker,sleep,statistics}/ — the sinks the
reference's e2e test rigs assert against (checker records everything for
key/value assertions, sleep injects sink latency for back-pressure tests,
statistics prints group/event/byte rates).
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Dict, List, Optional

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Flusher, PluginContext
from ..utils.logger import get_logger

log = get_logger("flusher_testing")


class FlusherChecker(Flusher):
    """flusher_checker: retains every received group; test helpers assert
    on counts and key/value pairs (flusher_checker.go:30-78)."""

    name = "flusher_checker"
    ledger_terminal = True  # loongledger: retained in memory == delivered

    def __init__(self) -> None:
        super().__init__()
        self.groups: List[PipelineEventGroup] = []
        self._lock = threading.Lock()

    def send(self, group: PipelineEventGroup) -> bool:
        with self._lock:
            self.groups.append(group)
        return True

    # -- assertion helpers (reference GetLogCount/CheckKeyValue*) ----------

    def get_log_count(self) -> int:
        with self._lock:
            return sum(len(g) for g in self.groups)

    def check_key_value(self, key: str, value: str) -> Optional[str]:
        """None when some event carries key=value; else the mismatch
        (first differing value seen, or key-not-found)."""
        kb = key.encode()
        mismatch: Optional[str] = None
        with self._lock:
            for g in self.groups:
                for ev in g.events:
                    contents = getattr(ev, "contents", None)
                    if not contents:
                        continue
                    for k, v in contents:
                        if bytes(k) == kb:
                            if v.to_bytes() == value.encode():
                                return None
                            if mismatch is None:
                                mismatch = (
                                    f"key: {key}, expect: {value}, "
                                    f"real: {v.to_bytes().decode()}")
        return mismatch or f"cannot find this key: {key}"

    def check_key_value_any(self, key: str, regex: str) -> bool:
        rx = re.compile(regex.encode())
        kb = key.encode()
        with self._lock:
            for g in self.groups:
                for ev in g.events:
                    for k, v in getattr(ev, "contents", []) or []:
                        if bytes(k) == kb and rx.search(v.to_bytes()):
                            return True
        return False


class FlusherSleep(Flusher):
    """flusher_sleep: stalls SleepMS per group — back-pressure and sink
    starvation scenarios (flusher_sleep.go)."""

    name = "flusher_sleep"
    ledger_terminal = True  # loongledger: send() IS delivery

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.sleep_s = int(config.get("SleepMS", 0)) / 1000.0
        return True

    def send(self, group: PipelineEventGroup) -> bool:
        if self.sleep_s:
            time.sleep(self.sleep_s)
        return True


class FlusherStatistics(Flusher):
    """flusher_statistics: rolling group/event/byte rates printed each
    RateIntervalMs (flusher_statistics.go); GeneratePB also serializes to
    measure the wire path."""

    name = "flusher_statistics"
    ledger_terminal = True  # loongledger: send() IS delivery

    def __init__(self) -> None:
        super().__init__()
        self.groups = 0
        self.events = 0
        self.bytes = 0
        self._window_start = time.monotonic()

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.rate_interval_s = int(config.get("RateIntervalMs", 1000)) / 1000.0
        self.generate_pb = bool(config.get("GeneratePB", False))
        self.sleep_s = int(config.get("SleepMsPerLogGroup", 0)) / 1000.0
        if self.generate_pb:
            from ..pipeline.serializer.sls_serializer import \
                SLSEventGroupSerializer
            self._serializer = SLSEventGroupSerializer()
        return True

    def send(self, group: PipelineEventGroup) -> bool:
        self.groups += 1
        self.events += len(group)
        if self.generate_pb:
            self.bytes += len(self._serializer.serialize([group]))
        else:
            self.bytes += group.data_size()
        if self.sleep_s:
            time.sleep(self.sleep_s)
        now = time.monotonic()
        if now - self._window_start >= self.rate_interval_s:
            dt = now - self._window_start
            log.info("statistics: %.1f groups/s %.1f events/s %.1f KB/s",
                     self.groups / dt, self.events / dt,
                     self.bytes / 1024.0 / dt)
            self.groups = self.events = self.bytes = 0
            self._window_start = now
        return True
