"""SLS client management: region endpoint pools + response classification.

Reference: core/plugin/flusher/sls/SLSClientManager.cpp (~500 LoC) keeps an
ordered endpoint list per region, moves off a failing endpoint after a
burst of errors, and periodically probes back toward the primary;
FlusherSLS.cpp (1419 LoC) maps server response codes — quota exceed,
unauthorized, server busy — onto retry/backoff/drop decisions that drive
the AIMD concurrency limiter.

Both concerns are host-side control-plane logic, deliberately independent
of the TPU data plane.
"""

from __future__ import annotations

import json
import threading
import time
from typing import List, Optional

FAIL_THRESHOLD = 3          # consecutive failures before rotating away
PRIMARY_RETRY_SECS = 60.0   # probe back to the primary after this long


class EndpointPool:
    """Ordered endpoint list with failure rotation and primary probe-back.

    current() returns the active endpoint; on_fail(ep)/on_success(ep) feed
    back transfer outcomes.  After FAIL_THRESHOLD consecutive failures the
    pool rotates to the next endpoint; once off-primary, every
    PRIMARY_RETRY_SECS one request is steered back to the primary as a
    probe (remember-last-good semantics, SLSClientManager.cpp)."""

    def __init__(self, endpoints: List[str]):
        if not endpoints:
            raise ValueError("EndpointPool needs >= 1 endpoint")
        self.endpoints = list(endpoints)
        self._idx = 0
        self._fails = 0
        self._lock = threading.Lock()
        self._primary_probe_at = 0.0
        self._probing = False

    def current(self) -> str:
        with self._lock:
            if (self._idx != 0 and not self._probing
                    and time.monotonic() >= self._primary_probe_at):
                # steer ONE request at the primary as a health probe
                self._probing = True
                return self.endpoints[0]
            return self.endpoints[self._idx]

    def on_success(self, endpoint: str) -> None:
        with self._lock:
            if endpoint == self.endpoints[0]:
                if self._idx != 0:
                    self._idx = 0        # primary recovered — move home
                # only the probe's own outcome clears the probe state;
                # concurrent fallback successes must not re-arm a probe at
                # a still-dead primary every request
                self._probing = False
            if endpoint == self.endpoints[self._idx]:
                self._fails = 0

    def on_fail(self, endpoint: str) -> None:
        with self._lock:
            if endpoint == self.endpoints[0] and self._probing:
                # failed probe: stay on the fallback, rearm the timer
                self._probing = False
                self._primary_probe_at = (time.monotonic()
                                          + PRIMARY_RETRY_SECS)
                return
            if endpoint != self.endpoints[self._idx]:
                return  # stale result for an endpoint we already left
            self._fails += 1
            if self._fails >= FAIL_THRESHOLD:
                self._idx = (self._idx + 1) % len(self.endpoints)
                self._fails = 0
                if self._idx != 0:
                    self._primary_probe_at = (time.monotonic()
                                              + PRIMARY_RETRY_SECS)


# SLS error codes signalling QUOTA exhaustion: the server is alive but this
# project/shard is over its write budget — collapse send concurrency
# (AIMD slow path) instead of hammering it (FlusherSLS.cpp semantics).
QUOTA_ERROR_CODES = {
    "WriteQuotaExceed",
    "ProjectQuotaExceed",
    "ShardWriteQuotaExceed",
    "ExceedQuota",
}


def parse_error_code(body: bytes) -> Optional[str]:
    """SLS error bodies are JSON {"errorCode": ..., "errorMessage": ...}."""
    try:
        doc = json.loads(body)
        code = doc.get("errorCode")
        return code if isinstance(code, str) else None
    except (ValueError, AttributeError):
        return None


def classify_response(status: int, body: bytes) -> str:
    """Map one SLS send response onto a sender-queue verdict:

    ok          2xx
    retry_slow  quota exceeded (429, or 403 with a quota errorCode) —
                retry later AND collapse concurrency
    retry       transient server/network trouble (5xx, timeouts, status 0)
    drop        permanent rejection (bad request, auth, not found)
    """
    if 200 <= status < 300:
        return "ok"
    if status == 429:
        return "retry_slow"
    if status == 403:
        code = parse_error_code(body)
        if code in QUOTA_ERROR_CODES:
            return "retry_slow"
        return "retry"  # auth trouble can be transient (clock, STS rotate)
    if status >= 500 or status <= 0:
        return "retry"
    return "drop"
