"""flusher_otlp — OTLP/HTTP logs export (JSON encoding).

Reference: plugins/flusher/otlp/flusher_otlp.go (gRPC exporter). This sink
speaks OTLP/HTTP with the official JSON mapping of ExportLogsServiceRequest
(`POST {endpoint}/v1/logs`): resourceLogs → scopeLogs → logRecords with
timeUnixNano, body.stringValue, and attributes. JSON is a first-class OTLP
encoding, and it keeps the sink dependency-free.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..pipeline.serializer.event_dicts import iter_event_dicts
from .http_base import HttpSinkFlusher, basic_auth_header


def _attr(key: str, value: object) -> Dict[str, object]:
    if isinstance(value, bool):
        v: Dict[str, object] = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


class FlusherOTLP(HttpSinkFlusher):
    name = "flusher_otlp"
    supports_columnar = True

    def _init_sink(self, config: Dict[str, Any]) -> bool:
        self.endpoint = (config.get("Endpoint") or "").rstrip("/")
        self.resource_attrs = {
            str(k): str(v)
            for k, v in (config.get("ResourceAttributes") or {}).items()}
        self.auth = basic_auth_header(config)
        return bool(self.endpoint)

    def build_payload(self, groups: List[PipelineEventGroup]
                      ) -> Optional[Tuple[bytes, Dict[str, str]]]:
        records = []
        for g in groups:
            for ts, obj in iter_event_dicts(g):
                body = obj.pop("content", None)
                sev = obj.pop("level", None)
                if sev is None:
                    sev = obj.pop("severity", "")
                rec: Dict[str, object] = {
                    "timeUnixNano": str(ts * 1_000_000_000),
                    "body": {"stringValue":
                             str(body) if body is not None
                             else json.dumps(obj, ensure_ascii=False)},
                }
                if sev:
                    rec["severityText"] = str(sev)
                attrs = [_attr(k, v) for k, v in obj.items()
                         if body is not None]
                if attrs:
                    rec["attributes"] = attrs
                records.append(rec)
        if not records:
            return None
        payload = {
            "resourceLogs": [{
                "resource": {"attributes": [
                    _attr(k, v) for k, v in self.resource_attrs.items()]},
                "scopeLogs": [{
                    "scope": {"name": "loongcollector_tpu"},
                    "logRecords": records,
                }],
            }],
        }
        return (json.dumps(payload, ensure_ascii=False).encode(),
                dict(self.auth))

    def endpoint_url(self, item) -> str:
        return f"{self.endpoint}/v1/logs"
