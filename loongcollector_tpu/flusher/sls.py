"""flusher_sls — SLS-protocol sink.

Reference: core/plugin/flusher/sls/FlusherSLS.cpp (1419 LoC): Batcher →
hand-rolled PB serialize → LZ4/ZSTD → sender queue (FlusherSLS.h:124-159);
per-region endpoints, quota/backoff response handling; disk buffering of
failed payloads.  This implementation covers the wire path (serialize,
compress, auth headers, endpoint) through the shared sender-queue/HttpSink
machinery; disk-buffer spill lives in runner/disk_buffer.py.
"""

from __future__ import annotations

import hashlib
import hmac
import time
from typing import Any, Dict, List

from .. import chaos
from ..models import PipelineEventGroup
from ..pipeline.batch.batcher import Batcher
from ..pipeline.batch.flush_strategy import FlushStrategy
from ..pipeline.compression import create_compressor
from ..pipeline.plugin.interface import PluginContext
from ..pipeline.queue.sender_queue import SenderQueueItem
from ..pipeline.serializer.sls_serializer import SLSEventGroupSerializer
from .http import FlusherHTTP, HttpRequest
from .sls_client import EndpointPool, classify_response

FP_POST = chaos.register_point("sls_client.post")


class FlusherSLS(FlusherHTTP):
    name = "flusher_sls"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.project = ""
        self.logstore = ""
        self.region = ""
        self.endpoint = ""
        self.access_key_id = ""
        self.access_key_secret = ""
        self.endpoint_pool: EndpointPool = None  # type: ignore

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        self.context = context
        self.project = config.get("Project", "")
        self.logstore = config.get("Logstore", "")
        self.region = config.get("Region", "")
        self.endpoint = config.get("Endpoint", "")
        self.access_key_id = config.get("AccessKeyId", "")
        self.access_key_secret = config.get("AccessKeySecret", "")
        # multi-endpoint region pool with fallback + primary probe-back
        # (SLSClientManager.cpp); "Endpoints" extends the single "Endpoint"
        endpoints = list(config.get("Endpoints", []))
        if self.endpoint and self.endpoint not in endpoints:
            endpoints.insert(0, self.endpoint)
        self.endpoint_pool = EndpointPool(endpoints) if endpoints else None
        self.remote_url = (f"http://{self.project}.{self.endpoint}"
                           f"/logstores/{self.logstore}/shards/lb"
                           if self.endpoint else "")
        self.serializer = SLSEventGroupSerializer(
            topic=config.get("Topic", "").encode())
        self.compressor = create_compressor(
            config.get("CompressType", "lz4"))
        strategy = FlushStrategy(
            min_cnt=int(config.get("MinCnt", 4096)),
            min_size_bytes=int(config.get("MinSizeBytes", 512 * 1024)),
            max_size_bytes=int(config.get("MaxSizeBytes", 5 * 1024 * 1024)),
            timeout_secs=float(config.get("TimeoutSecs", 1.0)))
        self._init_exactly_once(config, context)
        self.batcher = Batcher(strategy, on_flush=self._serialize_and_push,
                               flusher_id=self.name,
                               pipeline_name=context.pipeline_name)
        return bool(self.logstore)

    def build_request(self, item: SenderQueueItem) -> HttpRequest:
        # a fault here rides the build_request-failure path: FlusherRunner
        # backs the item off and feeds the sink circuit breaker
        chaos.faultpoint(FP_POST)
        endpoint = (self.endpoint_pool.current() if self.endpoint_pool
                    else self.endpoint)
        item.tag["sls_endpoint"] = endpoint
        date = time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime())
        md5 = hashlib.md5(item.data).hexdigest().upper()
        headers = {
            "Content-Type": "application/x-protobuf",
            "Content-MD5": md5,
            "Date": date,
            "Host": f"{self.project}.{endpoint}",
            "x-log-apiversion": "0.6.0",
            "x-log-bodyrawsize": str(item.raw_size),
            "x-log-signaturemethod": "hmac-sha1",
        }
        if self.compressor.name != "none":
            headers["x-log-compresstype"] = self.compressor.name
        if self.access_key_id:
            resource = f"/logstores/{self.logstore}/shards/lb"
            canon_headers = "".join(
                f"{k}:{headers[k]}\n" for k in sorted(headers)
                if k.startswith("x-log-") or k.startswith("x-acs-"))
            sign_str = (f"POST\n{md5}\napplication/x-protobuf\n{date}\n"
                        f"{canon_headers}{resource}")
            sig = hmac.new(self.access_key_secret.encode(),
                           sign_str.encode(), hashlib.sha1).digest()
            import base64
            headers["Authorization"] = (
                f"LOG {self.access_key_id}:"
                f"{base64.b64encode(sig).decode()}")
        url = (f"http://{self.project}.{endpoint}"
               f"/logstores/{self.logstore}/shards/lb")
        return HttpRequest("POST", url, headers, item.data)

    def on_send_done(self, item: SenderQueueItem, status: int,
                     body: bytes) -> str:
        verdict = classify_response(status, body)
        endpoint = item.tag.pop("sls_endpoint", None)
        if self.endpoint_pool is not None and endpoint:
            # endpoint health feedback: ANY HTTP response proves the
            # endpoint is reachable — quota (retry_slow) and 4xx (drop)
            # responses count as endpoint-healthy so a pending primary
            # probe always resolves; only network/5xx failures rotate
            if verdict == "ok" or (400 <= status < 500):
                self.endpoint_pool.on_success(endpoint)
            elif verdict in ("retry", "retry_slow"):
                self.endpoint_pool.on_fail(endpoint)
        cp = item.tag.get("eo_cp")
        if verdict == "ok":
            if cp is not None and self.eo_sender is not None:
                self.eo_sender.commit_slot(cp)
            return "ok"
        if verdict in ("retry", "retry_slow"):
            return verdict
        if cp is not None and self.eo_sender is not None:
            self.eo_sender.commit_slot(cp)  # discard-ack frees the slot
        return "drop"
