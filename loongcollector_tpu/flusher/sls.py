"""flusher_sls — SLS-protocol sink.

Reference: core/plugin/flusher/sls/FlusherSLS.cpp (1419 LoC): Batcher →
hand-rolled PB serialize → LZ4/ZSTD → sender queue (FlusherSLS.h:124-159);
per-region endpoints, quota/backoff response handling; disk buffering of
failed payloads.  This implementation covers the wire path (serialize,
compress, auth headers, endpoint) through the shared sender-queue/HttpSink
machinery; disk-buffer spill lives in runner/disk_buffer.py.
"""

from __future__ import annotations

import hashlib
import hmac
import time
from typing import Any, Dict, List

from ..models import PipelineEventGroup
from ..pipeline.batch.batcher import Batcher
from ..pipeline.batch.flush_strategy import FlushStrategy
from ..pipeline.compression import create_compressor
from ..pipeline.plugin.interface import PluginContext
from ..pipeline.queue.sender_queue import SenderQueueItem
from ..pipeline.serializer.sls_serializer import SLSEventGroupSerializer
from .http import FlusherHTTP, HttpRequest


class FlusherSLS(FlusherHTTP):
    name = "flusher_sls"

    def __init__(self) -> None:
        super().__init__()
        self.project = ""
        self.logstore = ""
        self.region = ""
        self.endpoint = ""
        self.access_key_id = ""
        self.access_key_secret = ""

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        self.context = context
        self.project = config.get("Project", "")
        self.logstore = config.get("Logstore", "")
        self.region = config.get("Region", "")
        self.endpoint = config.get("Endpoint", "")
        self.access_key_id = config.get("AccessKeyId", "")
        self.access_key_secret = config.get("AccessKeySecret", "")
        self.remote_url = (f"http://{self.project}.{self.endpoint}"
                           f"/logstores/{self.logstore}/shards/lb"
                           if self.endpoint else "")
        self.serializer = SLSEventGroupSerializer(
            topic=config.get("Topic", "").encode())
        self.compressor = create_compressor(
            config.get("CompressType", "lz4"))
        strategy = FlushStrategy(
            min_cnt=int(config.get("MinCnt", 4096)),
            min_size_bytes=int(config.get("MinSizeBytes", 512 * 1024)),
            max_size_bytes=int(config.get("MaxSizeBytes", 5 * 1024 * 1024)),
            timeout_secs=float(config.get("TimeoutSecs", 1.0)))
        self._init_exactly_once(config, context)
        self.batcher = Batcher(strategy, on_flush=self._serialize_and_push,
                               flusher_id=self.name,
                               pipeline_name=context.pipeline_name)
        return bool(self.logstore)

    def build_request(self, item: SenderQueueItem) -> HttpRequest:
        date = time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime())
        md5 = hashlib.md5(item.data).hexdigest().upper()
        headers = {
            "Content-Type": "application/x-protobuf",
            "Content-MD5": md5,
            "Date": date,
            "Host": f"{self.project}.{self.endpoint}",
            "x-log-apiversion": "0.6.0",
            "x-log-bodyrawsize": str(item.raw_size),
            "x-log-signaturemethod": "hmac-sha1",
        }
        if self.compressor.name != "none":
            headers["x-log-compresstype"] = self.compressor.name
        if self.access_key_id:
            resource = f"/logstores/{self.logstore}/shards/lb"
            canon_headers = "".join(
                f"{k}:{headers[k]}\n" for k in sorted(headers)
                if k.startswith("x-log-") or k.startswith("x-acs-"))
            sign_str = (f"POST\n{md5}\napplication/x-protobuf\n{date}\n"
                        f"{canon_headers}{resource}")
            sig = hmac.new(self.access_key_secret.encode(),
                           sign_str.encode(), hashlib.sha1).digest()
            import base64
            headers["Authorization"] = (
                f"LOG {self.access_key_id}:"
                f"{base64.b64encode(sig).decode()}")
        return HttpRequest("POST", self.remote_url, headers, item.data)

    def on_send_done(self, item: SenderQueueItem, status: int,
                     body: bytes) -> str:
        cp = item.tag.get("eo_cp")
        if 200 <= status < 300:
            if cp is not None and self.eo_sender is not None:
                self.eo_sender.commit_slot(cp)
            return "ok"
        if status in (403, 429, 500, 502, 503) or status <= 0:
            return "retry"  # quota/server errors back off (reference semantics)
        if cp is not None and self.eo_sender is not None:
            self.eo_sender.commit_slot(cp)  # discard-ack frees the slot
        return "drop"
