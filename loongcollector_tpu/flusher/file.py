"""flusher_file — local file sink (reference
core/plugin/flusher/file/FlusherFile.cpp: spdlog-based JSON sink)."""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List

from ..models import PipelineEventGroup
from ..pipeline.batch.batcher import Batcher
from ..pipeline.batch.flush_strategy import FlushStrategy
from ..pipeline.plugin.interface import Flusher, PluginContext
from ..pipeline.serializer.json_serializer import JsonSerializer


class FlusherFile(Flusher):
    name = "flusher_file"
    supports_columnar = True
    # loongledger: NOT ledger_terminal — send() only stages into the
    # batcher (whose occupancy the auditor counts); the terminal record
    # lands in _flush_groups AFTER the write, so a failed write is a
    # visible drop, never a pre-booked send_ok

    def __init__(self) -> None:
        super().__init__()
        self.file_path = ""
        self.serializer = JsonSerializer()
        self.batcher: Batcher = None  # type: ignore
        self._lock = threading.Lock()

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.file_path = config.get("FilePath", "")
        if not self.file_path:
            return False
        d = os.path.dirname(self.file_path)
        if d:
            os.makedirs(d, exist_ok=True)
        strategy = FlushStrategy(
            min_cnt=int(config.get("MinCnt", 0)),
            min_size_bytes=int(config.get("MinSizeBytes", 256 * 1024)),
            timeout_secs=float(config.get("TimeoutSecs", 1.0)))
        self.batcher = Batcher(strategy, on_flush=self._flush_groups,
                               flusher_id=self.name,
                               pipeline_name=context.pipeline_name)
        return True

    def send(self, group: PipelineEventGroup) -> bool:
        self.batcher.add(group)
        return True

    def _flush_groups(self, groups: List[PipelineEventGroup]) -> None:
        def write():
            data = self.serializer.serialize(groups)
            with self._lock:
                with open(self.file_path, "ab") as f:
                    f.write(data)
        self._ledger_terminal_write(groups, write)

    def flush_all(self) -> bool:
        self.batcher.flush_all()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self.batcher.flush_all()
        self.batcher.close()
        return True
