"""flusher_elasticsearch — bulk NDJSON sink.

Reference: plugins/flusher/elasticsearch/flusher_elasticsearch.go — config
Addresses, Index (dynamic %{field} patterns), Authentication.PlainText;
events ship as `_bulk` action/source line pairs.
"""

from __future__ import annotations

import json
import re
from datetime import datetime, timezone
from typing import Any, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..pipeline.serializer.event_dicts import iter_event_dicts
from .http_base import AddressRotator, HttpSinkFlusher, basic_auth_header

_PATTERN = re.compile(r"%\{([^}]+)\}")


def resolve_dynamic(template: str, obj: Dict[str, object]) -> str:
    """%{content.key} / %{tag.key} / %{key} → value from the event dict
    (the Go flusher's dynamic index convention)."""
    def sub(m):
        key = m.group(1)
        for k in (key, key.split(".", 1)[-1]):
            v = obj.get(k)
            if v is not None:
                return str(v)
        return "unknown"
    return _PATTERN.sub(sub, template)


class FlusherElasticsearch(HttpSinkFlusher):
    name = "flusher_elasticsearch"
    content_type = "application/x-ndjson"

    def _init_sink(self, config: Dict[str, Any]) -> bool:
        self.rotator = AddressRotator(config.get("Addresses", []))
        self.index = config.get("Index", "")
        self.auth = basic_auth_header(config)
        return bool(self.rotator) and bool(self.index)

    def build_payload(self, groups: List[PipelineEventGroup]
                      ) -> Optional[Tuple[bytes, Dict[str, str]]]:
        lines: List[bytes] = []
        dynamic = "%{" in self.index
        for g in groups:
            for ts, obj in iter_event_dicts(g):
                idx = resolve_dynamic(self.index, obj) if dynamic \
                    else self.index
                # ISO-8601: ES date fields parse bare ints as epoch_MILLIS,
                # which would land epoch-seconds logs in January 1970
                obj.setdefault("@timestamp", datetime.fromtimestamp(
                    ts, tz=timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"))
                lines.append(json.dumps(
                    {"index": {"_index": idx}}).encode())
                lines.append(json.dumps(obj, ensure_ascii=False).encode())
        if not lines:
            return None
        return b"\n".join(lines) + b"\n", self.auth

    def endpoint_url(self, item) -> str:
        return f"{self.rotator.next()}/_bulk"
