"""flusher_elasticsearch — bulk NDJSON sink.

Reference: plugins/flusher/elasticsearch/flusher_elasticsearch.go — config
Addresses, Index (dynamic %{field} patterns), Authentication.PlainText;
events ship as `_bulk` action/source line pairs.
"""

from __future__ import annotations

import json
import re
from datetime import datetime, timezone
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..models import PipelineEventGroup
from ..pipeline.serializer.batch_json import (TS_ISO8601, dumps_row,
                                              native_group_rows)
from ..pipeline.serializer.event_dicts import iter_event_dicts
from .http_base import AddressRotator, HttpSinkFlusher, basic_auth_header

_PATTERN = re.compile(r"%\{([^}]+)\}")


def resolve_dynamic(template: str, obj: Dict[str, object]) -> str:
    """%{content.key} / %{tag.key} / %{key} → value from the event dict
    (the Go flusher's dynamic index convention)."""
    def sub(m):
        key = m.group(1)
        for k in (key, key.split(".", 1)[-1]):
            v = obj.get(k)
            if v is not None:
                return str(v)
        return "unknown"
    return _PATTERN.sub(sub, template)


class FlusherElasticsearch(HttpSinkFlusher):
    name = "flusher_elasticsearch"
    supports_columnar = True
    content_type = "application/x-ndjson"

    def _init_sink(self, config: Dict[str, Any]) -> bool:
        self.rotator = AddressRotator(config.get("Addresses", []))
        self.index = config.get("Index", "")
        self.auth = basic_auth_header(config)
        return bool(self.rotator) and bool(self.index)

    def build_payload(self, groups: List[PipelineEventGroup]
                      ) -> Optional[Tuple[bytes, Dict[str, str]]]:
        parts: List = []
        empty = True
        dynamic = "%{" in self.index
        action = json.dumps({"index": {"_index": self.index}}).encode() \
            + b"\n"
        for g in groups:
            fast = None
            if not dynamic and self._ts_in_range(g):
                # shared batched serializer (loongshard): action line rides
                # as the row head, @timestamp appended as ISO-8601 —
                # byte-identical to the dict loop below
                fast = native_group_rows(g, "@timestamp",
                                         ts_mode=TS_ISO8601,
                                         ts_first=False, head=action)
            if fast is not None:
                if len(fast):
                    parts.append(fast)
                    empty = False
                continue
            for ts, obj in iter_event_dicts(g):
                idx = resolve_dynamic(self.index, obj) if dynamic \
                    else self.index
                # ISO-8601: ES date fields parse bare ints as epoch_MILLIS,
                # which would land epoch-seconds logs in January 1970
                obj.setdefault("@timestamp", datetime.fromtimestamp(
                    ts, tz=timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"))
                parts.append(json.dumps(
                    {"index": {"_index": idx}}).encode() + b"\n")
                parts.append(dumps_row(obj) + b"\n")
                empty = False
        if empty:
            return None
        return b"".join(parts), self.auth

    #: last epoch second of year 9999 — datetime.fromtimestamp raises past
    #: it, so the canonical dict path would surface such timestamps as a
    #: flusher error; the fast path must not silently serialize them
    _TS_MAX = 253402300799

    @classmethod
    def _ts_in_range(cls, group: PipelineEventGroup) -> bool:
        """Fast path only for sane epochs (0 <= ts <= year 9999):
        strftime("%Y") padding for years before 1000 is platform libc
        behaviour the native ISO-8601 writer does not chase, and a
        millisecond-epoch outlier must fail loudly on the dict path, not
        ship a five-digit year."""
        cols = group.columns
        if cols is None:
            return False
        tss = np.asarray(cols.timestamps)
        return bool(len(tss) == 0
                    or (int(tss.min()) >= 0
                        and int(tss.max()) <= cls._TS_MAX))

    def endpoint_url(self, item) -> str:
        return f"{self.rotator.next()}/_bulk"
