"""Minimal Kafka wire-protocol client (no external client library).

Reference: core/plugin/flusher/kafka/KafkaProducer.cpp and
plugins/input/kafka/input_kafka.go both wrap vendor clients
(librdkafka / sarama); this image has neither, so both directions speak the
public wire protocol directly:

  producer — Metadata (v1) for leader discovery and Produce (v3) with
  record batches (magic v2, varint-framed records, CRC32C over the body);
  consumer — the full group-membership protocol (FindCoordinator /
  JoinGroup / SyncGroup / Heartbeat with range+roundrobin assignors),
  OffsetFetch/OffsetCommit, ListOffsets resets, and Fetch (v4) with
  record-batch decoding.

Scope: plaintext or TLS brokers, SASL PLAIN/SCRAM, acks=all/1, single
in-flight request per connection.  CRC32C comes from the native library
when present, else a Python table fallback.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import chaos
from ..utils.logger import get_logger

log = get_logger("kafka")

FP_PRODUCE = chaos.register_point("kafka_client.produce")

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9
API_FIND_COORDINATOR = 10
API_JOIN_GROUP = 11
API_HEARTBEAT = 12
API_LEAVE_GROUP = 13
API_SYNC_GROUP = 14
API_SASL_HANDSHAKE = 17
API_SASL_AUTHENTICATE = 36

# error codes the consumer acts on
ERR_OFFSET_OUT_OF_RANGE = 1
ERR_UNKNOWN_TOPIC_OR_PARTITION = 3
ERR_NOT_LEADER = 6
ERR_COORDINATOR_NOT_AVAILABLE = 15
ERR_NOT_COORDINATOR = 16
ERR_ILLEGAL_GENERATION = 22
ERR_UNKNOWN_MEMBER_ID = 25
ERR_REBALANCE_IN_PROGRESS = 27
ERR_MEMBER_ID_REQUIRED = 79


# ---------------------------------------------------------------------------
# CRC32C
# ---------------------------------------------------------------------------

_crc_table: Optional[List[int]] = None


def _crc32c_py(data: bytes, seed: int = 0) -> int:
    global _crc_table
    if _crc_table is None:
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
            table.append(crc)
        _crc_table = table
    crc = seed ^ 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _crc_table[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def crc32c(data: bytes) -> int:
    try:
        import ctypes

        import numpy as np

        from ..native import get_lib
        lib = get_lib()
        if lib is not None:
            if not hasattr(lib, "_crc_configured"):
                lib.lct_crc32c.restype = ctypes.c_uint32
                lib.lct_crc32c.argtypes = [ctypes.POINTER(ctypes.c_uint8),
                                           ctypes.c_int64, ctypes.c_uint32]
                lib._crc_configured = True
            arr = np.frombuffer(data, dtype=np.uint8)
            return int(lib.lct_crc32c(
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                len(arr), 0))
    # deliberate capability probe, not a send path: ANY native-lib trouble
    # (missing .so, ctypes mismatch) falls back to the pure-python table —
    # there is no payload or signal to preserve here
    # loonglint: disable=swallowed-fault
    except Exception:  # noqa: BLE001
        pass
    return _crc32c_py(data)


# ---------------------------------------------------------------------------
# wire helpers
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    """Kafka zigzag varint."""
    z = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    data = s.encode()
    return struct.pack(">h", len(data)) + data


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def i16(self) -> int:
        v = struct.unpack_from(">h", self.data, self.pos)[0]
        self.pos += 2
        return v

    def i32(self) -> int:
        v = struct.unpack_from(">i", self.data, self.pos)[0]
        self.pos += 4
        return v

    def i64(self) -> int:
        v = struct.unpack_from(">q", self.data, self.pos)[0]
        self.pos += 8
        return v

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        v = self.data[self.pos : self.pos + n].decode()
        self.pos += n
        return v

    def array(self, fn):
        return [fn() for _ in range(self.i32())]


# ---------------------------------------------------------------------------
# record batch v2
# ---------------------------------------------------------------------------


def build_record_batch(records: List[Tuple[Optional[bytes], bytes]],
                       base_ts_ms: Optional[int] = None) -> bytes:
    """records: [(key, value)] → one magic-v2 record batch."""
    now = base_ts_ms if base_ts_ms is not None else int(time.time() * 1000)
    body = bytearray()
    for i, (key, value) in enumerate(records):
        rec = bytearray()
        rec += b"\x00"                      # attributes
        rec += _varint(0)                   # timestamp delta
        rec += _varint(i)                   # offset delta
        if key is None:
            rec += _varint(-1)
        else:
            rec += _varint(len(key)) + key
        rec += _varint(len(value)) + value
        rec += _varint(0)                   # headers count
        body += _varint(len(rec)) + rec

    n = len(records)
    # batch body after the CRC field
    after_crc = bytearray()
    after_crc += struct.pack(">h", 0)       # attributes (no compression)
    after_crc += struct.pack(">i", n - 1)   # last offset delta
    after_crc += struct.pack(">q", now)     # first timestamp
    after_crc += struct.pack(">q", now)     # max timestamp
    after_crc += struct.pack(">q", -1)      # producer id
    after_crc += struct.pack(">h", -1)      # producer epoch
    after_crc += struct.pack(">i", -1)      # base sequence
    after_crc += struct.pack(">i", n)       # record count
    after_crc += body

    crc = crc32c(bytes(after_crc))
    batch = bytearray()
    batch += struct.pack(">q", 0)           # base offset
    batch_len = 4 + 1 + 4 + len(after_crc)  # partition leader epoch..end
    batch += struct.pack(">i", batch_len)
    batch += struct.pack(">i", -1)          # partition leader epoch
    batch += struct.pack(">b", 2)           # magic
    batch += struct.pack(">I", crc)
    batch += after_crc
    return bytes(batch)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class KafkaError(Exception):
    pass


class KafkaPipelineError(KafkaError):
    """A pipelined window failed partway: `responses` holds the replies
    that WERE read (FIFO order, so responses[i] answers request i).  The
    caller uses them to ack the delivered prefix instead of retrying the
    whole window."""

    def __init__(self, message: str, responses: List[bytes]):
        super().__init__(message)
        self.responses = responses


class KafkaProduceError(KafkaError):
    """Produce failed for part of a send: `unacked` holds exactly the
    (key, value) records the broker did not acknowledge — the retry set.
    Records absent from `unacked` were acked and must NOT be re-sent
    (at-least-once without gratuitous duplication)."""

    def __init__(self, message: str,
                 unacked: List[Tuple[Optional[bytes], bytes]]):
        super().__init__(message)
        self.unacked = unacked


def _scram_escape(name: str) -> str:
    """RFC 5802 saslname escaping: ',' and '=' are reserved."""
    return name.replace("=", "=3D").replace(",", "=2C")


class KafkaClient:
    """Shared transport: connections, TLS, SASL, correlation ids, metadata."""

    def __init__(self, brokers: List[str],
                 client_id: str = "loongcollector-tpu",
                 timeout_ms: int = 10000,
                 tls: Optional[dict] = None, sasl: Optional[dict] = None):
        """tls: {CAFile, CertFile, KeyFile, InsecureSkipVerify} — enables
        TLS when present (reference KafkaProducer.cpp:41 ssl.* settings).
        sasl: {Mechanism: PLAIN|SCRAM-SHA-256|SCRAM-SHA-512, Username,
        Password} (reference :111 sasl.* settings; Kerberos/GSSAPI is out
        of scope — no KDC in this runtime)."""
        self.brokers = brokers
        self.client_id = client_id
        self.timeout_ms = timeout_ms
        self.tls = tls
        self.sasl = sasl
        self._corr = 0
        self._conns: Dict[str, socket.socket] = {}
        # topic -> [(partition, leader "host:port")]
        self._topic_meta: Dict[str, List[Tuple[int, str]]] = {}
        self._rr: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- transport ----------------------------------------------------------

    def _wrap_tls(self, sock: socket.socket, host: str) -> socket.socket:
        import ssl
        cfg = self.tls or {}
        if cfg.get("InsecureSkipVerify"):
            ctx = ssl._create_unverified_context()
        else:
            ctx = ssl.create_default_context(cafile=cfg.get("CAFile"))
        cert, key = cfg.get("CertFile"), cfg.get("KeyFile")
        if cert:
            ctx.load_cert_chain(cert, key)
        return ctx.wrap_socket(sock, server_hostname=host)

    def _next_corr(self) -> int:
        """Correlation-id allocation is a read-modify-write shared between
        the sender thread and main-thread metadata/close paths — under the
        lock, or two in-flight requests can claim the same id and fail
        each other's correlation check."""
        with self._lock:
            self._corr += 1
            return self._corr

    def _connect(self, addr: str) -> socket.socket:
        with self._lock:
            sock = self._conns.get(addr)
        if sock is not None:
            return sock
        host, _, port = addr.rpartition(":")
        sock = socket.create_connection((host, int(port or 9092)), timeout=10)
        try:
            if self.tls is not None:
                sock = self._wrap_tls(sock, host)
            if self.sasl is not None:
                self._sasl_handshake(sock)
        except (OSError, KafkaError):
            try:
                sock.close()
            except OSError:
                pass
            raise
        with self._lock:
            cur = self._conns.get(addr)
            if cur is None:
                self._conns[addr] = sock
                return sock
        # lost a connect race: keep the established entry, release ours
        try:
            sock.close()
        except OSError:
            pass
        return cur

    # -- SASL ---------------------------------------------------------------

    def _raw_request(self, sock: socket.socket, api: int, version: int,
                     payload: bytes) -> bytes:
        """One request/response on an ALREADY-OPEN socket (the handshake
        must not recurse into _connect)."""
        corr = self._next_corr()
        header = (struct.pack(">hhi", api, version, corr)
                  + _str(self.client_id))
        msg = header + payload
        sock.sendall(struct.pack(">i", len(msg)) + msg)
        raw = self._read_exact(sock, 4)
        size = struct.unpack(">i", raw)[0]
        resp = self._read_exact(sock, size)
        got = struct.unpack(">i", resp[:4])[0]
        if got != corr:
            raise KafkaError(f"correlation mismatch {got} != {corr}")
        return resp[4:]

    def _sasl_authenticate(self, sock: socket.socket,
                           auth_bytes: bytes) -> bytes:
        resp = _Reader(self._raw_request(
            sock, API_SASL_AUTHENTICATE, 0, _bytes(auth_bytes)))
        err = resp.i16()
        err_msg = resp.string()
        n = resp.i32()
        out = resp.data[resp.pos:resp.pos + n] if n >= 0 else b""
        if err != 0:
            raise KafkaError(f"SASL authenticate failed ({err}): {err_msg}")
        return out

    def _sasl_handshake(self, sock: socket.socket) -> None:
        mech = (self.sasl.get("Mechanism") or "PLAIN").upper()
        user = self.sasl.get("Username") or ""
        password = self.sasl.get("Password") or ""
        resp = _Reader(self._raw_request(
            sock, API_SASL_HANDSHAKE, 1, _str(mech)))
        err = resp.i16()
        if err != 0:
            mechs = resp.array(resp.string)
            raise KafkaError(
                f"SASL mechanism {mech} rejected ({err}); broker offers "
                f"{mechs}")
        if mech == "PLAIN":
            self._sasl_authenticate(
                sock, b"\0" + user.encode() + b"\0" + password.encode())
        elif mech in ("SCRAM-SHA-256", "SCRAM-SHA-512"):
            self._sasl_scram(sock, mech, user, password)
        else:
            raise KafkaError(f"unsupported SASL mechanism {mech}")

    def _sasl_scram(self, sock: socket.socket, mech: str, user: str,
                    password: str) -> None:
        """RFC 5802 SCRAM over KIP-84 SaslAuthenticate rounds."""
        algo = "sha256" if mech.endswith("256") else "sha512"
        H = getattr(hashlib, algo)
        nonce = base64.b64encode(os.urandom(18)).decode()
        gs2 = "n,,"
        cf_bare = f"n={_scram_escape(user)},r={nonce}"
        server_first = self._sasl_authenticate(
            sock, (gs2 + cf_bare).encode()).decode()
        parts = dict(p.split("=", 1) for p in server_first.split(","))
        r, s, i = parts["r"], parts["s"], int(parts["i"])
        if not r.startswith(nonce):
            raise KafkaError("SCRAM server nonce does not extend ours")
        salted = hashlib.pbkdf2_hmac(algo, password.encode(),
                                     base64.b64decode(s), i)
        client_key = hmac.new(salted, b"Client Key", H).digest()
        stored_key = H(client_key).digest()
        cf_woproof = f"c={base64.b64encode(gs2.encode()).decode()},r={r}"
        auth_msg = f"{cf_bare},{server_first},{cf_woproof}".encode()
        client_sig = hmac.new(stored_key, auth_msg, H).digest()
        proof = bytes(a ^ b for a, b in zip(client_key, client_sig))
        final = f"{cf_woproof},p={base64.b64encode(proof).decode()}"
        server_final = self._sasl_authenticate(sock, final.encode()).decode()
        fparts = dict(p.split("=", 1) for p in server_final.split(","))
        if "e" in fparts:
            raise KafkaError(f"SCRAM server error: {fparts['e']}")
        server_key = hmac.new(salted, b"Server Key", H).digest()
        expect = hmac.new(server_key, auth_msg, H).digest()
        if base64.b64decode(fparts.get("v", "")) != expect:
            raise KafkaError("SCRAM server signature verification failed")

    def _drop(self, addr: str) -> None:
        with self._lock:
            sock = self._conns.pop(addr, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _request(self, addr: str, api_key: int, api_version: int,
                 payload: bytes, expect_response: bool = True
                 ) -> Optional[bytes]:
        # connect FIRST: the TLS/SASL handshake inside _connect consumes
        # correlation ids of its own, so ours is allocated after it
        try:
            sock = self._connect(addr)
        except OSError as e:
            # keep the KafkaError contract: a refused/reset connect must
            # not escape raw and kill the caller's sender thread
            raise KafkaError(f"broker {addr}: {e}") from e
        my_corr = self._next_corr()
        header = (struct.pack(">hhi", api_key, api_version, my_corr)
                  + _str(self.client_id))
        msg = header + payload
        try:
            sock.sendall(struct.pack(">i", len(msg)) + msg)
            if not expect_response:
                return None
            raw = self._read_exact(sock, 4)
            size = struct.unpack(">i", raw)[0]
            resp = self._read_exact(sock, size)
        except OSError as e:
            self._drop(addr)
            raise KafkaError(f"broker {addr}: {e}") from e
        corr = struct.unpack(">i", resp[:4])[0]
        if corr != my_corr:
            self._drop(addr)
            raise KafkaError("correlation id mismatch")
        return resp[4:]

    def _pipeline_requests(self, addr: str,
                           reqs: List[Tuple[int, int, bytes]],
                           expect_response: bool = True,
                           max_in_flight: int = 5) -> List[bytes]:
        """Pipelined request windows: write up to `max_in_flight` requests
        before reading the first response (librdkafka's
        max.in.flight.requests.per.connection).  Kafka answers a
        connection's requests strictly in order, so FIFO correlation-id
        matching preserves ordering; one socket error drops the connection
        and fails the whole window (the caller's retry re-sends it — the
        same at-least-once contract as the serial path)."""
        # a connect/handshake failure means NOTHING in this batch was
        # delivered: surface it as a pipeline error with zero responses so
        # produce() books every payload as unacked instead of aborting
        try:
            sock = self._connect(addr)
        except (OSError, KafkaError) as e:
            self._drop(addr)
            raise KafkaPipelineError(f"broker {addr}: {e}", []) from e
        out: List[bytes] = []
        try:
            for w in range(0, len(reqs), max_in_flight):
                window = reqs[w:w + max_in_flight]
                corrs = []
                buf = bytearray()
                for api_key, api_version, payload in window:
                    corr = self._next_corr()
                    corrs.append(corr)
                    header = (struct.pack(">hhi", api_key, api_version,
                                          corr)
                              + _str(self.client_id))
                    msg = header + payload
                    buf += struct.pack(">i", len(msg)) + msg
                sock.sendall(buf)
                if not expect_response:
                    continue
                for my_corr in corrs:
                    raw = self._read_exact(sock, 4)
                    size = struct.unpack(">i", raw)[0]
                    resp = self._read_exact(sock, size)
                    corr = struct.unpack(">i", resp[:4])[0]
                    if corr != my_corr:
                        raise KafkaError("correlation id mismatch")
                    out.append(resp[4:])
        except (OSError, KafkaError) as e:
            self._drop(addr)
            msg = str(e) if isinstance(e, KafkaError) else \
                f"broker {addr}: {e}"
            # responses already read answer a delivered prefix — hand
            # them back so the caller retries only the unacked tail
            raise KafkaPipelineError(msg, out) from e
        return out

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> bytes:
        from ..utils.netio import read_exact
        return read_exact(sock, n)    # ConnectionError IS-A OSError

    # -- metadata -----------------------------------------------------------

    def refresh_metadata(self, topic: str) -> None:
        payload = struct.pack(">i", 1) + _str(topic)
        last_err = None
        for addr in self.brokers:
            try:
                resp = self._request(addr, API_METADATA, 1, payload)
            except (KafkaError, OSError) as e:
                # OSError covers connect refusals and TLS handshake
                # failures (ssl.SSLError ⊂ OSError) — one bad broker must
                # not defeat multi-broker failover
                last_err = e if isinstance(e, KafkaError) else \
                    KafkaError(f"broker {addr}: {e}")
                continue
            r = _Reader(resp)
            brokers = {}
            for _ in range(r.i32()):
                node = r.i32()
                host = r.string()
                port = r.i32()
                r.string()  # rack
                brokers[node] = f"{host}:{port}"
            r.i32()  # controller id (v1 layout: brokers, controller, topics)
            parts: List[Tuple[int, str]] = []
            for _ in range(r.i32()):
                r.i16()          # topic error
                r.string()       # topic name
                r.data[r.pos]    # is_internal (bool)
                r.pos += 1
                for _ in range(r.i32()):
                    r.i16()      # partition error
                    pid = r.i32()
                    leader = r.i32()
                    r.array(r.i32)   # replicas
                    r.array(r.i32)   # isr
                    if leader in brokers:
                        parts.append((pid, brokers[leader]))
            if parts:
                with self._lock:
                    self._topic_meta[topic] = sorted(parts)
                return
        raise last_err or KafkaError("no brokers reachable")

    def close(self) -> None:
        with self._lock:
            addrs = list(self._conns)
        for addr in addrs:
            self._drop(addr)


class KafkaProducer(KafkaClient):
    def __init__(self, brokers: List[str],
                 client_id: str = "loongcollector-tpu",
                 acks: int = -1, timeout_ms: int = 10000,
                 tls: Optional[dict] = None, sasl: Optional[dict] = None,
                 max_in_flight: int = 5):
        super().__init__(brokers, client_id, timeout_ms, tls, sasl)
        self.acks = acks
        # deep produce pipelining like librdkafka
        # (core/plugin/flusher/kafka/KafkaProducer.cpp:41 wraps it; this
        # client speaks the wire protocol, so the window lives here)
        self.max_in_flight = max(1, int(max_in_flight))

    # -- produce ------------------------------------------------------------

    def _pick_partition(self, topic: str, key: Optional[bytes],
                        nparts: int) -> int:
        """Keyed records hash to a stable partition (per-key ordering);
        unkeyed records round-robin."""
        if key:
            import hashlib
            return int.from_bytes(
                hashlib.md5(key).digest()[:4], "big") % nparts
        idx = self._rr.get(topic, 0)
        self._rr[topic] = idx + 1
        return idx % nparts

    def send(self, topic: str,
             records: List[Tuple[Optional[bytes], bytes]]) -> None:
        # chaos: "error" = broker unreachable before anything shipped (all
        # records unacked); "partial" = only a window prefix reaches the
        # broker — the prefix is sent for real, the suffix is reported
        # unacked exactly like a mid-window connection drop, so the
        # caller's partial-ack retry path is exercised without any loss
        decision = chaos.faultpoint(FP_PRODUCE, exc=KafkaError)
        if decision is not None and decision.action == chaos.ACTION_PARTIAL \
                and len(records) > 1:
            k = max(1, int(len(records) * decision.magnitude))
            prefix, suffix = records[:k], records[k:]
            try:
                self.send(topic, prefix)
            except KafkaProduceError as e:
                raise KafkaProduceError(
                    f"chaos partial window: {e}",
                    list(e.unacked) + suffix) from e
            raise KafkaProduceError(
                f"chaos[{FP_PRODUCE}#{decision.hit}]: window cut after "
                f"{k}/{len(records)} records", suffix)
        with self._lock:
            parts = self._topic_meta.get(topic)
        if not parts:
            self.refresh_metadata(topic)
            with self._lock:
                parts = self._topic_meta.get(topic, [])
        if not parts:
            raise KafkaError(f"no partitions for topic {topic}")
        leaders = dict(parts)
        nparts = len(parts)
        by_partition: Dict[int, List[Tuple[Optional[bytes], bytes]]] = {}
        for key, value in records:
            pid = self._pick_partition(topic, key, nparts)
            by_partition.setdefault(pid, []).append((key, value))
        # group per leader and PIPELINE: per-partition batches ride one
        # connection in max_in_flight windows instead of one blocking RTT
        # each; per-partition order is preserved (single connection, FIFO
        # responses).  Each payload keeps its backing records so a partial
        # window failure can report exactly the unacked set.
        by_leader: Dict[str, List[Tuple[bytes, List[Tuple[Optional[bytes],
                                                          bytes]]]]] = {}
        for partition, recs in by_partition.items():
            leader = leaders.get(partition)
            if leader is None:
                raise KafkaError(f"no leader for {topic}/{partition}")
            by_leader.setdefault(leader, []).append(
                (self._produce_payload(topic, partition, recs), recs))
        unacked: List[Tuple[Optional[bytes], bytes]] = []
        first_err: Optional[KafkaError] = None
        for leader, entries in by_leader.items():
            reqs = [(API_PRODUCE, 3, payload) for payload, _ in entries]
            try:
                resps = self._pipeline_requests(
                    leader, reqs, expect_response=(self.acks != 0),
                    max_in_flight=self.max_in_flight)
                err: Optional[KafkaError] = None
            except KafkaPipelineError as e:
                resps, err = e.responses, e
            if err is not None:
                with self._lock:
                    self._topic_meta.pop(topic, None)  # stale leader
                first_err = first_err or err
                if self.acks == 0:
                    # fire-and-forget: no acks exist, the whole leader
                    # group is in doubt — classic at-least-once retry
                    for _, recs in entries:
                        unacked.extend(recs)
                    continue
            # responses arrive FIFO: resps[i] answers entries[i]; payloads
            # past the received prefix were never acked
            for i, (_payload, recs) in enumerate(entries):
                if err is None and self.acks == 0:
                    continue                      # acks=0 clean send
                if i < len(resps):
                    try:
                        self._parse_produce_response(resps[i], topic)
                    except KafkaError as pe:
                        first_err = first_err or pe
                        unacked.extend(recs)
                else:
                    unacked.extend(recs)
        if first_err is not None:
            raise KafkaProduceError(
                f"produce to {topic} partially failed "
                f"({len(unacked)} records unacked): {first_err}",
                unacked) from first_err

    def _produce_payload(self, topic: str, partition: int, records) -> bytes:
        batch = build_record_batch(records)
        # ProduceRequest v3: transactional_id, acks, timeout, topic_data
        return (_str(None)
                + struct.pack(">h", self.acks)
                + struct.pack(">i", self.timeout_ms)
                + struct.pack(">i", 1) + _str(topic)
                + struct.pack(">i", 1) + struct.pack(">i", partition)
                + _bytes(batch))

    def _parse_produce_response(self, resp: Optional[bytes],
                                topic: str) -> None:
        if resp is None:  # acks=0: fire and forget
            return
        r = _Reader(resp)
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()          # partition
                err = r.i16()
                r.i64()          # base offset
                r.i64()          # log_append_time (v2+)
                if err != 0:
                    with self._lock:
                        self._topic_meta.pop(topic, None)
                    raise KafkaError(f"produce error code {err}")
        r.i32()                  # throttle_time_ms (v1+ trailer)


# ---------------------------------------------------------------------------
# record batch v2 decoding (consumer side)
# ---------------------------------------------------------------------------


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    """Zigzag varint → (value, new_pos)."""
    shift = 0
    z = 0
    while True:
        b = data[pos]
        pos += 1
        z |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (z >> 1) ^ -(z & 1), pos


class ConsumerRecord:
    __slots__ = ("topic", "partition", "offset", "timestamp", "key", "value")

    def __init__(self, topic, partition, offset, timestamp, key, value):
        self.topic = topic
        self.partition = partition
        self.offset = offset
        self.timestamp = timestamp
        self.key = key
        self.value = value


def _snappy_body(body: bytes) -> bytes:
    """Snappy-compressed records: raw block, or xerial-framed (the Java
    client's historical framing)."""
    from .. import native as native_mod
    if body.startswith(b"\x82SNAPPY\x00"):
        out = bytearray()
        pos = 16                        # magic(8) + version(4) + compat(4)
        while pos + 4 <= len(body):
            n = struct.unpack_from(">i", body, pos)[0]
            pos += 4
            chunk = native_mod.snappy_decompress(body[pos : pos + n])
            if chunk is None:
                raise KafkaError("snappy codec unavailable (native lib)")
            out += chunk
            pos += n
        return bytes(out)
    plain = native_mod.snappy_decompress(body)
    if plain is None:
        raise KafkaError("snappy codec unavailable (native lib)")
    return plain


def decode_record_batches(data: bytes, topic: str = "", partition: int = 0
                          ) -> Tuple[List[ConsumerRecord], Optional[int]]:
    """Walk concatenated magic-v2 record batches → (records, next_offset).

    next_offset advances past every COMPLETE batch — including control
    batches (transaction markers, attributes bit 5) and batches whose
    codec this client cannot decode (warned + skipped) — so the consumer
    never refetches the same undecodable batch forever.  A truncated
    final batch (the broker may cut at max_bytes) is silently dropped.
    """
    out: List[ConsumerRecord] = []
    next_offset: Optional[int] = None
    pos = 0
    n = len(data)
    while pos + 12 <= n:
        base_offset, batch_len = struct.unpack_from(">qi", data, pos)
        end = pos + 12 + batch_len
        if batch_len <= 0 or end > n:
            break                       # truncated tail
        magic = data[pos + 16]
        if magic != 2:
            pos = end                   # legacy message set: skip
            continue
        attributes = struct.unpack_from(">h", data, pos + 21)[0]
        last_delta = struct.unpack_from(">i", data, pos + 23)[0]
        first_ts = struct.unpack_from(">q", data, pos + 27)[0]
        count = struct.unpack_from(">i", data, pos + 57)[0]
        next_offset = base_offset + last_delta + 1
        if attributes & 0x20:           # control batch: commit/abort marker
            pos = end
            continue
        body = data[pos + 61 : end]
        codec = attributes & 0x07
        if codec == 1:                  # gzip
            import gzip
            body = gzip.decompress(body)
        elif codec == 2:                # snappy
            body = _snappy_body(body)
        elif codec != 0:                # lz4-frame / zstd: skip, don't wedge
            log.warning("skipping batch at %s/%d offset %d: unsupported "
                        "compression codec %d", topic, partition,
                        base_offset, codec)
            pos = end
            continue
        p = 0
        for _ in range(count):
            if p >= len(body):
                break
            rec_len, p = _read_varint(body, p)
            rec_end = p + rec_len
            p += 1                      # attributes
            ts_delta, p = _read_varint(body, p)
            off_delta, p = _read_varint(body, p)
            klen, p = _read_varint(body, p)
            key = None
            if klen >= 0:
                key = body[p : p + klen]
                p += klen
            vlen, p = _read_varint(body, p)
            value = b""
            if vlen >= 0:
                value = body[p : p + vlen]
                p += vlen
            out.append(ConsumerRecord(topic, partition,
                                      base_offset + off_delta,
                                      first_ts + ts_delta, key, value))
            p = rec_end
        pos = end
    return out, next_offset


# ---------------------------------------------------------------------------
# consumer group protocol
# ---------------------------------------------------------------------------


def _subscription_metadata(topics: List[str]) -> bytes:
    """ConsumerProtocolSubscription v0."""
    out = struct.pack(">h", 0) + struct.pack(">i", len(topics))
    for t in topics:
        out += _str(t)
    out += struct.pack(">i", -1)        # user data
    return out


def _encode_assignment(assign: Dict[str, List[int]]) -> bytes:
    """ConsumerProtocolAssignment v0."""
    out = struct.pack(">h", 0) + struct.pack(">i", len(assign))
    for topic in sorted(assign):
        out += _str(topic) + struct.pack(">i", len(assign[topic]))
        for p in sorted(assign[topic]):
            out += struct.pack(">i", p)
    out += struct.pack(">i", -1)
    return out


def _decode_assignment(data: bytes) -> Dict[str, List[int]]:
    if not data:
        return {}
    r = _Reader(data)
    r.i16()                             # version
    out: Dict[str, List[int]] = {}
    for _ in range(r.i32()):
        topic = r.string()
        out[topic] = [r.i32() for _ in range(r.i32())]
    return out


def _decode_subscription(data: bytes) -> List[str]:
    r = _Reader(data)
    r.i16()
    return [r.string() for _ in range(r.i32())]


class KafkaConsumer(KafkaClient):
    """Consumer-group client for input_kafka (reference
    plugins/input/kafka/input_kafka.go wraps sarama's ConsumerGroup; this
    speaks the group protocol directly).

    Usage: poll() joins/rejoins the group as needed and returns a batch of
    ConsumerRecords; commit() writes the consumed positions back.  All
    calls from ONE thread (the input plugin's service thread)."""

    def __init__(self, brokers: List[str], group_id: str,
                 topics: List[str], client_id: str = "loongcollector-tpu",
                 offset_reset: str = "oldest", assignor: str = "range",
                 session_timeout_ms: int = 10000,
                 max_bytes: int = 4 << 20,
                 tls: Optional[dict] = None, sasl: Optional[dict] = None):
        super().__init__(brokers, client_id, tls=tls, sasl=sasl)
        self.group_id = group_id
        self.topics = list(topics)
        self.offset_reset = offset_reset
        self.assignor = assignor if assignor in ("range", "roundrobin") \
            else "range"
        self.session_timeout_ms = session_timeout_ms
        self.max_bytes = max_bytes
        self._coordinator: Optional[str] = None
        self._member_id = ""
        self._generation = -1
        self._assignment: Dict[str, List[int]] = {}
        self._positions: Dict[Tuple[str, int], int] = {}
        self._committed: Dict[Tuple[str, int], int] = {}
        self._last_heartbeat = 0.0
        self._joined = False

    # -- coordinator / membership -------------------------------------------

    def _find_coordinator(self) -> str:
        last_err: Optional[Exception] = None
        for addr in self.brokers:
            try:
                resp = self._request(addr, API_FIND_COORDINATOR, 1,
                                     _str(self.group_id) + b"\x00")
            except (KafkaError, OSError) as e:
                last_err = e
                continue
            r = _Reader(resp)
            r.i32()                     # throttle
            err = r.i16()
            r.string()                  # error message
            r.i32()                     # node id
            host = r.string()
            port = r.i32()
            if err == 0:
                return f"{host}:{port}"
            last_err = KafkaError(f"FindCoordinator error {err}")
        raise last_err or KafkaError("no brokers reachable")

    def _join(self) -> None:
        self._coordinator = self._find_coordinator()
        meta = _subscription_metadata(self.topics)
        protocols = (struct.pack(">i", 2)
                     + _str("range") + _bytes(_subscription_metadata(
                         self.topics))
                     + _str("roundrobin") + _bytes(meta)) \
            if self.assignor == "range" else \
            (struct.pack(">i", 2)
             + _str("roundrobin") + _bytes(meta)
             + _str("range") + _bytes(meta))
        for attempt in range(3):
            payload = (_str(self.group_id)
                       + struct.pack(">i", self.session_timeout_ms)
                       + struct.pack(">i", self.session_timeout_ms * 3)
                       + _str(self._member_id)
                       + _str("consumer")
                       + protocols)
            r = _Reader(self._request(self._coordinator, API_JOIN_GROUP, 2,
                                      payload))
            r.i32()                     # throttle
            err = r.i16()
            generation = r.i32()
            protocol = r.string()
            leader = r.string()
            member_id = r.string()
            members = []
            for _ in range(r.i32()):
                mid = r.string()
                mlen = r.i32()
                mdata = r.data[r.pos : r.pos + mlen] if mlen >= 0 else b""
                r.pos += max(mlen, 0)
                members.append((mid, mdata))
            if err == ERR_MEMBER_ID_REQUIRED:
                self._member_id = member_id
                continue
            if err != 0:
                raise KafkaError(f"JoinGroup error {err}")
            self._member_id = member_id
            self._generation = generation
            break
        else:
            raise KafkaError("JoinGroup retries exhausted")

        assignments = b""
        if member_id == leader:
            plan = self._assign(protocol or self.assignor, members)
            assignments = struct.pack(">i", len(plan))
            for mid, a in plan.items():
                assignments += _str(mid) + _bytes(_encode_assignment(a))
        else:
            assignments = struct.pack(">i", 0)
        payload = (_str(self.group_id) + struct.pack(">i", self._generation)
                   + _str(self._member_id) + assignments)
        r = _Reader(self._request(self._coordinator, API_SYNC_GROUP, 1,
                                  payload))
        r.i32()                         # throttle
        err = r.i16()
        alen = r.i32()
        adata = r.data[r.pos : r.pos + alen] if alen >= 0 else b""
        if err != 0:
            raise KafkaError(f"SyncGroup error {err}")
        self._assignment = _decode_assignment(adata)
        self._positions.clear()
        self._fetch_committed()
        self._joined = True
        self._last_heartbeat = time.monotonic()
        log.info("kafka consumer joined %s gen=%d assignment=%s",
                 self.group_id, self._generation, self._assignment)

    def _assign(self, protocol: str, members) -> Dict[str, Dict[str, List[int]]]:
        """Leader-side partition assignment (range or roundrobin)."""
        subscribed: Dict[str, List[str]] = {}
        for mid, mdata in members:
            try:
                subscribed[mid] = _decode_subscription(mdata)
            except Exception:  # noqa: BLE001 — malformed peer metadata
                subscribed[mid] = list(self.topics)
        all_topics = sorted({t for ts in subscribed.values() for t in ts})
        parts: Dict[str, List[int]] = {}
        for t in all_topics:
            self.refresh_metadata(t)
            with self._lock:
                parts[t] = [p for p, _ in self._topic_meta.get(t, [])]
        plan: Dict[str, Dict[str, List[int]]] = {
            mid: {} for mid, _ in members}
        if protocol == "roundrobin":
            i = 0
            mids = sorted(plan)
            for t in all_topics:
                for p in parts[t]:
                    takers = [m for m in mids if t in subscribed[m]]
                    if not takers:
                        continue
                    m = takers[i % len(takers)]
                    i += 1
                    plan[m].setdefault(t, []).append(p)
        else:                           # range, per topic
            for t in all_topics:
                takers = sorted(m for m in plan if t in subscribed[m])
                if not takers:
                    continue
                ps = parts[t]
                per = len(ps) // len(takers)
                extra = len(ps) % len(takers)
                idx = 0
                for k, m in enumerate(takers):
                    take = per + (1 if k < extra else 0)
                    if take:
                        plan[m].setdefault(t, []).extend(
                            ps[idx : idx + take])
                        idx += take
        return plan

    # -- offsets ------------------------------------------------------------

    def _fetch_committed(self) -> None:
        if not self._assignment:
            return
        payload = _str(self.group_id) + struct.pack(
            ">i", len(self._assignment))
        for t, ps in self._assignment.items():
            payload += _str(t) + struct.pack(">i", len(ps))
            for p in ps:
                payload += struct.pack(">i", p)
        r = _Reader(self._request(self._coordinator, API_OFFSET_FETCH, 1,
                                  payload))
        need_reset: List[Tuple[str, int]] = []
        for _ in range(r.i32()):
            t = r.string()
            for _ in range(r.i32()):
                p = r.i32()
                off = r.i64()
                r.string()              # metadata
                err = r.i16()
                if err == 0 and off >= 0:
                    self._positions[(t, p)] = off
                    self._committed[(t, p)] = off
                else:
                    need_reset.append((t, p))
        for t, p in need_reset:
            self._positions[(t, p)] = self._reset_offset(t, p)

    def _reset_offset(self, topic: str, partition: int) -> int:
        ts = -2 if self.offset_reset in ("oldest", "earliest", "") else -1
        leader = self._leader_for(topic, partition)
        payload = (struct.pack(">i", -1) + struct.pack(">i", 1)
                   + _str(topic) + struct.pack(">i", 1)
                   + struct.pack(">i", partition) + struct.pack(">q", ts))
        r = _Reader(self._request(leader, API_LIST_OFFSETS, 1, payload))
        for _ in range(r.i32()):        # (throttle_time only appears in v2+)
            r.string()
            for _ in range(r.i32()):
                r.i32()                 # partition
                err = r.i16()
                r.i64()                 # timestamp
                off = r.i64()
                if err != 0:
                    raise KafkaError(f"ListOffsets error {err}")
                return off
        raise KafkaError("empty ListOffsets response")

    def _leader_for(self, topic: str, partition: int) -> str:
        with self._lock:
            parts = dict(self._topic_meta.get(topic, []))
        if partition not in parts:
            self.refresh_metadata(topic)
            with self._lock:
                parts = dict(self._topic_meta.get(topic, []))
        leader = parts.get(partition)
        if leader is None:
            raise KafkaError(f"no leader for {topic}/{partition}")
        return leader

    def commit(self) -> None:
        """OffsetCommit v2 for every consumed position."""
        dirty = {tp: off for tp, off in self._positions.items()
                 if self._committed.get(tp) != off}
        if not dirty or not self._joined:
            return
        by_topic: Dict[str, List[Tuple[int, int]]] = {}
        for (t, p), off in dirty.items():
            by_topic.setdefault(t, []).append((p, off))
        payload = (_str(self.group_id) + struct.pack(">i", self._generation)
                   + _str(self._member_id) + struct.pack(">q", -1)
                   + struct.pack(">i", len(by_topic)))
        for t, ps in by_topic.items():
            payload += _str(t) + struct.pack(">i", len(ps))
            for p, off in ps:
                payload += struct.pack(">i", p) + struct.pack(">q", off) \
                    + _str(None)
        r = _Reader(self._request(self._coordinator, API_OFFSET_COMMIT, 2,
                                  payload))
        for _ in range(r.i32()):
            t = r.string()
            for _ in range(r.i32()):
                p = r.i32()
                err = r.i16()
                if err == 0:
                    self._committed[(t, p)] = self._positions[(t, p)]
                elif err in (ERR_ILLEGAL_GENERATION, ERR_UNKNOWN_MEMBER_ID,
                             ERR_REBALANCE_IN_PROGRESS):
                    self._joined = False
                else:
                    log.warning("OffsetCommit %s/%d error %d", t, p, err)

    # -- heartbeat / fetch ---------------------------------------------------

    def _maybe_heartbeat(self) -> None:
        if time.monotonic() - self._last_heartbeat \
                < self.session_timeout_ms / 3000.0:
            return
        payload = (_str(self.group_id) + struct.pack(">i", self._generation)
                   + _str(self._member_id))
        r = _Reader(self._request(self._coordinator, API_HEARTBEAT, 1,
                                  payload))
        r.i32()
        err = r.i16()
        self._last_heartbeat = time.monotonic()
        if err in (ERR_REBALANCE_IN_PROGRESS, ERR_ILLEGAL_GENERATION,
                   ERR_UNKNOWN_MEMBER_ID, ERR_NOT_COORDINATOR,
                   ERR_COORDINATOR_NOT_AVAILABLE):
            log.info("heartbeat error %d: rejoining group", err)
            self._joined = False
        elif err != 0:
            raise KafkaError(f"Heartbeat error {err}")

    def poll(self, max_wait_ms: int = 500) -> List[ConsumerRecord]:
        """Join if needed, heartbeat, then one Fetch round across leaders."""
        if not self._joined:
            self._join()
        self._maybe_heartbeat()
        by_leader: Dict[str, Dict[str, List[int]]] = {}
        for t, ps in self._assignment.items():
            for p in ps:
                if (t, p) not in self._positions:
                    self._positions[(t, p)] = self._reset_offset(t, p)
                by_leader.setdefault(self._leader_for(t, p),
                                     {}).setdefault(t, []).append(p)
        records: List[ConsumerRecord] = []
        for leader, topics in by_leader.items():
            payload = (struct.pack(">i", -1)
                       + struct.pack(">i", max_wait_ms)
                       + struct.pack(">i", 1)
                       + struct.pack(">i", self.max_bytes)
                       + b"\x00"
                       + struct.pack(">i", len(topics)))
            for t, ps in topics.items():
                payload += _str(t) + struct.pack(">i", len(ps))
                for p in ps:
                    payload += (struct.pack(">i", p)
                                + struct.pack(">q", self._positions[(t, p)])
                                + struct.pack(">i", self.max_bytes))
            r = _Reader(self._request(leader, API_FETCH, 4, payload))
            r.i32()                     # throttle
            for _ in range(r.i32()):
                t = r.string()
                for _ in range(r.i32()):
                    p = r.i32()
                    err = r.i16()
                    r.i64()             # high watermark
                    r.i64()             # last stable offset
                    for _ in range(r.i32()):
                        r.i64()         # aborted txn producer id
                        r.i64()         # aborted txn first offset
                    rlen = r.i32()
                    rdata = r.data[r.pos : r.pos + rlen] if rlen > 0 else b""
                    r.pos += max(rlen, 0)
                    if err == ERR_OFFSET_OUT_OF_RANGE:
                        self._positions[(t, p)] = self._reset_offset(t, p)
                        continue
                    if err == ERR_NOT_LEADER:
                        with self._lock:
                            self._topic_meta.pop(t, None)
                        continue
                    if err != 0:
                        log.warning("fetch %s/%d error %d", t, p, err)
                        continue
                    recs, next_off = decode_record_batches(rdata, t, p)
                    for rec in recs:
                        if rec.offset >= self._positions[(t, p)]:
                            records.append(rec)
                    advance = self._positions[(t, p)]
                    if recs:
                        advance = max(advance, recs[-1].offset + 1)
                    if next_off is not None:
                        advance = max(advance, next_off)
                    self._positions[(t, p)] = advance
        return records

    def close(self, commit: bool = True) -> None:
        """commit=False when the caller could not deliver the last polled
        batch downstream — committing would drop it (at-least-once)."""
        if self._joined and self._coordinator:
            try:
                if commit:
                    self.commit()
                payload = _str(self.group_id) + _str(self._member_id)
                self._request(self._coordinator, API_LEAVE_GROUP, 1, payload)
            except (KafkaError, OSError):
                pass
        super().close()
