"""Minimal Kafka wire-protocol producer (no external client library).

Reference: core/plugin/flusher/kafka/KafkaProducer.cpp uses librdkafka; this
image has no Kafka client, so the producer speaks the public wire protocol
directly: Metadata (v1) for leader discovery and Produce (v3) with record
batches (magic v2, varint-framed records, CRC32C over the batch body).

Scope: plaintext brokers, acks=all/1, gzip-free (compression handled at the
payload level by the pipeline when desired), single in-flight request per
connection.  CRC32C comes from the native library when present, else a
Python table fallback.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.logger import get_logger

log = get_logger("kafka")

API_PRODUCE = 0
API_METADATA = 3
API_SASL_HANDSHAKE = 17
API_SASL_AUTHENTICATE = 36


# ---------------------------------------------------------------------------
# CRC32C
# ---------------------------------------------------------------------------

_crc_table: Optional[List[int]] = None


def _crc32c_py(data: bytes, seed: int = 0) -> int:
    global _crc_table
    if _crc_table is None:
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
            table.append(crc)
        _crc_table = table
    crc = seed ^ 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _crc_table[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def crc32c(data: bytes) -> int:
    try:
        import ctypes

        import numpy as np

        from ..native import get_lib
        lib = get_lib()
        if lib is not None:
            if not hasattr(lib, "_crc_configured"):
                lib.lct_crc32c.restype = ctypes.c_uint32
                lib.lct_crc32c.argtypes = [ctypes.POINTER(ctypes.c_uint8),
                                           ctypes.c_int64, ctypes.c_uint32]
                lib._crc_configured = True
            arr = np.frombuffer(data, dtype=np.uint8)
            return int(lib.lct_crc32c(
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                len(arr), 0))
    except Exception:  # noqa: BLE001
        pass
    return _crc32c_py(data)


# ---------------------------------------------------------------------------
# wire helpers
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    """Kafka zigzag varint."""
    z = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    data = s.encode()
    return struct.pack(">h", len(data)) + data


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def i16(self) -> int:
        v = struct.unpack_from(">h", self.data, self.pos)[0]
        self.pos += 2
        return v

    def i32(self) -> int:
        v = struct.unpack_from(">i", self.data, self.pos)[0]
        self.pos += 4
        return v

    def i64(self) -> int:
        v = struct.unpack_from(">q", self.data, self.pos)[0]
        self.pos += 8
        return v

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        v = self.data[self.pos : self.pos + n].decode()
        self.pos += n
        return v

    def array(self, fn):
        return [fn() for _ in range(self.i32())]


# ---------------------------------------------------------------------------
# record batch v2
# ---------------------------------------------------------------------------


def build_record_batch(records: List[Tuple[Optional[bytes], bytes]],
                       base_ts_ms: Optional[int] = None) -> bytes:
    """records: [(key, value)] → one magic-v2 record batch."""
    now = base_ts_ms if base_ts_ms is not None else int(time.time() * 1000)
    body = bytearray()
    for i, (key, value) in enumerate(records):
        rec = bytearray()
        rec += b"\x00"                      # attributes
        rec += _varint(0)                   # timestamp delta
        rec += _varint(i)                   # offset delta
        if key is None:
            rec += _varint(-1)
        else:
            rec += _varint(len(key)) + key
        rec += _varint(len(value)) + value
        rec += _varint(0)                   # headers count
        body += _varint(len(rec)) + rec

    n = len(records)
    # batch body after the CRC field
    after_crc = bytearray()
    after_crc += struct.pack(">h", 0)       # attributes (no compression)
    after_crc += struct.pack(">i", n - 1)   # last offset delta
    after_crc += struct.pack(">q", now)     # first timestamp
    after_crc += struct.pack(">q", now)     # max timestamp
    after_crc += struct.pack(">q", -1)      # producer id
    after_crc += struct.pack(">h", -1)      # producer epoch
    after_crc += struct.pack(">i", -1)      # base sequence
    after_crc += struct.pack(">i", n)       # record count
    after_crc += body

    crc = crc32c(bytes(after_crc))
    batch = bytearray()
    batch += struct.pack(">q", 0)           # base offset
    batch_len = 4 + 1 + 4 + len(after_crc)  # partition leader epoch..end
    batch += struct.pack(">i", batch_len)
    batch += struct.pack(">i", -1)          # partition leader epoch
    batch += struct.pack(">b", 2)           # magic
    batch += struct.pack(">I", crc)
    batch += after_crc
    return bytes(batch)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class KafkaError(Exception):
    pass


def _scram_escape(name: str) -> str:
    """RFC 5802 saslname escaping: ',' and '=' are reserved."""
    return name.replace("=", "=3D").replace(",", "=2C")


class KafkaProducer:
    def __init__(self, brokers: List[str], client_id: str = "loongcollector-tpu",
                 acks: int = -1, timeout_ms: int = 10000,
                 tls: Optional[dict] = None, sasl: Optional[dict] = None):
        """tls: {CAFile, CertFile, KeyFile, InsecureSkipVerify} — enables
        TLS when present (reference KafkaProducer.cpp:41 ssl.* settings).
        sasl: {Mechanism: PLAIN|SCRAM-SHA-256|SCRAM-SHA-512, Username,
        Password} (reference :111 sasl.* settings; Kerberos/GSSAPI is out
        of scope — no KDC in this runtime)."""
        self.brokers = brokers
        self.client_id = client_id
        self.acks = acks
        self.timeout_ms = timeout_ms
        self.tls = tls
        self.sasl = sasl
        self._corr = 0
        self._conns: Dict[str, socket.socket] = {}
        # topic -> [(partition, leader "host:port")]
        self._topic_meta: Dict[str, List[Tuple[int, str]]] = {}
        self._rr: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- transport ----------------------------------------------------------

    def _wrap_tls(self, sock: socket.socket, host: str) -> socket.socket:
        import ssl
        cfg = self.tls or {}
        if cfg.get("InsecureSkipVerify"):
            ctx = ssl._create_unverified_context()
        else:
            ctx = ssl.create_default_context(cafile=cfg.get("CAFile"))
        cert, key = cfg.get("CertFile"), cfg.get("KeyFile")
        if cert:
            ctx.load_cert_chain(cert, key)
        return ctx.wrap_socket(sock, server_hostname=host)

    def _connect(self, addr: str) -> socket.socket:
        sock = self._conns.get(addr)
        if sock is not None:
            return sock
        host, _, port = addr.rpartition(":")
        sock = socket.create_connection((host, int(port or 9092)), timeout=10)
        try:
            if self.tls is not None:
                sock = self._wrap_tls(sock, host)
            if self.sasl is not None:
                self._sasl_handshake(sock)
        except (OSError, KafkaError):
            try:
                sock.close()
            except OSError:
                pass
            raise
        self._conns[addr] = sock
        return sock

    # -- SASL ---------------------------------------------------------------

    def _raw_request(self, sock: socket.socket, api: int, version: int,
                     payload: bytes) -> bytes:
        """One request/response on an ALREADY-OPEN socket (the handshake
        must not recurse into _connect)."""
        self._corr += 1
        corr = self._corr
        header = (struct.pack(">hhi", api, version, corr)
                  + _str(self.client_id))
        msg = header + payload
        sock.sendall(struct.pack(">i", len(msg)) + msg)
        raw = self._read_exact(sock, 4)
        size = struct.unpack(">i", raw)[0]
        resp = self._read_exact(sock, size)
        got = struct.unpack(">i", resp[:4])[0]
        if got != corr:
            raise KafkaError(f"correlation mismatch {got} != {corr}")
        return resp[4:]

    def _sasl_authenticate(self, sock: socket.socket,
                           auth_bytes: bytes) -> bytes:
        resp = _Reader(self._raw_request(
            sock, API_SASL_AUTHENTICATE, 0, _bytes(auth_bytes)))
        err = resp.i16()
        err_msg = resp.string()
        n = resp.i32()
        out = resp.data[resp.pos:resp.pos + n] if n >= 0 else b""
        if err != 0:
            raise KafkaError(f"SASL authenticate failed ({err}): {err_msg}")
        return out

    def _sasl_handshake(self, sock: socket.socket) -> None:
        mech = (self.sasl.get("Mechanism") or "PLAIN").upper()
        user = self.sasl.get("Username") or ""
        password = self.sasl.get("Password") or ""
        resp = _Reader(self._raw_request(
            sock, API_SASL_HANDSHAKE, 1, _str(mech)))
        err = resp.i16()
        if err != 0:
            mechs = resp.array(resp.string)
            raise KafkaError(
                f"SASL mechanism {mech} rejected ({err}); broker offers "
                f"{mechs}")
        if mech == "PLAIN":
            self._sasl_authenticate(
                sock, b"\0" + user.encode() + b"\0" + password.encode())
        elif mech in ("SCRAM-SHA-256", "SCRAM-SHA-512"):
            self._sasl_scram(sock, mech, user, password)
        else:
            raise KafkaError(f"unsupported SASL mechanism {mech}")

    def _sasl_scram(self, sock: socket.socket, mech: str, user: str,
                    password: str) -> None:
        """RFC 5802 SCRAM over KIP-84 SaslAuthenticate rounds."""
        algo = "sha256" if mech.endswith("256") else "sha512"
        H = getattr(hashlib, algo)
        nonce = base64.b64encode(os.urandom(18)).decode()
        gs2 = "n,,"
        cf_bare = f"n={_scram_escape(user)},r={nonce}"
        server_first = self._sasl_authenticate(
            sock, (gs2 + cf_bare).encode()).decode()
        parts = dict(p.split("=", 1) for p in server_first.split(","))
        r, s, i = parts["r"], parts["s"], int(parts["i"])
        if not r.startswith(nonce):
            raise KafkaError("SCRAM server nonce does not extend ours")
        salted = hashlib.pbkdf2_hmac(algo, password.encode(),
                                     base64.b64decode(s), i)
        client_key = hmac.new(salted, b"Client Key", H).digest()
        stored_key = H(client_key).digest()
        cf_woproof = f"c={base64.b64encode(gs2.encode()).decode()},r={r}"
        auth_msg = f"{cf_bare},{server_first},{cf_woproof}".encode()
        client_sig = hmac.new(stored_key, auth_msg, H).digest()
        proof = bytes(a ^ b for a, b in zip(client_key, client_sig))
        final = f"{cf_woproof},p={base64.b64encode(proof).decode()}"
        server_final = self._sasl_authenticate(sock, final.encode()).decode()
        fparts = dict(p.split("=", 1) for p in server_final.split(","))
        if "e" in fparts:
            raise KafkaError(f"SCRAM server error: {fparts['e']}")
        server_key = hmac.new(salted, b"Server Key", H).digest()
        expect = hmac.new(server_key, auth_msg, H).digest()
        if base64.b64decode(fparts.get("v", "")) != expect:
            raise KafkaError("SCRAM server signature verification failed")

    def _drop(self, addr: str) -> None:
        sock = self._conns.pop(addr, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _request(self, addr: str, api_key: int, api_version: int,
                 payload: bytes, expect_response: bool = True
                 ) -> Optional[bytes]:
        # connect FIRST: the TLS/SASL handshake inside _connect consumes
        # correlation ids of its own, so ours is allocated after it
        sock = self._connect(addr)
        self._corr += 1
        my_corr = self._corr
        header = (struct.pack(">hhi", api_key, api_version, my_corr)
                  + _str(self.client_id))
        msg = header + payload
        try:
            sock.sendall(struct.pack(">i", len(msg)) + msg)
            if not expect_response:
                return None
            raw = self._read_exact(sock, 4)
            size = struct.unpack(">i", raw)[0]
            resp = self._read_exact(sock, size)
        except OSError as e:
            self._drop(addr)
            raise KafkaError(f"broker {addr}: {e}") from e
        corr = struct.unpack(">i", resp[:4])[0]
        if corr != my_corr:
            self._drop(addr)
            raise KafkaError("correlation id mismatch")
        return resp[4:]

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise OSError("connection closed")
            buf += chunk
        return bytes(buf)

    # -- metadata -----------------------------------------------------------

    def refresh_metadata(self, topic: str) -> None:
        payload = struct.pack(">i", 1) + _str(topic)
        last_err = None
        for addr in self.brokers:
            try:
                resp = self._request(addr, API_METADATA, 1, payload)
            except (KafkaError, OSError) as e:
                # OSError covers connect refusals and TLS handshake
                # failures (ssl.SSLError ⊂ OSError) — one bad broker must
                # not defeat multi-broker failover
                last_err = e if isinstance(e, KafkaError) else \
                    KafkaError(f"broker {addr}: {e}")
                continue
            r = _Reader(resp)
            brokers = {}
            for _ in range(r.i32()):
                node = r.i32()
                host = r.string()
                port = r.i32()
                r.string()  # rack
                brokers[node] = f"{host}:{port}"
            r.i32()  # controller id (v1 layout: brokers, controller, topics)
            parts: List[Tuple[int, str]] = []
            for _ in range(r.i32()):
                r.i16()          # topic error
                r.string()       # topic name
                r.data[r.pos]    # is_internal (bool)
                r.pos += 1
                for _ in range(r.i32()):
                    r.i16()      # partition error
                    pid = r.i32()
                    leader = r.i32()
                    r.array(r.i32)   # replicas
                    r.array(r.i32)   # isr
                    if leader in brokers:
                        parts.append((pid, brokers[leader]))
            if parts:
                with self._lock:
                    self._topic_meta[topic] = sorted(parts)
                return
        raise last_err or KafkaError("no brokers reachable")

    # -- produce ------------------------------------------------------------

    def _pick_partition(self, topic: str, key: Optional[bytes],
                        nparts: int) -> int:
        """Keyed records hash to a stable partition (per-key ordering);
        unkeyed records round-robin."""
        if key:
            import hashlib
            return int.from_bytes(
                hashlib.md5(key).digest()[:4], "big") % nparts
        idx = self._rr.get(topic, 0)
        self._rr[topic] = idx + 1
        return idx % nparts

    def send(self, topic: str,
             records: List[Tuple[Optional[bytes], bytes]]) -> None:
        with self._lock:
            parts = self._topic_meta.get(topic)
        if not parts:
            self.refresh_metadata(topic)
            with self._lock:
                parts = self._topic_meta.get(topic, [])
        if not parts:
            raise KafkaError(f"no partitions for topic {topic}")
        leaders = dict(parts)
        nparts = len(parts)
        by_partition: Dict[int, List[Tuple[Optional[bytes], bytes]]] = {}
        for key, value in records:
            pid = self._pick_partition(topic, key, nparts)
            by_partition.setdefault(pid, []).append((key, value))
        for partition, recs in by_partition.items():
            leader = leaders.get(partition)
            if leader is None:
                raise KafkaError(f"no leader for {topic}/{partition}")
            self._send_one(topic, partition, leader, recs)

    def _send_one(self, topic: str, partition: int, leader: str,
                  records) -> None:
        batch = build_record_batch(records)
        # ProduceRequest v3: transactional_id, acks, timeout, topic_data
        payload = (_str(None)
                   + struct.pack(">h", self.acks)
                   + struct.pack(">i", self.timeout_ms)
                   + struct.pack(">i", 1) + _str(topic)
                   + struct.pack(">i", 1) + struct.pack(">i", partition)
                   + _bytes(batch))
        try:
            resp = self._request(leader, API_PRODUCE, 3, payload,
                                 expect_response=(self.acks != 0))
        except KafkaError:
            with self._lock:
                self._topic_meta.pop(topic, None)  # stale leader: refetch
            raise
        if resp is None:  # acks=0: fire and forget
            return
        r = _Reader(resp)
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()          # partition
                err = r.i16()
                r.i64()          # base offset
                r.i64()          # log_append_time (v2+)
                if err != 0:
                    with self._lock:
                        self._topic_meta.pop(topic, None)
                    raise KafkaError(f"produce error code {err}")
        r.i32()                  # throttle_time_ms (v1+ trailer)

    def close(self) -> None:
        for addr in list(self._conns):
            self._drop(addr)
