"""flusher_clickhouse — HTTP-interface INSERT sink.

Reference: plugins/flusher/clickhouse/flusher_clickhouse.go — the Go
flusher drives clickhouse-go; the HTTP interface (`POST /?query=INSERT INTO
db.table FORMAT JSONEachRow`) carries identical rows without a client
library, which is the idiomatic shape for this framework's sender path.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import quote

from ..models import PipelineEventGroup
from ..pipeline.serializer.event_dicts import iter_event_dicts
from .http_base import AddressRotator, HttpSinkFlusher, basic_auth_header


class FlusherClickHouse(HttpSinkFlusher):
    name = "flusher_clickhouse"
    content_type = "application/x-ndjson"

    def _init_sink(self, config: Dict[str, Any]) -> bool:
        self.rotator = AddressRotator(config.get("Addresses", []))
        self.table = config.get("Table", "")
        self.database = config.get("Database", "default")
        self.auth = basic_auth_header(config)
        return bool(self.rotator) and bool(self.table)

    def build_payload(self, groups: List[PipelineEventGroup]
                      ) -> Optional[Tuple[bytes, Dict[str, str]]]:
        rows: List[bytes] = []
        for g in groups:
            for ts, obj in iter_event_dicts(g):
                obj.setdefault("_timestamp", ts)
                rows.append(json.dumps(obj, ensure_ascii=False).encode())
        if not rows:
            return None
        return b"\n".join(rows) + b"\n", self.auth

    def endpoint_url(self, item) -> str:
        q = quote(f"INSERT INTO {self.database}.{self.table} "
                  f"FORMAT JSONEachRow")
        return f"{self.rotator.next()}/?query={q}"
