"""flusher_clickhouse — HTTP-interface INSERT sink.

Reference: plugins/flusher/clickhouse/flusher_clickhouse.go — the Go
flusher drives clickhouse-go; the HTTP interface (`POST /?query=INSERT INTO
db.table FORMAT JSONEachRow`) carries identical rows without a client
library, which is the idiomatic shape for this framework's sender path.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import quote

from ..models import PipelineEventGroup
from ..pipeline.serializer.batch_json import ndjson_payload
from .http_base import AddressRotator, HttpSinkFlusher, basic_auth_header


class FlusherClickHouse(HttpSinkFlusher):
    name = "flusher_clickhouse"
    supports_columnar = True
    content_type = "application/x-ndjson"

    def _init_sink(self, config: Dict[str, Any]) -> bool:
        self.rotator = AddressRotator(config.get("Addresses", []))
        self.table = config.get("Table", "")
        self.database = config.get("Database", "default")
        self.auth = basic_auth_header(config)
        return bool(self.rotator) and bool(self.table)

    def build_payload(self, groups: List[PipelineEventGroup]
                      ) -> Optional[Tuple[bytes, Dict[str, str]]]:
        # shared batched serializer (loongshard): columnar groups assemble
        # JSONEachRow bytes natively, identical to the old per-row
        # json.dumps loop (tests/test_batch_json.py goldens)
        body = ndjson_payload(groups, ts_key="_timestamp")
        if body is None:
            return None
        return body, self.auth

    def endpoint_url(self, item) -> str:
        q = quote(f"INSERT INTO {self.database}.{self.table} "
                  f"FORMAT JSONEachRow")
        return f"{self.rotator.next()}/?query={q}"
