"""flusher_prometheus — Prometheus remote-write 1.0 sink.

Reference: plugins/flusher/prometheus/ (Go remote-write client). Wire
format (public spec): snappy-block-compressed protobuf WriteRequest —

    WriteRequest { repeated TimeSeries timeseries = 1; }
    TimeSeries   { repeated Label labels = 1; repeated Sample samples = 2; }
    Label        { string name = 1; string value = 2; }
    Sample       { double value = 1; int64 timestamp = 2; }  // ms

The protobuf writer is hand-rolled (same approach as the SLS serializer —
no intermediate PB objects); snappy rides the native lib's block codec.
MetricEvents map 1:1; LOG-kind events are skipped (remote write carries
samples only).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

from ..models import MetricEvent, PipelineEventGroup
from .http_base import HttpSinkFlusher, basic_auth_header


def _varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def _ld(field: int, payload: bytes) -> bytes:
    """Length-delimited field."""
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _label(name: bytes, value: bytes) -> bytes:
    return _ld(1, _ld(1, name) + _ld(2, value))


def _sample(value: float, ts_ms: int) -> bytes:
    body = bytes([0x09]) + struct.pack("<d", value)          # field 1 fixed64
    body += _varint((2 << 3) | 0) + _varint(ts_ms & (2**64 - 1))
    return _ld(2, body)


def encode_write_request(series: List[Tuple[List[Tuple[bytes, bytes]],
                                            float, int]]) -> bytes:
    """series: [(labels, value, ts_ms)]; labels must include __name__."""
    out = bytearray()
    for labels, value, ts_ms in series:
        ts_body = bytearray()
        # spec: labels sorted by name, __name__ first naturally ('_' < alpha)
        for name, val in sorted(labels):
            ts_body += _label(name, val)
        ts_body += _sample(value, ts_ms)
        out += _ld(1, bytes(ts_body))
    return bytes(out)


class FlusherPrometheus(HttpSinkFlusher):
    name = "flusher_prometheus"
    content_type = "application/x-protobuf"

    def _init_sink(self, config: Dict[str, Any]) -> bool:
        self.endpoint = config.get("Endpoint", "")
        self.auth = basic_auth_header(config)
        from ..pipeline.compression import SnappyCompressor
        try:
            self._snappy = SnappyCompressor()
            self._snappy.compress(b"probe")
        except RuntimeError:
            return False        # remote write REQUIRES snappy
        return bool(self.endpoint)

    def init(self, config, context) -> bool:
        ok = super().init(config, context)
        if ok:
            # the base compressor must NOT double-compress: snappy is applied
            # here (it is part of the protocol, not a negotiated encoding)
            from ..pipeline.compression import Compressor
            self.compressor = Compressor()
        return ok

    def build_payload(self, groups: List[PipelineEventGroup]
                      ) -> Optional[Tuple[bytes, Dict[str, str]]]:
        series = []
        for g in groups:
            for ev in g.events:
                if not isinstance(ev, MetricEvent):
                    continue
                name = bytes(ev.name) if ev.name else b""
                base = [(b"__name__", name)]
                base += [(bytes(k), bytes(str(v).encode()
                                          if not isinstance(v, bytes) else v))
                         for k, v in ev.tags.items()]
                ts_ms = ev.timestamp * 1000
                if ev.value.is_multi():
                    for sub, val in ev.value.values.items():
                        labels = [(b"__name__", name + b"_" + sub)] + base[1:]
                        series.append((labels, float(val), ts_ms))
                else:
                    series.append((base, float(ev.value.value), ts_ms))
        if not series:
            return None
        body = self._snappy.compress(encode_write_request(series))
        headers = dict(self.auth)
        headers["Content-Encoding"] = "snappy"
        headers["X-Prometheus-Remote-Write-Version"] = "0.1.0"
        return body, headers

    def endpoint_url(self, item) -> str:
        return self.endpoint
