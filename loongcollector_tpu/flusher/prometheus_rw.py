"""flusher_prometheus — Prometheus remote-write 1.0 sink.

Reference: plugins/flusher/prometheus/ (Go remote-write client). Wire
format (public spec): snappy-block-compressed protobuf WriteRequest —

    WriteRequest { repeated TimeSeries timeseries = 1; }
    TimeSeries   { repeated Label labels = 1; repeated Sample samples = 2; }
    Label        { string name = 1; string value = 2; }
    Sample       { double value = 1; int64 timestamp = 2; }  // ms

The protobuf writer is hand-rolled (same approach as the SLS serializer —
no intermediate PB objects); snappy rides the native lib's block codec.
MetricEvents map 1:1; LOG-kind events are skipped (remote write carries
samples only).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

from ..models import MetricEvent, PipelineEventGroup
from .http_base import HttpSinkFlusher, basic_auth_header


def _varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def _ld(field: int, payload: bytes) -> bytes:
    """Length-delimited field."""
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _label(name: bytes, value: bytes) -> bytes:
    return _ld(1, _ld(1, name) + _ld(2, value))


def _sample(value: float, ts_ms: int) -> bytes:
    body = bytes([0x09]) + struct.pack("<d", value)          # field 1 fixed64
    body += _varint((2 << 3) | 0) + _varint(ts_ms & (2**64 - 1))
    return _ld(2, body)


def encode_write_request(series: List[Tuple[List[Tuple[bytes, bytes]],
                                            float, int]]) -> bytes:
    """series: [(labels, value, ts_ms)]; labels must include __name__."""
    out = bytearray()
    for labels, value, ts_ms in series:
        ts_body = bytearray()
        # spec: labels sorted by name, __name__ first naturally ('_' < alpha)
        for name, val in sorted(labels):
            ts_body += _label(name, val)
        ts_body += _sample(value, ts_ms)
        out += _ld(1, bytes(ts_body))
    return bytes(out)


#: rollup columns that are NOT label columns in the remote-write mapping
_AGG_VALUE_FIELDS = {"sum", "count", "min", "max", "last"}
_AGG_META_FIELDS = _AGG_VALUE_FIELDS | {"hist", "window_start",
                                        "window_end"}


class FlusherPrometheus(HttpSinkFlusher):
    name = "flusher_prometheus"
    content_type = "application/x-protobuf"
    #: loongagg: rollup groups arrive as span columns and serialize
    #: straight into the WriteRequest — no per-event materialization
    supports_columnar = True

    def _init_sink(self, config: Dict[str, Any]) -> bool:
        self.endpoint = config.get("Endpoint", "")
        self.auth = basic_auth_header(config)
        from ..pipeline.compression import SnappyCompressor
        try:
            self._snappy = SnappyCompressor()
            self._snappy.compress(b"probe")
        except RuntimeError:
            return False        # remote write REQUIRES snappy
        return bool(self.endpoint)

    def init(self, config, context) -> bool:
        ok = super().init(config, context)
        if ok:
            # the base compressor must NOT double-compress: snappy is applied
            # here (it is part of the protocol, not a negotiated encoding)
            from ..pipeline.compression import Compressor
            self.compressor = Compressor()
        return ok

    def _columnar_series(self, g: PipelineEventGroup, series: list) -> bool:
        """loongagg rollup groups: one sample per aggregate column per
        row, named ``<metric>_sum`` / ``_count`` / ``_min`` / ``_max`` /
        ``_last`` (the remote-write shape of a windowed rollup), labels
        read as spans from the columnar arena.  Returns False when the
        group is not a rollup (the caller falls back to the per-event
        route).  Gated on the ``__rollup__`` tag the aggregator stamps —
        shape-sniffing field names would misserialize ordinary columnar
        log groups whose parsed fields happen to be called "count"."""
        if g.get_tag(b"__rollup__") is None:
            return False
        cols = g.columns
        if cols is None or g._events:
            return self._rollup_series_from_events(g, series)
        fields = cols.fields
        name_pair = None
        label_cols = []
        agg_cols = []
        for fname, pair in fields.items():
            key = fname if isinstance(fname, str) else fname.decode(
                "utf-8", "replace")
            if key == "__name__":
                name_pair = pair
            elif key in _AGG_VALUE_FIELDS:
                agg_cols.append((("_" + key).encode(), pair))
            elif key not in _AGG_META_FIELDS:
                label_cols.append((key.encode(), pair))
        if name_pair is None or not agg_cols:
            return False
        raw = g.source_buffer.raw

        def span(pair, r):
            off, ln = int(pair[0][r]), int(pair[1][r])
            if ln < 0:
                return None
            return bytes(raw[off:off + ln])

        ts = cols.timestamps
        for r in range(len(cols)):
            name = span(name_pair, r)
            if name is None:
                continue
            base = []
            for lk, pair in label_cols:
                lv = span(pair, r)
                if lv is not None:
                    base.append((lk, lv))
            ts_ms = int(ts[r]) * 1000
            for suffix, pair in agg_cols:
                sv = span(pair, r)
                if sv is None:
                    continue
                try:
                    value = float(sv)
                except ValueError:
                    continue
                series.append(([(b"__name__", name + suffix)] + base,
                               value, ts_ms))
        return True

    def _rollup_series_from_events(self, g: PipelineEventGroup,
                                   series: list) -> bool:
        """Dict-mode route for the same rollup groups: the sink boundary
        materialized the rows into LogEvents (``LOONG_COLUMNAR=0``), so
        the per-event MetricEvent route would silently discard them —
        read the rollup contents off the LogEvents instead."""
        from ..models import LogEvent
        for ev in g.events:
            if not isinstance(ev, LogEvent):
                continue
            name = ev.get_content(b"__name__")
            if name is None:
                continue
            name = bytes(name)
            base = []
            agg_vals = []
            for k, v in ev.contents:
                kb = bytes(k)
                key = kb.decode("utf-8", "replace")
                if kb == b"__name__":
                    continue
                if key in _AGG_VALUE_FIELDS:
                    try:
                        agg_vals.append((b"_" + kb, float(bytes(v))))
                    except ValueError:
                        continue
                elif key not in _AGG_META_FIELDS:
                    base.append((kb, bytes(v)))
            ts_ms = int(ev.timestamp) * 1000
            for suffix, value in agg_vals:
                series.append(([(b"__name__", name + suffix)] + base,
                               value, ts_ms))
        return True

    def build_payload(self, groups: List[PipelineEventGroup]
                      ) -> Optional[Tuple[bytes, Dict[str, str]]]:
        series = []
        for g in groups:
            if self._columnar_series(g, series):
                continue
            for ev in g.events:
                if not isinstance(ev, MetricEvent):
                    continue
                name = bytes(ev.name) if ev.name else b""
                base = [(b"__name__", name)]
                base += [(bytes(k), bytes(str(v).encode()
                                          if not isinstance(v, bytes) else v))
                         for k, v in ev.tags.items()]
                ts_ms = ev.timestamp * 1000
                if ev.value.is_multi():
                    for sub, val in ev.value.values.items():
                        labels = [(b"__name__", name + b"_" + sub)] + base[1:]
                        series.append((labels, float(val), ts_ms))
                else:
                    series.append((base, float(ev.value.value), ts_ms))
        if not series:
            return None
        body = self._snappy.compress(encode_write_request(series))
        headers = dict(self.auth)
        headers["Content-Encoding"] = "snappy"
        headers["X-Prometheus-Remote-Write-Version"] = "0.1.0"
        return body, headers

    def endpoint_url(self, item) -> str:
        return self.endpoint
