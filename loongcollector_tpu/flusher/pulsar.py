"""flusher_pulsar — Apache Pulsar producer over the binary wire protocol.

Reference: plugins/flusher/pulsar/ wraps the Pulsar Go client; this
implementation speaks the public binary protocol (PulsarApi.proto framing)
directly, the same from-scratch approach as flusher/kafka_client.py:

  simple frame:   [totalSize u32][commandSize u32][BaseCommand pb]
  payload frame:  ... command ... [0x0e01][crc32c u32][metaSize u32]
                  [MessageMetadata pb][payload]
  crc32c covers metaSize+metadata+payload (Castagnoli, same table as the
  Kafka client).

Session: CONNECT → CONNECTED, PRODUCER → PRODUCER_SUCCESS, then SEND →
SEND_RECEIPT per batch; PING answered with PONG.  The flusher connects to
the broker given in `BrokerURL` (pulsar://host:6650) — topic lookup is the
broker's job in multi-broker clusters and can be fronted by a proxy.

Only the fields this producer needs are encoded; unknown response fields
are skipped (proto3-style tolerance, agent_v2_pb.iter_fields).
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import urlparse

from .. import chaos
from ..config.agent_v2_pb import (dec_varint, e_bytes, e_varint, enc_varint,
                                  iter_fields)
from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext
from ..pipeline.queue.sender_queue import SenderQueueItem
from ..utils.logger import get_logger
from .async_sink import AsyncSinkFlusher
from .kafka_client import crc32c

log = get_logger("pulsar")

FP_SEND = chaos.register_point("pulsar.send")

# BaseCommand.Type (PulsarApi.proto)
CONNECT = 2
CONNECTED = 3
PRODUCER = 5
SEND = 6
SEND_RECEIPT = 7
SEND_ERROR = 8
SUCCESS = 13
ERROR = 14
CLOSE_PRODUCER = 15
PRODUCER_SUCCESS = 17
PING = 18
PONG = 19

_MAGIC = b"\x0e\x01"


def _cmd(cmd_type: int, field_no: int = 0, body: bytes = b"") -> bytes:
    """BaseCommand{type=cmd_type, <field_no>=body} serialized."""
    out = e_varint(1, cmd_type) if cmd_type else b""
    # BaseCommand.type is field 1 (enum); the command payload is a nested
    # message whose field number equals its position in BaseCommand
    if field_no:
        out += e_bytes(field_no, body)
    return out


def _frame_simple(command: bytes) -> bytes:
    return struct.pack(">II", 4 + len(command), len(command)) + command


def _frame_payload(command: bytes, metadata: bytes, payload: bytes) -> bytes:
    meta_part = struct.pack(">I", len(metadata)) + metadata + payload
    crc = crc32c(meta_part)
    rest = (struct.pack(">I", len(command)) + command
            + _MAGIC + struct.pack(">I", crc) + meta_part)
    return struct.pack(">I", len(rest)) + rest


class PulsarError(RuntimeError):
    pass


class PulsarProducer:
    """One connection + one producer session on a broker.

    Threading contract: the blocking send path (`send`) is owned by ONE
    caller — FlusherPulsar's dedicated sender thread (async_sink.py), which
    is also joined before close().  Socket I/O therefore runs lock-free
    (the PR-1 loonglint debt: connect/reconnect under self._lock blocked
    sibling senders behind broker latency); only sequence-id allocation
    keeps a lock, held for an increment and nothing else."""

    def __init__(self, broker_url: str, topic: str,
                 timeout: float = 10.0):
        u = urlparse(broker_url)
        self.host = u.hostname or "localhost"
        self.port = u.port or 6650
        self.topic = topic
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        self._producer_name = ""
        self._seq_lock = threading.Lock()

    # -- wire ---------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        from ..utils.netio import read_exact
        try:
            return read_exact(self._sock, n)
        except ConnectionError as e:
            raise PulsarError(str(e))

    def _read_frame(self) -> Tuple[int, Dict[int, bytes]]:
        """Returns (command_type, {field_no: raw nested bytes})."""
        total = struct.unpack(">I", self._read_exact(4))[0]
        data = self._read_exact(total)
        cmd_size = struct.unpack(">I", data[:4])[0]
        command = data[4:4 + cmd_size]
        cmd_type = 0
        fields: Dict[int, bytes] = {}
        for f, wt, v in iter_fields(command):
            if f == 1 and wt == 0:
                cmd_type = v
            elif wt == 2:
                fields[f] = bytes(v)
        return cmd_type, fields

    def _expect(self, want_type: int) -> Dict[int, bytes]:
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline:
            cmd_type, fields = self._read_frame()
            if cmd_type == PING:
                self._sock.sendall(_frame_simple(_cmd(PONG)))
                continue
            if cmd_type == want_type:
                return fields
            if cmd_type in (ERROR, SEND_ERROR):
                raise PulsarError(f"broker error: {fields}")
            # unrelated command (e.g. broker notices) — keep waiting
        raise PulsarError(f"timed out waiting for command {want_type}")

    # -- session ------------------------------------------------------------

    def connect(self) -> None:
        self._sock = socket.create_connection((self.host, self.port),
                                              timeout=self.timeout)
        # CommandConnect{client_version=1, protocol_version=7}
        body = e_bytes(1, "loongcollector-tpu") + e_varint(4, 7)
        self._sock.sendall(_frame_simple(_cmd(CONNECT, 2, body)))
        self._expect(CONNECTED)
        # CommandProducer{topic=1, producer_id=2, request_id=3}
        body = e_bytes(1, self.topic) + e_varint(2, 1) + e_varint(3, 1)
        self._sock.sendall(_frame_simple(_cmd(PRODUCER, 5, body)))
        fields = self._expect(PRODUCER_SUCCESS)
        # CommandProducerSuccess{request_id=1, producer_name=2}
        success = fields.get(17, b"")
        for f, wt, v in iter_fields(success):
            if f == 2 and wt == 2:
                self._producer_name = bytes(v).decode("utf-8", "replace")

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def send(self, payload: bytes,
             properties: Optional[Dict[str, str]] = None) -> None:
        """One message; blocks until SEND_RECEIPT (at-least-once).  Single
        caller by contract (see class docstring) — broker I/O runs outside
        any lock."""
        chaos.faultpoint(FP_SEND, exc=PulsarError)
        if self._sock is None:
            self.connect()
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        try:
            self._send_once(seq, payload, properties)
        except (OSError, PulsarError):
            # one reconnect attempt (broker restart / idle close)
            self.close()
            self.connect()
            self._send_once(seq, payload, properties)

    def _send_once(self, seq: int, payload: bytes, properties) -> None:
        # CommandSend{producer_id=1, sequence_id=2, num_messages=3}
        command = _cmd(SEND, 6, e_varint(1, 1) + e_varint(2, seq)
                       + e_varint(3, 1))
        # MessageMetadata{producer_name=1, sequence_id=2, publish_time=3,
        #                 properties=4 (KeyValue{key=1,value=2})}
        meta = (e_bytes(1, self._producer_name or "lct")
                + e_varint(2, seq)
                + e_varint(3, int(time.time() * 1000)))
        for k, v in (properties or {}).items():
            kv = e_bytes(1, k) + e_bytes(2, v)
            meta += e_bytes(4, kv)
        self._sock.sendall(_frame_payload(command, meta, payload))
        fields = self._expect(SEND_RECEIPT)
        receipt = fields.get(7, b"")
        got_seq = None
        for f, wt, v in iter_fields(receipt):
            if f == 2 and wt == 0:
                got_seq = v
        if got_seq is not None and got_seq != seq:
            raise PulsarError(f"receipt for seq {got_seq}, wanted {seq}")


class FlusherPulsar(AsyncSinkFlusher):
    """Batch → JSON/SLS-PB payload → Pulsar message (one per batch, with
    pipeline properties), through the shared batcher machinery.  Delivery
    runs on the flusher's OWN sender thread (async_sink.py) — a down
    broker backs payloads up in the bounded queue with retry/backoff and
    never blocks the pipeline's processing thread."""

    name = "flusher_pulsar"
    supports_columnar = True
    content_type = "application/octet-stream"

    def __init__(self) -> None:
        super().__init__()
        self.producer: Optional[PulsarProducer] = None
        self.fmt = "json"

    def _init_sink(self, config: Dict[str, Any]) -> bool:
        broker = config.get("BrokerURL") or config.get("URL", "")
        topic = config.get("Topic", "")
        if not broker or not topic:
            return False
        self.fmt = str(config.get("Format", "json")).lower()
        self.producer = PulsarProducer(
            broker, topic, timeout=float(config.get("TimeoutSecs", 10)))
        return True

    def build_payload(self, groups: List[PipelineEventGroup]):
        if self.fmt in ("sls", "sls_pb"):
            from ..pipeline.serializer.sls_serializer import \
                SLSEventGroupSerializer
            return SLSEventGroupSerializer().serialize(groups), {}
        from ..pipeline.serializer.json_serializer import JsonSerializer
        return JsonSerializer().serialize(groups), {}

    def deliver(self, payload: bytes) -> None:
        self.producer.send(
            payload, {"pipeline": self.context.pipeline_name
                      if self.context else ""})

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        super().stop(is_pipeline_removing)
        if self.producer is not None:
            self.producer.close()
        return True
