"""Flusher plugins (reference: core/plugin/flusher/, SURVEY.md §2.4)."""


def register_all(registry) -> None:
    from .blackhole import FlusherBlackHole
    from .clickhouse import FlusherClickHouse
    from .doris import FlusherDoris
    from .elasticsearch import FlusherElasticsearch
    from .file import FlusherFile
    from .http import FlusherHTTP
    from .kafka import FlusherKafka
    from .loki import FlusherLoki
    from .otlp import FlusherOTLP
    from .prometheus_rw import FlusherPrometheus
    from .grpc_flusher import FlusherGrpc
    from .pulsar import FlusherPulsar
    from .sls import FlusherSLS
    from .stdout import FlusherStdout

    registry.register_flusher("flusher_stdout", FlusherStdout)
    registry.register_flusher("flusher_file", FlusherFile)
    registry.register_flusher("flusher_blackhole", FlusherBlackHole)
    registry.register_flusher("flusher_http", FlusherHTTP)
    registry.register_flusher("flusher_sls", FlusherSLS)
    registry.register_flusher("flusher_kafka", FlusherKafka)
    registry.register_flusher("flusher_elasticsearch", FlusherElasticsearch)
    registry.register_flusher("flusher_loki", FlusherLoki)
    registry.register_flusher("flusher_clickhouse", FlusherClickHouse)
    registry.register_flusher("flusher_otlp", FlusherOTLP)
    registry.register_flusher("flusher_prometheus", FlusherPrometheus)
    registry.register_flusher("flusher_doris", FlusherDoris)
    registry.register_flusher("flusher_pulsar", FlusherPulsar)
    registry.register_flusher("flusher_grpc", FlusherGrpc)
    from .testing import (FlusherChecker, FlusherSleep,
                          FlusherStatistics)
    registry.register_flusher("flusher_checker", FlusherChecker)
    registry.register_flusher("flusher_sleep", FlusherSleep)
    registry.register_flusher("flusher_statistics",
                              FlusherStatistics)
