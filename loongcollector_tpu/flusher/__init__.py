"""Flusher plugins (reference: core/plugin/flusher/, SURVEY.md §2.4)."""


def register_all(registry) -> None:
    from .blackhole import FlusherBlackHole
    from .file import FlusherFile
    from .stdout import FlusherStdout
    from .http import FlusherHTTP
    from .sls import FlusherSLS
    from .kafka import FlusherKafka

    registry.register_flusher("flusher_stdout", FlusherStdout)
    registry.register_flusher("flusher_file", FlusherFile)
    registry.register_flusher("flusher_blackhole", FlusherBlackHole)
    registry.register_flusher("flusher_http", FlusherHTTP)
    registry.register_flusher("flusher_sls", FlusherSLS)
    registry.register_flusher("flusher_kafka", FlusherKafka)
