"""flusher_http — generic HTTP sink through the sender-queue path.

Reference: the HttpFlusher interface (collection_pipeline/plugin/interface/
HttpFlusher.h): BuildRequest produces the request for the sink thread;
OnSendDone handles the response.  Payloads are serialized + compressed, then
queued as SenderQueueItems for FlusherRunner → HttpSink dispatch.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional
from urllib.parse import urlparse

import time

from ..models import EventGroupMetaKey, PipelineEventGroup
from ..monitor import ledger, slo
from ..runner import ack_watermark
from ..pipeline.batch.batcher import Batcher
from ..pipeline.batch.flush_strategy import FlushStrategy
from ..pipeline.compression import create_compressor
from ..pipeline.plugin.interface import Flusher, PluginContext
from ..pipeline.queue.sender_queue import SenderQueueItem
from ..pipeline.serializer.json_serializer import JsonSerializer
from ..pipeline.serializer.sls_serializer import SLSEventGroupSerializer


class HttpRequest:
    __slots__ = ("method", "url", "headers", "body", "timeout")

    def __init__(self, method: str, url: str, headers: Dict[str, str],
                 body: bytes, timeout: float = 15.0):
        self.method = method
        self.url = url
        self.headers = headers
        self.body = body
        self.timeout = timeout


class FlusherHTTP(Flusher):
    name = "flusher_http"
    supports_columnar = True

    def __init__(self) -> None:
        super().__init__()
        self.remote_url = ""
        self.headers: Dict[str, str] = {}
        self.serializer = None
        self.compressor = None
        self.batcher: Batcher = None  # type: ignore
        self.eo_sender = None  # ExactlyOnceSender when ExactlyOnce configured
        self._eo_stop = False
        self.authenticator = None     # extension refs (resolve_http_extensions)
        self.breaker = None
        self.flush_interceptor = None
        self._encoder_ext = None

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.remote_url = config.get("RemoteURL", "")
        if not self.remote_url:
            return False
        self.headers = dict(config.get("Headers", {}))
        from .http_base import resolve_http_extensions
        if not resolve_http_extensions(self, config, context):
            return False
        fmt = config.get("Format", "json")
        # an encoder EXTENSION ref overrides the built-in Format choice
        enc_ref = config.get("Encoder")
        self._encoder_ext = (context.get_extension(str(enc_ref))
                             if enc_ref else None)
        if enc_ref and self._encoder_ext is None:
            return False
        self.serializer = (SLSEventGroupSerializer() if fmt == "sls_pb"
                           else JsonSerializer())
        self.compressor = create_compressor(config.get("Compression"))
        self._init_exactly_once(config, context)
        strategy = FlushStrategy(
            min_cnt=int(config.get("MinCnt", 0)),
            min_size_bytes=int(config.get("MinSizeBytes", 256 * 1024)),
            max_size_bytes=int(config.get("MaxSizeBytes", 5 * 1024 * 1024)),
            timeout_secs=float(config.get("TimeoutSecs", 1.0)))
        self.batcher = Batcher(strategy, on_flush=self._serialize_and_push,
                               flusher_id=self.name,
                               pipeline_name=context.pipeline_name)
        return True

    def _init_exactly_once(self, config, context) -> None:
        eo_cfg = config.get("ExactlyOnce")
        if not eo_cfg:
            return
        from ..input.file.checkpoint_v2 import (ExactlyOnceSender,
                                                get_default_manager)
        mgr = get_default_manager()
        if mgr is not None:
            self.eo_sender = ExactlyOnceSender(
                mgr, f"{context.pipeline_name}:{self.plugin_id or self.name}",
                concurrency=int(eo_cfg.get("Concurrency", 8)))

    def send(self, group: PipelineEventGroup) -> bool:
        if self.flush_interceptor is not None \
                and not self.flush_interceptor.filter([group]):
            # filtered out, not an error — but terminal for these events
            self._ledger_drop("flush_filtered", group=group)
            return True
        if self.eo_sender is not None:
            return self._send_exactly_once(group)
        self.batcher.add(group)
        return True

    def _send_exactly_once(self, group: PipelineEventGroup) -> bool:
        """Exactly-once path: one payload per group, range checkpoint
        persisted BEFORE enqueue, committed on sink ack (reference
        ExactlyOnceQueueManager; batching is bypassed so each payload maps
        to one file range)."""
        def _meta_int(key):
            v = group.get_metadata(key)
            try:
                return int(str(v)) if v is not None else 0
            except ValueError:
                return 0
        path = group.get_metadata(EventGroupMetaKey.LOG_FILE_PATH)
        cp = None
        # slot back-pressure caps in-flight EO sends; the wait breaks on
        # flusher stop so shutdown never spins a processor thread forever
        while not self._eo_stop:
            cp = self.eo_sender.acquire_slot(
                str(path) if path is not None else "",
                _meta_int(EventGroupMetaKey.LOG_FILE_DEV),
                _meta_int(EventGroupMetaKey.LOG_FILE_INODE),
                _meta_int(EventGroupMetaKey.LOG_FILE_OFFSET),
                _meta_int(EventGroupMetaKey.LOG_FILE_LENGTH))
            if cp is not None:
                break
            time.sleep(0.005)
        if cp is None:
            # shutting down; range stays uncommitted → checkpoint replay
            # re-reads the SOURCE bytes next start, so the events never
            # entered the sink path — a terminal discard for THIS run
            self._ledger_drop("eo_shutdown", group=group)
            return False
        data = self.serializer.serialize([group])
        if ledger.is_on():
            ledger.record(self._ledger_pipeline(), ledger.B_SERIALIZE,
                          len(group), len(data))
        payload = self.compressor.compress(data)
        item = SenderQueueItem(payload, len(data), flusher=self,
                               queue_key=self.queue_key,
                               tag={"eo_cp": cp}, event_cnt=len(group),
                               spans=ack_watermark.spans_of([group]),
                               stamps=slo.stamps_of([group]))
        if self.sender_queue is None:
            self._ledger_drop("no_sender_queue", len(group))
            ack_watermark.ack_spans(item.spans, force=True)
            slo.observe_stamps(self._ledger_pipeline(), item.stamps,
                               slo.OUTCOME_DROP)
        elif not self.sender_queue.push(item):
            # refused push (queue retired mid-hot-reload): terminal —
            # nothing downstream will ever dispatch or count this payload
            self._ledger_drop("queue_retired", len(group))
            ack_watermark.ack_spans(item.spans, force=True)
            slo.observe_stamps(self._ledger_pipeline(), item.stamps,
                               slo.OUTCOME_DROP)
        return True

    def _serialize_and_push(self, groups: List[PipelineEventGroup]) -> None:
        n_events = sum(len(g) for g in groups)
        if self._encoder_ext is not None:
            data = self._encoder_ext.encode(groups)
        else:
            # view path: the compressor consumes the serializer's buffer
            # directly (SLS returns a memoryview; others return bytes)
            data = self.serializer.serialize_view(groups)
        raw_size = len(data)
        if ledger.is_on():
            ledger.record(self._ledger_pipeline(), ledger.B_SERIALIZE,
                          n_events, raw_size)
        payload = self.compressor.compress(data)
        item = SenderQueueItem(payload, raw_size, flusher=self,
                               queue_key=self.queue_key, event_cnt=n_events,
                               spans=ack_watermark.spans_of(groups),
                               stamps=slo.stamps_of(groups))
        if self.sender_queue is None:
            self._ledger_drop("no_sender_queue", n_events)
            ack_watermark.ack_spans(item.spans, force=True)
            slo.observe_stamps(self._ledger_pipeline(), item.stamps,
                               slo.OUTCOME_DROP)
        elif not self.sender_queue.push(item):
            self._ledger_drop("queue_retired", n_events)
            ack_watermark.ack_spans(item.spans, force=True)
            slo.observe_stamps(self._ledger_pipeline(), item.stamps,
                               slo.OUTCOME_DROP)

    def build_request(self, item: SenderQueueItem) -> HttpRequest:
        from .http_base import check_breaker
        check_breaker(self)
        headers = dict(self.headers)
        if self._encoder_ext is not None:
            # the encoder EXTENSION owns the payload format
            wire_pb = getattr(self._encoder_ext, "fmt", "") in ("sls",
                                                                "sls_pb")
        else:
            wire_pb = isinstance(self.serializer, SLSEventGroupSerializer)
        headers.setdefault("Content-Type",
                           "application/x-protobuf" if wire_pb
                           else "application/json")
        if self.compressor.name != "none":
            headers["Content-Encoding"] = self.compressor.name
            headers["x-log-bodyrawsize"] = str(item.raw_size)
        req = HttpRequest("POST", self.remote_url, headers, item.data)
        if self.authenticator is not None:
            self.authenticator.apply(req)
        return req

    def on_send_done(self, item: SenderQueueItem, status: int,
                     body: bytes) -> str:
        """Returns 'ok' | 'retry' | 'drop' (reference OnSendDone semantics)."""
        if self.breaker is not None:
            self.breaker.on_result(200 <= status < 300)
        cp = item.tag.get("eo_cp")
        if 200 <= status < 300:
            if cp is not None and self.eo_sender is not None:
                self.eo_sender.commit_slot(cp)
            return "ok"
        if status in (429, 500, 502, 503, 504) or status <= 0:
            return "retry"
        # non-retryable rejection: the sink refused the data permanently —
        # commit the range (discard-ack) so the slot frees and the range is
        # not replayed forever
        if cp is not None and self.eo_sender is not None:
            self.eo_sender.commit_slot(cp)
        return "drop"

    def flush_all(self) -> bool:
        self.batcher.flush_all()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self._eo_stop = True
        self.batcher.flush_all()
        self.batcher.close()
        return True
