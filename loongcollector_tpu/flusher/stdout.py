"""flusher_stdout — JSON lines to stdout (quick-start sink; the reference's
quick-start uses flusher_stdout from the Go runtime — here it's native)."""

from __future__ import annotations

import sys
from typing import Any, Dict, List

from ..models import PipelineEventGroup
from ..pipeline.batch.batcher import Batcher
from ..pipeline.batch.flush_strategy import FlushStrategy
from ..pipeline.plugin.interface import Flusher, PluginContext
from ..pipeline.serializer.json_serializer import JsonSerializer


class FlusherStdout(Flusher):
    name = "flusher_stdout"
    supports_columnar = True
    # loongledger: NOT ledger_terminal — send() stages into the batcher;
    # the terminal record lands in _flush_groups after the stream write
    # (see FlusherFile for the rationale)

    def __init__(self) -> None:
        super().__init__()
        self.serializer = JsonSerializer()
        self.batcher: Batcher = None  # type: ignore
        self.only_stdout = True
        self._stream = sys.stdout

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        # stdout flushes immediately by default (interactive quick start)
        strategy = FlushStrategy(min_cnt=int(config.get("MinCnt", 0)) or 1,
                                 min_size_bytes=0, timeout_secs=1.0)
        self.batcher = Batcher(strategy, on_flush=self._flush_groups,
                               flusher_id=self.name,
                               pipeline_name=context.pipeline_name)
        return True

    def send(self, group: PipelineEventGroup) -> bool:
        self.batcher.add(group)
        return True

    def _flush_groups(self, groups: List[PipelineEventGroup]) -> None:
        def write():
            data = self.serializer.serialize(groups)
            self._stream.write(data.decode("utf-8", "replace"))
            self._stream.flush()
        self._ledger_terminal_write(groups, write)

    def flush_all(self) -> bool:
        self.batcher.flush_all()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self.batcher.flush_all()
        self.batcher.close()
        return True
