"""service_telegraf — supervised Telegraf agent bridge.

Reference: plugins/input/telegraf/ (input_telegraf.go registers config
snippets with a singleton Manager; telegraf_manager.go writes
conf.d/<name>.conf + a pinned telegraf.conf, supervises the external
telegraf process with a 30 s status check, and telegraf_log_collector.go
tails telegraf's own log into the agent's alarm channel).

Data path: the pinned telegraf.conf adds an `outputs.http` writing influx
line protocol to this agent's HTTP ingest (Format "influx",
input_http_server) or any sink the user's Detail configures — the bridge
itself only manages lifecycle, exactly like the reference.

Degraded gate: when no telegraf binary is present the manager still
renders configs (an external supervisor can pick them up) and reports a
warning instead of failing the pipeline.
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import threading
import time
from typing import Any, Dict, Optional

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger
from .supervisor import ProcessSupervisor, sanitize_name

log = get_logger("telegraf")

_DEFAULT_CONF = """# DO NOT MODIFY: regenerated when the agent starts.
[agent]
  interval = "10s"
  flush_interval = "10s"
  logfile = "{logfile}"
"""

_CHECK_INTERVAL_S = 30.0


class TelegrafManager(ProcessSupervisor):
    """Singleton per install dir (reference GetTelegrafManager)."""

    check_interval_s = _CHECK_INTERVAL_S

    def __init__(self, base_dir: str) -> None:
        super().__init__(base_dir)
        self.conf_dir = os.path.join(base_dir, "conf.d")
        self.log_path = os.path.join(base_dir, "telegraf.log")
        self.binary = (shutil.which("telegraf")
                       or (os.path.join(base_dir, "telegraf")
                           if os.path.exists(os.path.join(base_dir,
                                                          "telegraf"))
                           else None))
        self._configs: Dict[str, str] = {}
        self._dirty = False
        self._sinks: Dict[str, Any] = {}
        self._log_thread: Optional[threading.Thread] = None

    # -- config registration -----------------------------------------------

    def register(self, name: str, detail: str, sink=None) -> None:
        with self._lock:
            if self._configs.get(name) != detail:
                self._dirty = True
            self._configs[name] = detail
            if sink is not None:
                self._sinks[name] = sink
            started = self._running
        if not started:
            self.start_loop()
        self.wake()

    def unregister(self, name: str) -> None:
        with self._lock:
            if name in self._configs:
                self._dirty = True
            self._configs.pop(name, None)
            self._sinks.pop(name, None)
            empty = not self._configs
        self.wake()
        if empty:
            self.stop_loop()

    # -- filesystem --------------------------------------------------------

    def _render(self) -> None:
        os.makedirs(self.conf_dir, exist_ok=True)
        with self._lock:
            configs = dict(self._configs)
        base = os.path.join(self.base_dir, "telegraf.conf")
        with open(base, "w", encoding="utf-8") as f:
            f.write(_DEFAULT_CONF.format(logfile=self.log_path))
        keep = set()
        for name, detail in configs.items():
            safe = sanitize_name(name)
            keep.add(safe + ".conf")
            path = os.path.join(self.conf_dir, safe + ".conf")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(detail)
            os.replace(tmp, path)
        for existing in os.listdir(self.conf_dir):
            if existing.endswith(".conf") and existing not in keep:
                os.unlink(os.path.join(self.conf_dir, existing))

    # -- supervision -------------------------------------------------------

    def _on_start(self) -> None:
        # snapshot the tail position BEFORE the thread exists: anything
        # appended after start() returns is then guaranteed to ship (the
        # thread taking the snapshot raced writers that appended between
        # start() returning and the thread's first scheduling)
        try:
            self._log_pos = os.path.getsize(self.log_path)
        except OSError:
            self._log_pos = 0
        self._log_thread = threading.Thread(target=self._tail_log,
                                            daemon=True,
                                            name="telegraf-logtail")
        self._log_thread.start()

    def _on_stop(self) -> None:
        if self._log_thread is not None:
            self._log_thread.join(timeout=3)
            self._log_thread = None

    def _tick(self) -> None:
        with self._lock:
            have_cfg = bool(self._configs)
            dirty, self._dirty = self._dirty, False
        try:
            self._render()
        except OSError as e:
            log.warning("telegraf conf render failed: %s", e)
        if have_cfg and self.binary:
            self._ensure_proc(reload=dirty)
        elif not have_cfg:
            self.kill_proc()
        elif self.binary is None:
            log.warning("telegraf binary not found; configs rendered "
                        "to %s but nothing supervises them",
                        self.conf_dir)

    def _ensure_proc(self, reload: bool = False) -> None:
        if self.proc_alive():
            if reload:       # config changed: telegraf reloads on SIGHUP
                try:
                    self._proc.send_signal(signal.SIGHUP)
                except OSError:
                    pass
            return
        try:
            self._proc = subprocess.Popen(
                [self.binary, "--config",
                 os.path.join(self.base_dir, "telegraf.conf"),
                 "--config-directory", self.conf_dir],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                cwd=self.base_dir)
            log.info("telegraf started pid=%s", self._proc.pid)
        except OSError as e:
            log.warning("telegraf start failed: %s", e)
            self._proc = None

    # -- telegraf's own log → events (reference LogCollector) ---------------

    def _tail_log(self) -> None:
        # tail from the position snapshotted at start (pre-existing log
        # content was either already shipped by a previous run or predates
        # this agent)
        pos = getattr(self, "_log_pos", 0)
        while True:
            with self._lock:
                if not self._running:
                    return
                sinks = list(self._sinks.values())
            try:
                if os.path.exists(self.log_path):
                    with open(self.log_path, "rb") as f:
                        f.seek(0, os.SEEK_END)
                        end = f.tell()
                        if end < pos:          # rotated
                            pos = 0
                        f.seek(pos)
                        chunk = f.read(256 * 1024)
                        # consume only complete lines; a torn tail waits
                        # for the writer to finish it
                        cut = chunk.rfind(b"\n")
                        if cut < 0:
                            chunk = b""
                        else:
                            chunk = chunk[: cut + 1]
                        pos += len(chunk)
                    if chunk and sinks:
                        self._emit_log(chunk, sinks)
            except OSError:
                pass
            time.sleep(2.0)

    @staticmethod
    def _emit_log(chunk: bytes, sinks) -> None:
        group = PipelineEventGroup()
        sb = group.source_buffer
        now = int(time.time())
        for line in chunk.splitlines():
            if not line.strip():
                continue
            ev = group.add_log_event(now)
            ev.set_content(b"content", sb.copy_string(line))
            # telegraf log format: ts level! msg  (E!/W!/I!/D!)
            for marker, level in ((b" E! ", b"error"), (b" W! ", b"warning"),
                                  (b" I! ", b"info"), (b" D! ", b"debug")):
                if marker in line:
                    ev.set_content(b"level", level)
                    break
        group.set_tag(b"__source__", b"telegraf")
        if len(group):
            for sink in sinks:
                sink(group)


class ServiceTelegraf(Input):
    """service_telegraf (plugins/input/telegraf/input_telegraf.go)."""

    name = "service_telegraf"

    def __init__(self) -> None:
        super().__init__()
        self._manager: Optional[TelegrafManager] = None
        self._cfg_name = ""

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.detail = str(config.get("Detail", ""))
        base = config.get("TelegrafHome") or os.path.join(
            os.environ.get("LOONG_THIRD_PARTY_DIR",
                           os.path.join(os.path.expanduser("~"),
                                        ".loongcollector", "thirdparty")),
            "telegraf")
        self._base_dir = str(base)
        return bool(self.detail)

    def start(self) -> bool:
        self._manager = TelegrafManager.get(self._base_dir)
        self._cfg_name = self.context.pipeline_name or "telegraf"
        pqm = self.context.process_queue_manager
        key = self.context.process_queue_key

        def sink(group: PipelineEventGroup) -> None:
            pqm.push_queue(key, group)

        self._manager.register(self._cfg_name, self.detail,
                               sink if pqm is not None else None)
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        if self._manager is not None:
            self._manager.unregister(self._cfg_name)
            self._manager = None
        return True
