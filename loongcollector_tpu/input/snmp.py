"""input_snmp — SNMP v2c polling with a minimal BER codec.

Reference: plugins/input/snmp/ (gosnmp). No SNMP library here, so the
input encodes GetRequest PDUs and decodes responses directly (the tiny
ASN.1/BER subset SNMP needs: SEQUENCE, INTEGER, OCTET STRING, OID, NULL,
plus the application types Counter32/Gauge32/TimeTicks/Counter64).
Each poll emits one MetricEvent per OID with numeric values, or a
LogEvent field for strings.
"""

from __future__ import annotations

import socket
import time
from typing import Any, Dict, List, Tuple

from ..models import MetricValue, PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext
from ..utils.logger import get_logger
from ..utils.net import host_port
from .polling_base import PollingInput

log = get_logger("snmp")

# -- BER ---------------------------------------------------------------------


def _ber_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def _tlv(tag: int, payload: bytes) -> bytes:
    return bytes([tag]) + _ber_len(len(payload)) + payload


def _ber_int(v: int, tag: int = 0x02) -> bytes:
    if v == 0:
        return _tlv(tag, b"\x00")
    body = v.to_bytes((v.bit_length() + 8) // 8, "big", signed=True)
    return _tlv(tag, body)


def encode_oid(oid: str) -> bytes:
    parts = [int(p) for p in oid.strip(".").split(".")]
    body = bytearray([parts[0] * 40 + parts[1]])
    for p in parts[2:]:
        chunk = bytearray()
        chunk.append(p & 0x7F)
        p >>= 7
        while p:
            chunk.append((p & 0x7F) | 0x80)
            p >>= 7
        body += bytes(reversed(chunk))
    return _tlv(0x06, bytes(body))


def _parse_tlv(buf: bytes, pos: int) -> Tuple[int, bytes, int]:
    tag = buf[pos]
    pos += 1
    ln = buf[pos]
    pos += 1
    if ln & 0x80:
        nb = ln & 0x7F
        ln = int.from_bytes(buf[pos:pos + nb], "big")
        pos += nb
    return tag, buf[pos:pos + ln], pos + ln


def decode_oid(body: bytes) -> str:
    parts = [body[0] // 40, body[0] % 40]
    v = 0
    for b in body[1:]:
        v = (v << 7) | (b & 0x7F)
        if not b & 0x80:
            parts.append(v)
            v = 0
    return ".".join(str(p) for p in parts)


def build_get_request(community: str, oids: List[str],
                      request_id: int) -> bytes:
    varbinds = b"".join(
        _tlv(0x30, encode_oid(o) + _tlv(0x05, b"")) for o in oids)
    pdu = _tlv(0xA0,                    # GetRequest-PDU
               _ber_int(request_id)
               + _ber_int(0) + _ber_int(0)      # error-status/index
               + _tlv(0x30, varbinds))
    return _tlv(0x30, _ber_int(1)               # version: v2c
                + _tlv(0x04, community.encode()) + pdu)


def parse_response(data: bytes) -> Dict[str, Any]:
    """Response message → {oid: value} (ints, bytes, or None).
    Malformed datagrams (truncated BER, stray packets) return {} — a bad
    response must never kill the polling thread."""
    try:
        return _parse_response(data)
    except (IndexError, ValueError):
        return {}


def _parse_response(data: bytes) -> Dict[str, Any]:
    _, msg, _ = _parse_tlv(data, 0)
    pos = 0
    _, _, pos = _parse_tlv(msg, pos)            # version
    _, _, pos = _parse_tlv(msg, pos)            # community
    tag, pdu, _ = _parse_tlv(msg, pos)
    pos = 0
    _, _, pos = _parse_tlv(pdu, pos)            # request id
    _, err, pos = _parse_tlv(pdu, pos)          # error-status
    _, _, pos = _parse_tlv(pdu, pos)            # error-index
    if err and int.from_bytes(err, "big"):
        return {}
    _, binds, _ = _parse_tlv(pdu, pos)
    out: Dict[str, Any] = {}
    pos = 0
    while pos < len(binds):
        _, vb, pos = _parse_tlv(binds, pos)
        otag, oid_body, vpos = _parse_tlv(vb, 0)
        vtag, val, _ = _parse_tlv(vb, vpos)
        oid = decode_oid(oid_body)
        if vtag == 0x02 or vtag in (0x41, 0x42, 0x43, 0x46):
            # INTEGER / Counter32 / Gauge32 / TimeTicks / Counter64
            out[oid] = int.from_bytes(val, "big",
                                      signed=(vtag == 0x02))
        elif vtag == 0x04:
            out[oid] = val
        elif vtag == 0x06:
            out[oid] = decode_oid(val)
        else:
            out[oid] = None
    return out


def snmp_get(host: str, port: int, community: str, oids: List[str],
             timeout: float = 3.0, request_id: int = 1) -> Dict[str, Any]:
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.settimeout(timeout)
    try:
        sock.sendto(build_get_request(community, oids, request_id),
                    (host, port))
        data, _ = sock.recvfrom(65535)
    finally:
        sock.close()
    return parse_response(data)


# -- input plugin ------------------------------------------------------------


class InputSNMP(PollingInput):
    name = "input_snmp"

    def __init__(self) -> None:
        super().__init__()
        self._req_id = 0

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.targets: List[str] = list(config.get("Targets", []))
        self.oids: Dict[str, str] = dict(config.get("Oids", {}))  # name→oid
        self.community = config.get("Community", "public")
        self.interval = float(config.get("IntervalSecs", 30.0))
        return bool(self.targets) and bool(self.oids)

    def poll_once(self) -> None:
        pqm = self.context.process_queue_manager
        names = list(self.oids)
        oid_list = [self.oids[n] for n in names]
        for target in self.targets:
            host, port = host_port(target, 161)
            self._req_id += 1
            try:
                vals = snmp_get(host, port, self.community, oid_list,
                                request_id=self._req_id)
            except OSError as e:
                log.warning("snmp poll %s failed: %s", target, e)
                continue
            if pqm is None:
                continue
            group = PipelineEventGroup()
            sb = group.source_buffer
            now = int(time.time())
            for name, oid in zip(names, oid_list):
                v = vals.get(oid.strip("."))
                if v is None:
                    continue
                if isinstance(v, int):
                    ev = group.add_metric_event(now)
                    ev.name = name.encode()
                    ev.value = MetricValue(float(v))
                    ev.set_tag(b"target", target.encode())
                else:
                    lev = group.add_log_event(now)
                    lev.set_content(sb.copy_string(name.encode()),
                                    sb.copy_string(
                                        v if isinstance(v, bytes)
                                        else str(v).encode()))
                    lev.set_content(b"target", sb.copy_string(
                        target.encode()))
            if len(group):
                group.set_tag(b"__source__", b"snmp")
                pqm.push_queue(self.context.process_queue_key, group)
