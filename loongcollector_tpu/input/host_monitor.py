"""input_host_monitor — host metrics collectors.

Reference: core/host_monitor/ (8.5k LoC) — timer-scheduled collectors
(CPU/Mem/Disk/Net/Process/System) reading /proc via LinuxSystemInterface,
assembling metric events pushed through HostMonitorInputRunner
(HostMonitorInputRunner.cpp:285-339).

One runner thread schedules registered collectors on their intervals and
pushes MetricEvent groups into the owning pipeline's process queue.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger

log = get_logger("host_monitor")


# ---------------------------------------------------------------------------
# collectors: each returns {metric_name: (value, {tag: val})}
# ---------------------------------------------------------------------------


class CpuCollector:
    name = "cpu"

    def __init__(self) -> None:
        self._last: Optional[List[int]] = None

    def collect(self) -> List[Tuple[str, float, Dict[str, str]]]:
        with open("/proc/stat") as f:
            line = f.readline().split()
        vals = [int(x) for x in line[1:9]]
        out = []
        if self._last is not None:
            deltas = [a - b for a, b in zip(vals, self._last)]
            total = sum(deltas) or 1
            names = ["user", "nice", "system", "idle", "iowait", "irq",
                     "softirq", "steal"]
            for n, d in zip(names, deltas):
                out.append((f"cpu_{n}_percent", 100.0 * d / total, {}))
        self._last = vals
        return out


class MemCollector:
    name = "mem"

    def collect(self):
        info = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                info[k] = int(rest.split()[0]) * 1024
        total = info.get("MemTotal", 0)
        avail = info.get("MemAvailable", 0)
        used = total - avail
        out = [("memory_total_bytes", float(total), {}),
               ("memory_used_bytes", float(used), {}),
               ("memory_available_bytes", float(avail), {})]
        if total:
            out.append(("memory_used_percent", 100.0 * used / total, {}))
        return out


class DiskCollector:
    name = "disk"

    def collect(self):
        out = []
        seen = set()
        with open("/proc/mounts") as f:
            for line in f:
                dev, mnt, fstype = line.split()[:3]
                if not dev.startswith("/dev/") or mnt in seen:
                    continue
                seen.add(mnt)
                self._emit(out, dev, mnt, fstype)
        if "/" not in seen:
            # containers/VMs often mount the root fs from a non-/dev/
            # source (overlayfs, 9p, virtiofs) — report the root volume
            # even when /dev/-backed data volumes exist, so root-disk
            # capacity alerting is never blind
            with open("/proc/mounts") as f:
                for line in f:
                    dev, mnt, fstype = line.split()[:3]
                    if mnt == "/":
                        self._emit(out, dev, mnt, fstype)
                        break
        return out

    @staticmethod
    def _emit(out, dev, mnt, fstype):
        try:
            st = os.statvfs(mnt)
        except OSError:
            return
        total = st.f_blocks * st.f_frsize
        free = st.f_bavail * st.f_frsize
        if total == 0:
            return
        tags = {"device": dev, "mount": mnt, "fstype": fstype}
        out.append(("disk_total_bytes", float(total), tags))
        out.append(("disk_free_bytes", float(free), tags))
        out.append(("disk_used_percent",
                    100.0 * (total - free) / total, tags))


class NetCollector:
    name = "net"

    def __init__(self) -> None:
        self._last: Dict[str, Tuple[int, int]] = {}
        self._last_t = 0.0

    def collect(self):
        out = []
        now = time.monotonic()
        dt = now - self._last_t if self._last_t else 0
        with open("/proc/net/dev") as f:
            lines = f.readlines()[2:]
        for line in lines:
            iface, _, rest = line.partition(":")
            iface = iface.strip()
            vals = rest.split()
            rx, tx = int(vals[0]), int(vals[8])
            if iface in self._last and dt > 0:
                lrx, ltx = self._last[iface]
                tags = {"interface": iface}
                out.append(("net_rx_bytes_per_sec", (rx - lrx) / dt, tags))
                out.append(("net_tx_bytes_per_sec", (tx - ltx) / dt, tags))
            self._last[iface] = (rx, tx)
        self._last_t = now
        return out


class SystemCollector:
    name = "system"

    def collect(self):
        la1, la5, la15 = os.getloadavg()
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        return [("system_load_1m", la1, {}),
                ("system_load_5m", la5, {}),
                ("system_load_15m", la15, {}),
                ("system_uptime_seconds", uptime, {})]


class ProcessCollector:
    """Top-N processes by CPU ticks (reference ProcessCollector)."""

    name = "process"

    def __init__(self, top_n: int = 10):
        self.top_n = top_n

    def collect(self):
        procs = []
        for pid in os.listdir("/proc"):
            if not pid.isdigit():
                continue
            try:
                with open(f"/proc/{pid}/stat") as f:
                    data = f.read()
                # comm may contain spaces/parens: field 2 ends at last ')'
                rp = data.rindex(")")
                comm = data[data.index("(") + 1 : rp]
                rest = data[rp + 2 :].split()
                ticks = int(rest[11]) + int(rest[12])   # utime+stime
                rss = int(rest[21]) * os.sysconf("SC_PAGE_SIZE")
                nthreads = int(rest[17])
                start_ticks = int(rest[19])
                procs.append((ticks, comm, pid, rss, nthreads, start_ticks))
            except (OSError, IndexError, ValueError):
                continue
        procs.sort(reverse=True)
        out = []
        for ticks, comm, pid, rss, nthreads, start_ticks in procs[:self.top_n]:
            tags = {"pid": pid, "comm": comm}
            # entity detail (reference ProcessEntityCollector): cmdline,
            # uid, open fds, thread count, start time
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmdline = f.read().replace(b"\0", b" ").strip().decode(
                        "utf-8", "replace")
                if cmdline:
                    tags["cmdline"] = cmdline[:256]
            except OSError:
                pass
            try:
                st = os.stat(f"/proc/{pid}")
                tags["uid"] = str(st.st_uid)
            except OSError:
                pass
            try:
                n_fds = len(os.listdir(f"/proc/{pid}/fd"))
                out.append(("process_open_fds", float(n_fds), tags))
            except OSError:
                pass
            out.append(("process_cpu_ticks", float(ticks), tags))
            out.append(("process_rss_bytes", float(rss), tags))
            out.append(("process_threads", float(nthreads), tags))
            out.append(("process_start_ticks", float(start_ticks), tags))
        return out


class ProcessEntityCollector:
    """Process ENTITY lifecycle events (reference
    host_monitor/collector/ProcessEntityCollector.cpp:65-130 + the field
    vocabulary in constants/EntityConstants.cpp): top-N processes by CPU
    usage between collections, each emitted as an entity event (domain,
    entity type, stable entity id = md5(host|pid|ktime), first/last
    observed, keep-alive) plus a process→host link event.  Goes past the
    reference's TODOs: user name, cwd, binary and arguments are filled
    from /proc where readable."""

    name = "process_entity"

    def __init__(self, top_n: int = 20, interval_s: float = 60.0):
        import socket
        self.top_n = top_n
        self.interval_s = interval_s
        self._prev_ticks: Dict[int, int] = {}
        self._hostname = socket.gethostname()
        self._host_entity_id = hashlib.md5(
            self._hostname.encode()).hexdigest()
        self._boot_time = 0
        try:
            with open("/proc/stat") as f:
                for line in f:
                    if line.startswith("btime "):
                        self._boot_time = int(line.split()[1])
                        break
        except OSError:
            pass
        self._clk = os.sysconf("SC_CLK_TCK")

    def _entity_id(self, pid: str, ktime: str) -> str:
        return hashlib.md5(
            f"{self._hostname}{pid}{ktime}".encode()).hexdigest()

    @staticmethod
    def _user_of(uid: int) -> str:
        try:
            import pwd
            return pwd.getpwuid(uid).pw_name
        except (KeyError, ImportError):
            return str(uid)

    def _scan(self):
        """[(cpu_delta, pid, comm, ppid, start_ticks)] sorted by usage."""
        rows = []
        new_ticks: Dict[int, int] = {}
        for pid_s in os.listdir("/proc"):
            if not pid_s.isdigit():
                continue
            pid = int(pid_s)
            try:
                with open(f"/proc/{pid}/stat") as f:
                    data = f.read()
                rp = data.rindex(")")
                comm = data[data.index("(") + 1 : rp]
                rest = data[rp + 2 :].split()
                ppid = int(rest[1])
                ticks = int(rest[11]) + int(rest[12])
                start_ticks = int(rest[19])
            except (OSError, IndexError, ValueError):
                continue
            new_ticks[pid] = ticks
            delta = ticks - self._prev_ticks.get(pid, 0)
            rows.append((delta, pid, comm, ppid, start_ticks))
        self._prev_ticks = new_ticks
        rows.sort(reverse=True)
        return rows[: self.top_n]

    def collect_group(self) -> "PipelineEventGroup":
        group = PipelineEventGroup()
        sb = group.source_buffer
        now = int(time.time())
        keep_alive = str(int(self.interval_s * 2))

        def put(ev, k: str, v: str) -> None:
            ev.set_content(sb.copy_string(k.encode()),
                           sb.copy_string(v.encode()[:512]))

        for _delta, pid, comm, ppid, start_ticks in self._scan():
            ktime = str(self._boot_time + start_ticks // self._clk)
            entity_id = self._entity_id(str(pid), ktime)
            ev = group.add_log_event(now)
            put(ev, "__domain__", "infra")
            put(ev, "__entity_type__", "infra.host.process")
            put(ev, "__entity_id__", entity_id)
            put(ev, "__first_observed_time__", ktime)
            put(ev, "__last_observed_time__", str(now))
            put(ev, "__keep_alive_seconds__", keep_alive)
            put(ev, "pid", str(pid))
            put(ev, "ppid", str(ppid))
            put(ev, "comm", comm)
            put(ev, "ktime", ktime)
            try:
                st = os.stat(f"/proc/{pid}")
                put(ev, "user", self._user_of(st.st_uid))
            except OSError:
                pass
            try:
                put(ev, "cwd", os.readlink(f"/proc/{pid}/cwd"))
            except OSError:
                pass
            try:
                put(ev, "binary", os.readlink(f"/proc/{pid}/exe"))
            except OSError:
                pass
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    args = f.read().replace(b"\0", b" ").strip()
                if args:
                    put(ev, "arguments",
                        args.decode("utf-8", "replace"))
            except OSError:
                pass
            # process → host relation (reference link event)
            link = group.add_log_event(now)
            put(link, "__src_domain__", "infra")
            put(link, "__src_entity_type__", "infra.host.process")
            put(link, "__src_entity_id__", entity_id)
            put(link, "__dest_domain__", "infra")
            put(link, "__dest_entity_type__", "acs.host.instance")
            put(link, "__dest_entity_id__", self._host_entity_id)
            put(link, "__relation_type__", "update")
            put(link, "__first_observed_time__", ktime)
            put(link, "__last_observed_time__", str(now))
            put(link, "__keep_alive_seconds__", keep_alive)
        group.set_tag(b"__source__", b"process_entity")
        return group


class InputProcessEntity(Input):
    """Periodic process-entity snapshots (reference wires process_entity
    through InputHostMonitor's collector matrix; standalone input here)."""

    name = "input_process_entity"
    is_singleton = True

    def __init__(self) -> None:
        super().__init__()
        self.interval_s = 60.0
        self.top_n = 20
        self._collector: Optional[ProcessEntityCollector] = None

    def init(self, config, context) -> bool:
        super().init(config, context)
        self.interval_s = float(config.get("IntervalSeconds", 60))
        self.top_n = int(config.get("TopN", 20))
        self._collector = ProcessEntityCollector(self.top_n, self.interval_s)
        return True

    def start(self) -> bool:
        runner = HostMonitorInputRunner.instance()
        runner.register_group_collector(
            f"{self.context.pipeline_name}#process_entity",
            self._collector.collect_group,
            self.interval_s, self.context.process_queue_key, immediate=True)
        runner.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        HostMonitorInputRunner.instance().unregister(
            f"{self.context.pipeline_name}#process_entity")
        return True


class GPUCollector:
    """GPU utilisation/memory (reference host_monitor GPU collector via
    NVML). Gated: reads nvidia-smi when present; on TPU hosts, surfaces
    the accelerator count from the jax backend instead."""

    name = "gpu"

    def collect(self):
        out = []
        import shutil
        import subprocess
        smi = shutil.which("nvidia-smi")
        if smi:
            try:
                r = subprocess.run(
                    [smi, "--query-gpu=index,utilization.gpu,memory.used,"
                     "memory.total", "--format=csv,noheader,nounits"],
                    capture_output=True, timeout=5, text=True)
                for line in r.stdout.splitlines():
                    parts = [p.strip() for p in line.split(",")]
                    if len(parts) != 4:
                        continue
                    tags = {"gpu": parts[0]}
                    out.append(("gpu_utilization_percent",
                                float(parts[1]), tags))
                    out.append(("gpu_memory_used_mb", float(parts[2]), tags))
                    out.append(("gpu_memory_total_mb", float(parts[3]), tags))
            except (OSError, ValueError, subprocess.SubprocessError):
                pass
        return out


COLLECTORS: Dict[str, Callable] = {
    "cpu": CpuCollector,
    "mem": MemCollector,
    "disk": DiskCollector,
    "net": NetCollector,
    "system": SystemCollector,
    "process": ProcessCollector,
    "gpu": GPUCollector,
}


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


class HostMonitorInputRunner:
    _instance: Optional["HostMonitorInputRunner"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._registrations: Dict[str, tuple] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self.process_queue_manager = None

    @classmethod
    def instance(cls) -> "HostMonitorInputRunner":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def register(self, name: str, collectors: List[str], interval_s: float,
                 queue_key: int) -> None:
        insts = [COLLECTORS[c]() for c in collectors if c in COLLECTORS]
        with self._lock:
            self._registrations[name] = (insts, interval_s, queue_key, [0.0])

    def register_group_collector(self, name: str, fn, interval_s: float,
                                 queue_key: int,
                                 immediate: bool = False) -> None:
        """Schedule an arbitrary group-producing callable (entity snapshots
        etc.); fn() -> Optional[PipelineEventGroup]."""
        with self._lock:
            self._registrations[name] = (
                fn, interval_s, queue_key,
                [0.0 if immediate else time.monotonic()])

    def unregister(self, name: str) -> None:
        with self._lock:
            self._registrations.pop(name, None)

    def start(self) -> None:
        with self._lock:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(target=self._run, name="host-monitor",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=2)
            self._thread = None

    def _run(self) -> None:
        while self._running:
            time.sleep(0.2)
            with self._lock:
                regs = dict(self._registrations)
            now = time.monotonic()
            for name, (insts, interval, queue_key, last) in regs.items():
                if now - last[0] < interval:
                    continue
                last[0] = now
                try:
                    if callable(insts):
                        group = insts()
                        if group is not None and not group.empty() \
                                and self.process_queue_manager is not None:
                            self.process_queue_manager.push_queue(queue_key,
                                                                  group)
                    else:
                        self.collect_once(insts, queue_key)
                except Exception:  # noqa: BLE001
                    log.exception("host monitor collect failed: %s", name)

    def collect_once(self, insts, queue_key: int) -> None:
        group = PipelineEventGroup()
        sb = group.source_buffer
        ts = int(time.time())
        for coll in insts:
            for metric, value, tags in coll.collect():
                ev = group.add_metric_event(ts)
                ev.set_name(sb.copy_string(metric))
                ev.set_value(value)
                for k, v in tags.items():
                    ev.set_tag(sb.copy_string(k), sb.copy_string(v))
        if not group.empty() and self.process_queue_manager is not None:
            self.process_queue_manager.push_queue(queue_key, group)


class HostMetaCollector:
    """Entity snapshots (reference InputHostMeta): one host entity plus one
    entity per running process, shaped as log events with entity fields."""

    name = "host_meta"

    def collect_entities(self):
        import socket
        entities = []
        host = {
            "__entity_type__": "host",
            "hostname": socket.gethostname(),
            "os": "linux",
        }
        try:
            with open("/proc/sys/kernel/osrelease") as f:
                host["kernel"] = f.read().strip()
        except OSError:
            pass
        entities.append(host)
        for pid in os.listdir("/proc"):
            if not pid.isdigit():
                continue
            try:
                with open(f"/proc/{pid}/comm") as f:
                    comm = f.read().strip()
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmdline = f.read().replace(b"\0", b" ").decode(
                        "utf-8", "replace").strip()
                st = os.stat(f"/proc/{pid}")
            except OSError:
                continue
            entities.append({
                "__entity_type__": "process",
                "pid": pid,
                "comm": comm,
                "cmdline": cmdline[:512],
                "uid": str(st.st_uid),
            })
        return entities


class InputHostMeta(Input):
    """Periodic host/process entity snapshots, scheduled through the shared
    HostMonitorInputRunner (one timer thread for all host collectors)."""

    name = "input_host_meta"
    is_singleton = True

    def __init__(self) -> None:
        super().__init__()
        self.interval_s = 300.0

    def init(self, config, context) -> bool:
        super().init(config, context)
        self.interval_s = float(config.get("IntervalSeconds", 300))
        return True

    def _build_group(self):
        group = PipelineEventGroup()
        sb = group.source_buffer
        ts = int(time.time())
        for entity in HostMetaCollector().collect_entities():
            ev = group.add_log_event(ts)
            for k, v in entity.items():
                ev.set_content(sb.copy_string(k), sb.copy_string(v))
        group.set_tag(b"__source__", b"host_meta")
        return group

    def collect_once(self) -> None:
        runner = HostMonitorInputRunner.instance()
        if runner.process_queue_manager is None:
            return
        runner.process_queue_manager.push_queue(
            self.context.process_queue_key, self._build_group())

    def start(self) -> bool:
        runner = HostMonitorInputRunner.instance()
        runner.register_group_collector(
            f"{self.context.pipeline_name}#hostmeta", self._build_group,
            self.interval_s, self.context.process_queue_key, immediate=True)
        runner.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        HostMonitorInputRunner.instance().unregister(
            f"{self.context.pipeline_name}#hostmeta")
        return True


class InputHostMonitor(Input):
    name = "input_host_monitor"
    is_singleton = True

    def __init__(self) -> None:
        super().__init__()
        self.collectors: List[str] = []
        self.interval_s = 60.0

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.collectors = list(config.get(
            "Collectors", ["cpu", "mem", "disk", "net", "system"]))
        self.interval_s = float(config.get("IntervalSeconds", 60))
        return True

    def start(self) -> bool:
        runner = HostMonitorInputRunner.instance()
        runner.register(self.context.pipeline_name, self.collectors,
                        self.interval_s, self.context.process_queue_key)
        runner.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        HostMonitorInputRunner.instance().unregister(self.context.pipeline_name)
        return True
