"""input_command — periodic script execution with captured stdout.

Reference: plugins/input/command/ (input_command.go: validated script
types + non-root user gate + Base64 payloads; command_script_storage.go:
scripts materialized under the agent conf dir keyed by config + content
md5; RunCommandWithTimeOut: kill-on-timeout).

Events carry one content field per LineSplitSep chunk plus the script_md5
the reference stamps for traceability.
"""

from __future__ import annotations

import base64
import hashlib
import os
import shutil
import subprocess
import time
from typing import Any, Dict, Optional

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext
from ..utils.logger import get_logger
from .polling_base import PollingInput

log = get_logger("command")

# (suffix, interpreter name, fallback absolute paths).  The interpreter is
# resolved via PATH first — containers differ on where sh/bash live
# (/usr/bin vs /bin vs busybox), and the reference only fixes the NAME of
# the interpreter, not its location.
SCRIPT_TYPES = {
    "bash": ("sh", "bash", ("/usr/bin/bash", "/bin/bash")),
    "shell": ("sh", "sh", ("/usr/bin/sh", "/bin/sh")),
    "python2": ("py", "python2", ("/usr/bin/python2",)),
    "python3": ("py", "python3", ("/usr/bin/python3",)),
}


def resolve_interpreter(script_type: str) -> Optional[str]:
    """Absolute interpreter path for a script type: $PATH lookup first,
    then the conventional locations.  None when nothing exists."""
    _, name, candidates = SCRIPT_TYPES[script_type]
    found = shutil.which(name)
    if found:
        return found
    for cand in candidates:
        if os.path.exists(cand):
            return cand
    return None


class InputCommand(PollingInput):
    name = "input_command"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.script_type = str(config.get("ScriptType", "bash"))
        if self.script_type not in SCRIPT_TYPES:
            log.error("input_command: unsupported ScriptType %r",
                      self.script_type)
            return False
        content = str(config.get("ScriptContent", ""))
        if not content:
            log.error("input_command: ScriptContent is required")
            return False
        if str(config.get("ContentEncoding", "PlainText")) == "Base64":
            try:
                content = base64.b64decode(content).decode()
            except (ValueError, UnicodeDecodeError) as e:
                log.error("input_command: bad Base64 ScriptContent: %s", e)
                return False
        if len(content) > 512 * 1024:
            log.error("input_command: ScriptContent > 512K")
            return False
        self.user = str(config.get("User", ""))
        if self.user == "root":
            log.error("input_command: running as root is refused")
            return False
        self.content = content
        self.content_md5 = hashlib.md5(content.encode()).hexdigest()
        self.line_sep = str(config.get("LineSplitSep", ""))
        self.interval = int(config.get("IntervalMs", 5000)) / 1000.0
        self.timeout_s = min(int(config.get("TimeoutMilliSeconds", 3000)),
                             int(config.get("IntervalMs", 5000))) / 1000.0
        self.environments = list(config.get("Environments") or [])
        self.ignore_error = bool(config.get("IgnoreError", False))
        suffix = SCRIPT_TYPES[self.script_type][0]
        cmd_path = config.get("CmdPath") or resolve_interpreter(
            self.script_type)
        if not cmd_path or not os.path.exists(str(cmd_path)):
            log.error("input_command: no interpreter for %s (CmdPath=%r)",
                      self.script_type, config.get("CmdPath"))
            return False
        self.cmd_path = str(cmd_path)
        storage = os.path.join(
            os.environ.get("LOONG_CONF_DIR",
                           os.path.join(os.path.expanduser("~"),
                                        ".loongcollector")), "scripts")
        os.makedirs(storage, exist_ok=True)
        os.chmod(storage, 0o755)       # demoted exec user must traverse
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in (context.pipeline_name or "cmd"))
        self.script_path = os.path.join(
            storage, f"{safe}_{self.content_md5}.{suffix}")
        if not os.path.exists(self.script_path):
            with open(self.script_path, "w", encoding="utf-8") as f:
                f.write(content)
            os.chmod(self.script_path, 0o755)
        return True

    def _demote_ids(self):
        """(uid, gid) to run the script as, or (None, None) to run as-is.
        Passed via subprocess's user=/group= — NOT a preexec_fn closure,
        which can deadlock the forked child in a multi-threaded agent."""
        if not self.user:
            return None, None
        try:
            import pwd
            rec = pwd.getpwnam(self.user)
        except (ImportError, KeyError):
            log.warning("input_command: user %r not found; running as self",
                        self.user)
            return None, None
        if os.geteuid() != 0:
            return None, None
        return rec.pw_uid, rec.pw_gid

    def poll_once(self) -> None:
        env = dict(os.environ)
        for e in self.environments:
            k, _, v = e.partition("=")
            env[k] = v
        uid, gid = self._demote_ids()
        try:
            proc = subprocess.run(
                [self.cmd_path, self.script_path], capture_output=True,
                timeout=self.timeout_s, env=env, text=True,
                user=uid, group=gid)
        except subprocess.TimeoutExpired:
            if not self.ignore_error:
                log.warning("input_command: script timed out (%ss)",
                            self.timeout_s)
            return
        except OSError as e:
            if not self.ignore_error:
                log.warning("input_command: exec failed: %s", e)
            return
        if (proc.returncode != 0 or proc.stderr) and not self.ignore_error:
            log.warning("input_command: rc=%s stderr=%r", proc.returncode,
                        proc.stderr[:512])
            if proc.returncode != 0:
                return
        chunks = (proc.stdout.split(self.line_sep) if self.line_sep
                  else [proc.stdout])
        group = PipelineEventGroup()
        sb = group.source_buffer
        now = int(time.time())
        for chunk in chunks:
            ev = group.add_log_event(now)
            ev.set_content(b"content", sb.copy_string(chunk.encode()))
            ev.set_content(b"script_md5",
                           sb.copy_string(self.content_md5.encode()))
        group.set_tag(b"__source__", b"command")
        pqm = self.context.process_queue_manager
        if pqm is not None and len(group):
            pqm.push_queue(self.context.process_queue_key, group)
