"""input_syslog — UDP/TCP syslog ingest (RFC3164 + RFC5424).

Reference: plugins/input/syslog/ (Go service input).  Listens on UDP
datagrams and/or TCP newline-framed streams; each message parses into
priority (facility/severity), timestamp, hostname, tag/app and content
fields, with raw retention on parse failure.
"""

from __future__ import annotations

import re
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger

log = get_logger("syslog")

_FACILITIES = ["kern", "user", "mail", "daemon", "auth", "syslog", "lpr",
               "news", "uucp", "cron", "authpriv", "ftp", "ntp", "audit",
               "alert", "clock", "local0", "local1", "local2", "local3",
               "local4", "local5", "local6", "local7"]
_SEVERITIES = ["emerg", "alert", "crit", "err", "warning", "notice", "info",
               "debug"]

# RFC3164: <PRI>MMM dd HH:MM:SS host tag[pid]: msg
_RFC3164 = re.compile(
    rb"<(\d{1,3})>([A-Z][a-z]{2} [ \d]\d \d{2}:\d{2}:\d{2}) (\S+) "
    rb"([^:\[\s]+)(?:\[(\d+)\])?:? ?(.*)", re.S)
# RFC5424: <PRI>1 TIMESTAMP HOST APP PROCID MSGID SD MSG
_RFC5424 = re.compile(
    rb"<(\d{1,3})>1 (\S+) (\S+) (\S+) (\S+) (\S+) "
    rb"(-|(?:\[(?:[^\]\\]|\\.)*\])+) ?(.*)", re.S)


def parse_syslog(data: bytes) -> Optional[Dict[bytes, bytes]]:
    m = _RFC5424.fullmatch(data)
    if m:
        pri = int(m.group(1))
        return {
            b"facility": _FACILITIES[min(pri >> 3, 23)].encode(),
            b"severity": _SEVERITIES[pri & 7].encode(),
            b"timestamp": m.group(2),
            b"hostname": m.group(3),
            b"program": m.group(4),
            b"procid": m.group(5),
            b"msgid": m.group(6),
            b"content": m.group(8),
        }
    m = _RFC3164.fullmatch(data)
    if m:
        pri = int(m.group(1))
        out = {
            b"facility": _FACILITIES[min(pri >> 3, 23)].encode(),
            b"severity": _SEVERITIES[pri & 7].encode(),
            b"timestamp": m.group(2),
            b"hostname": m.group(3),
            b"program": m.group(4),
            b"content": m.group(6),
        }
        if m.group(5):
            out[b"pid"] = m.group(5)
        return out
    return None


class SyslogServer:
    def __init__(self, address: str, protocol: str, queue_key: int,
                 process_queue_manager, max_batch: int = 512):
        host, _, port = address.rpartition(":")
        self.host = host or "0.0.0.0"
        self.port = int(port)
        self.protocol = protocol
        self.queue_key = queue_key
        self.pqm = process_queue_manager
        self.max_batch = max_batch
        self._threads: List[threading.Thread] = []
        self._running = False
        self._udp_sock: Optional[socket.socket] = None
        self._tcp_sock: Optional[socket.socket] = None
        self._pending: List[bytes] = []
        self._pending_lock = threading.Lock()
        self._last_flush = time.monotonic()

    def start(self) -> bool:
        self._running = True
        try:
            if self.protocol in ("udp", "both"):
                self._udp_sock = socket.socket(socket.AF_INET,
                                               socket.SOCK_DGRAM)
                self._udp_sock.bind((self.host, self.port))
                self._udp_sock.settimeout(0.2)
                t = threading.Thread(target=self._udp_loop, daemon=True,
                                     name="syslog-udp")
                t.start()
                self._threads.append(t)
            if self.protocol in ("tcp", "both"):
                self._tcp_sock = socket.socket(socket.AF_INET,
                                               socket.SOCK_STREAM)
                self._tcp_sock.setsockopt(socket.SOL_SOCKET,
                                          socket.SO_REUSEADDR, 1)
                self._tcp_sock.bind((self.host, self.port))
                self._tcp_sock.listen(16)
                self._tcp_sock.settimeout(0.2)
                t = threading.Thread(target=self._tcp_loop, daemon=True,
                                     name="syslog-tcp")
                t.start()
                self._threads.append(t)
        except OSError as e:
            log.error("syslog bind %s:%d failed: %s", self.host, self.port, e)
            self.stop()
            return False
        t = threading.Thread(target=self._flush_loop, daemon=True,
                             name="syslog-flush")
        t.start()
        self._threads.append(t)
        return True

    def stop(self) -> None:
        self._running = False
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
        for sock in (self._udp_sock, self._tcp_sock):
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        self._flush()

    # -- receive ------------------------------------------------------------

    def _udp_loop(self) -> None:
        while self._running:
            try:
                data, _ = self._udp_sock.recvfrom(65536)
            except socket.timeout:
                continue
            except OSError:
                return
            if data:
                self._enqueue(data.rstrip(b"\n"))

    def _tcp_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._tcp_sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._tcp_conn, args=(conn,),
                             daemon=True).start()

    def _tcp_conn(self, conn: socket.socket) -> None:
        conn.settimeout(1.0)
        buf = bytearray()
        try:
            while self._running:
                try:
                    chunk = conn.recv(65536)
                except socket.timeout:
                    continue
                if not chunk:
                    break
                buf += chunk
                while True:
                    nl = buf.find(b"\n")
                    if nl < 0:
                        break
                    line = bytes(buf[:nl])
                    del buf[: nl + 1]
                    if line:
                        self._enqueue(line)
        except OSError:
            pass
        finally:
            if buf:
                self._enqueue(bytes(buf))
            try:
                conn.close()
            except OSError:
                pass

    # -- batching -----------------------------------------------------------

    def _enqueue(self, message: bytes) -> None:
        with self._pending_lock:
            self._pending.append(message)
            full = len(self._pending) >= self.max_batch
        if full:
            self._flush()

    def _flush_loop(self) -> None:
        while self._running:
            time.sleep(0.2)
            if time.monotonic() - self._last_flush >= 1.0:
                self._flush()

    def _flush(self) -> None:
        with self._pending_lock:
            pending, self._pending = self._pending, []
            self._last_flush = time.monotonic()
        if not pending or self.pqm is None:
            return
        group = PipelineEventGroup()
        sb = group.source_buffer
        now = int(time.time())
        for raw in pending:
            ev = group.add_log_event(now)
            fields = parse_syslog(raw)
            if fields is None:
                ev.set_content(b"content", sb.copy_string(raw))
            else:
                for k, v in fields.items():
                    ev.set_content(sb.copy_string(k), sb.copy_string(v))
        group.set_tag(b"__source__", b"syslog")
        self.pqm.push_queue(self.queue_key, group)


class InputSyslog(Input):
    name = "input_syslog"

    def __init__(self) -> None:
        super().__init__()
        self.server: Optional[SyslogServer] = None

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self._address = config.get("Address", "0.0.0.0:5140")
        self._protocol = config.get("Protocol", "udp").lower()
        host, sep, port = self._address.rpartition(":")
        if not sep or not port.isdigit():
            log.error("input_syslog Address must be host:port, got %r",
                      self._address)
            return False
        return self._protocol in ("udp", "tcp", "both")

    def start(self) -> bool:
        self.server = SyslogServer(self._address, self._protocol,
                                   self.context.process_queue_key,
                                   self.context.process_queue_manager)
        return self.server.start()

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        if self.server:
            self.server.stop()
            self.server = None
        return True
