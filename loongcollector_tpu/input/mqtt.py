"""input_mqtt — MQTT 3.1.1 subscriber over the public wire protocol.

Reference: plugins/input/mqtt/ (paho client). No MQTT library in this
image, so the input speaks the protocol directly: CONNECT/CONNACK,
SUBSCRIBE/SUBACK, PUBLISH receive (QoS 0 and 1 — PUBACK sent), PINGREQ
keepalive. Each PUBLISH becomes one event (topic + payload).
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger

log = get_logger("mqtt")

CONNECT, CONNACK, PUBLISH, PUBACK = 1, 2, 3, 4
SUBSCRIBE, SUBACK, PINGREQ, PINGRESP, DISCONNECT = 8, 9, 12, 13, 14


def _mqtt_str(s: bytes) -> bytes:
    return struct.pack(">H", len(s)) + s


def _remaining_len(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        out.append(b | 0x80 if n else b)
        if not n:
            return bytes(out)


def _read_packet(sock: socket.socket):
    """Returns (packet_type, flags, payload) or None on EOF."""
    h = sock.recv(1)
    if not h:
        return None
    ptype, flags = h[0] >> 4, h[0] & 0x0F
    mult, n = 1, 0
    while True:
        b = sock.recv(1)
        if not b:
            return None
        n += (b[0] & 0x7F) * mult
        if not b[0] & 0x80:
            break
        mult *= 128
    payload = b""
    while len(payload) < n:
        chunk = sock.recv(n - len(payload))
        if not chunk:
            return None
        payload += chunk
    return ptype, flags, payload


class MQTTSubscriber:
    def __init__(self, host: str, port: int, topics: List[str],
                 client_id: str = "loongcollector-tpu",
                 username: str = "", password: str = "",
                 keepalive: int = 30, on_message=None):
        self.host, self.port = host, port
        self.topics = topics
        self.client_id = client_id
        self.username, self.password = username, password
        self.keepalive = keepalive
        self.on_message = on_message
        self._sock: Optional[socket.socket] = None
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._pkt_id = 0

    def _connect(self) -> None:
        sock = socket.create_connection((self.host, self.port), timeout=10)
        flags = 0x02                                  # clean session
        payload = _mqtt_str(self.client_id.encode())
        if self.username:
            flags |= 0x80
            payload += _mqtt_str(self.username.encode())
            if self.password:
                flags |= 0x40
                payload += _mqtt_str(self.password.encode())
        var = (_mqtt_str(b"MQTT") + b"\x04" + bytes([flags])
               + struct.pack(">H", self.keepalive))
        pkt = bytes([CONNECT << 4]) + _remaining_len(
            len(var) + len(payload)) + var + payload
        sock.sendall(pkt)
        resp = _read_packet(sock)
        if resp is None or resp[0] != CONNACK or resp[2][1] != 0:
            raise OSError(f"MQTT CONNACK refused: {resp}")
        # subscribe (QoS 1 requested; broker may grant 0)
        self._pkt_id += 1
        sub_payload = b"".join(_mqtt_str(t.encode()) + b"\x01"
                               for t in self.topics)
        var = struct.pack(">H", self._pkt_id)
        sock.sendall(bytes([(SUBSCRIBE << 4) | 0x02])
                     + _remaining_len(len(var) + len(sub_payload))
                     + var + sub_payload)
        resp = _read_packet(sock)
        if resp is None or resp[0] != SUBACK:
            raise OSError(f"MQTT SUBACK missing: {resp}")
        sock.settimeout(self.keepalive / 2 if self.keepalive else 15)
        self._sock = sock

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._run, name="mqtt",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        backoff = 1.0
        while self._running:
            try:
                self._connect()
                backoff = 1.0
                self._loop()
            except OSError as e:
                if self._running:
                    log.warning("mqtt connection lost: %s", e)
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 30.0)

    def _loop(self) -> None:
        assert self._sock is not None
        while self._running:
            try:
                pkt = _read_packet(self._sock)
            except socket.timeout:
                self._sock.sendall(bytes([PINGREQ << 4, 0]))
                continue
            if pkt is None:
                raise OSError("broker closed connection")
            ptype, flags, payload = pkt
            if ptype == PUBLISH:
                try:
                    qos = (flags >> 1) & 3
                    tlen = struct.unpack(">H", payload[:2])[0]
                    topic = payload[2:2 + tlen]
                    pos = 2 + tlen
                    if qos > 0:
                        pid = struct.unpack(">H",
                                            payload[pos:pos + 2])[0]
                        pos += 2
                        self._sock.sendall(bytes([PUBACK << 4, 2])
                                           + struct.pack(">H", pid))
                except (struct.error, IndexError) as e:
                    # stream desync: reconnect rather than die
                    raise OSError(f"malformed PUBLISH: {e}") from e
                if self.on_message is not None:
                    self.on_message(topic, payload[pos:])
            elif ptype == PINGRESP:
                pass

    def stop(self) -> None:
        self._running = False
        sock = self._sock
        if sock is not None:
            try:
                sock.sendall(bytes([DISCONNECT << 4, 0]))
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
            self._sock = None
        if self._thread is not None:
            self._thread.join(timeout=3)


class InputMQTT(Input):
    name = "input_mqtt"

    def __init__(self) -> None:
        super().__init__()
        self._client: Optional[MQTTSubscriber] = None

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        addr = config.get("Address", "")
        host, sep, port = addr.rpartition(":")
        if not sep or not port.isdigit():
            log.error("input_mqtt Address must be host:port, got %r", addr)
            return False
        self._host, self._port = host, int(port)
        self.topics = list(config.get("Topics", []))
        self.username = config.get("Username", "")
        self.password = config.get("Password", "")
        return bool(self.topics)

    def _on_message(self, topic: bytes, payload: bytes) -> None:
        pqm = self.context.process_queue_manager
        if pqm is None:
            return
        group = PipelineEventGroup()
        sb = group.source_buffer
        ev = group.add_log_event(int(time.time()))
        ev.set_content(b"topic", sb.copy_string(topic))
        ev.set_content(b"content", sb.copy_string(payload))
        group.set_tag(b"__source__", b"mqtt")
        pqm.push_queue(self.context.process_queue_key, group)

    def start(self) -> bool:
        self._client = MQTTSubscriber(
            self._host, self._port, self.topics,
            username=self.username, password=self.password,
            on_message=self._on_message)
        self._client.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        if self._client is not None:
            self._client.stop()
            self._client = None
        return True
