"""service_kubernetes_meta — K8s entity + entity-link collection.

Reference: plugins/input/kubernetesmetav2/ (service_meta.go: per-kind
entity switches and link switches whose config VALUE is the relation
type; meta_collector.go:419-451: the reserved __domain__/__entity_type__
/__entity_id__/__method__/observed-time field contract;
meta_collector_core.go: per-kind custom fields) and kubernetesmetav1
(periodic full listing — this implementation's collection model: list
snapshots + diff instead of informers, producing the same
Add/Update/Delete methods).

Transport rides the same injectable apiserver client as the container
metadata cache (container_manager.K8sMetadata), so tests run against a
local fake apiserver.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger

log = get_logger("k8s_meta")

# kind → list path (cluster-scope list; namespaced objects carry their
# namespace in metadata)
_KIND_PATHS = {
    "Pod": "/api/v1/pods",
    "Node": "/api/v1/nodes",
    "Service": "/api/v1/services",
    "Namespace": "/api/v1/namespaces",
    "Configmap": "/api/v1/configmaps",
    "PersistentVolume": "/api/v1/persistentvolumes",
    "PersistentVolumeClaim": "/api/v1/persistentvolumeclaims",
    "Deployment": "/apis/apps/v1/deployments",
    "ReplicaSet": "/apis/apps/v1/replicasets",
    "DaemonSet": "/apis/apps/v1/daemonsets",
    "StatefulSet": "/apis/apps/v1/statefulsets",
    "Job": "/apis/batch/v1/jobs",
    "CronJob": "/apis/batch/v1/cronjobs",
    "Ingress": "/apis/networking.k8s.io/v1/ingresses",
    "StorageClass": "/apis/storage.k8s.io/v1/storageclasses",
}
# canonical kind spelling for entity types/keys (config switch → kind)
_KIND_NAMES = {k: ("ConfigMap" if k == "Configmap" else k)
               for k in _KIND_PATHS}

# ownerReferences-derived links: child kind → (owner kind, switch attr)
_OWNER_LINKS = [
    ("Pod", "ReplicaSet", "ReplicaSet2Pod"),
    ("Pod", "StatefulSet", "StatefulSet2Pod"),
    ("Pod", "DaemonSet", "DaemonSet2Pod"),
    ("Pod", "Job", "Job2Pod"),
    ("ReplicaSet", "Deployment", "Deployment2ReplicaSet"),
    ("Job", "CronJob", "CronJob2Job"),
]

_NS_LINKS = [
    ("Pod", "Namespace2Pod"), ("Service", "Namespace2Service"),
    ("Deployment", "Namespace2Deployment"),
    ("DaemonSet", "Namespace2DaemonSet"),
    ("StatefulSet", "Namespace2StatefulSet"),
    ("Configmap", "Namespace2Configmap"), ("Job", "Namespace2Job"),
    ("CronJob", "Namespace2CronJob"),
    ("PersistentVolumeClaim", "Namespace2PersistentVolumeClaim"),
    ("Ingress", "Namespace2Ingress"),
]


def _meta(obj: dict) -> dict:
    return obj.get("metadata", {}) or {}


def _jdump(v) -> str:
    return json.dumps(v, separators=(",", ":"), ensure_ascii=False)


class ServiceK8sMeta(Input):
    """service_kubernetes_meta: entity switches (Pod/Node/Service/...),
    EnableLabels/EnableAnnotations, link switches whose value is the
    relation type (e.g. ``Node2Pod: runs``)."""

    name = "service_kubernetes_meta"

    def __init__(self) -> None:
        super().__init__()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # entity key → first_observed_time
        self._first_seen: Dict[str, int] = {}
        self._last_keys: set = set()

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.interval = int(config.get("Interval", 60))
        self.kinds = [k for k in _KIND_PATHS if bool(config.get(k))]
        self.container_entities = bool(config.get("Container"))
        self.enable_labels = bool(config.get("EnableLabels", False))
        self.enable_annotations = bool(config.get("EnableAnnotations", False))
        self.cluster_id = str(config.get("ClusterID", ""))
        self.cluster_name = str(config.get("ClusterName", ""))
        self.cluster_region = str(config.get("ClusterRegion", ""))
        self.domain = str(config.get("Domain", "k8s"))
        self.links = {key: str(val) for key, val in config.items()
                      if "2" in key and isinstance(val, str) and val}
        # tests / out-of-cluster: explicit apiserver endpoint
        self._endpoint = config.get("Endpoint")  # {Scheme,Host,Port,Token}
        return bool(self.kinds)

    # -- transport -----------------------------------------------------------

    def _client(self):
        from ..container_manager import K8sMetadata
        k = K8sMetadata()
        if self._endpoint:
            k.configure(str(self._endpoint.get("Scheme", "http")),
                        str(self._endpoint.get("Host", "127.0.0.1")),
                        int(self._endpoint.get("Port", 0)),
                        str(self._endpoint.get("Token", "")))
        return k

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> bool:
        client = self._client()
        if not client.available():
            log.warning("service_kubernetes_meta: no apiserver available; "
                        "input idles")
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, args=(client,),
                                        daemon=True, name="k8s-meta")
        self._thread.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=3)
            self._thread = None
        return True

    def _run(self, client) -> None:
        while not self._stop.is_set():
            try:
                self.collect_once(client)
            except Exception:  # noqa: BLE001 — apiserver flap must not kill it
                log.exception("k8s meta collection failed")
            self._stop.wait(self.interval)

    # -- collection ----------------------------------------------------------

    def collect_once(self, client) -> Optional[PipelineEventGroup]:
        if not client.available():
            return None
        snapshots: Dict[str, List[dict]] = {}
        failed_kinds: set = set()
        for kind in self.kinds:
            try:
                data = client._get_json(_KIND_PATHS[kind])
            except (OSError, ValueError):
                data = None
            if data is None:
                # transient apiserver failure: an unknown state must not
                # read as "everything of this kind was deleted"
                failed_kinds.add(_KIND_NAMES.get(kind, kind))
                snapshots[kind] = []
            else:
                snapshots[kind] = data.get("items", []) or []

        now = int(time.time())
        group = PipelineEventGroup()
        seen: set = set()
        for kind in self.kinds:
            for obj in snapshots[kind]:
                self._emit_entity(group, kind, obj, now, seen)
        self._emit_links(group, snapshots, now)
        if any(k.startswith("Cluster2") for k in self.links):
            self._emit_cluster(group, now)
        # disappeared objects → Delete entities (skip kinds whose list
        # failed this round — their objects may well still exist)
        carried: set = set()
        for key in self._last_keys - seen:
            kind, ns, name = key.split("|", 2)
            if kind in failed_kinds or \
                    (kind == "container" and "Pod" in failed_kinds):
                carried.add(key)
                continue
            ev = group.add_log_event(now)
            self._common_entity_fields(ev, group, kind, ns, name, "Delete",
                                       self._first_seen.get(key, now), now)
            self._first_seen.pop(key, None)
        self._last_keys = seen | carried
        if not len(group):
            return None
        group.set_tag(b"__source__", b"k8s_meta")
        pqm = self.context.process_queue_manager if self.context else None
        if pqm is not None:
            pqm.push_queue(self.context.process_queue_key, group)
        return group

    # -- entity emission -----------------------------------------------------

    def _gen_key(self, kind: str, namespace: str, name: str) -> str:
        raw = (self.cluster_id + kind + namespace + name).encode()
        return hashlib.md5(raw).hexdigest()

    def _type_key(self, kind: str) -> str:
        return f"{self.domain}.{kind.lower()}"

    def _put(self, ev, group, key: str, val: str) -> None:
        sb = group.source_buffer
        ev.set_content(sb.copy_string(key.encode()),
                       sb.copy_string(str(val).encode()))

    def _common_entity_fields(self, ev, group, kind: str, namespace: str,
                              name: str, method: str, first: int,
                              now: int, creation: str = "") -> None:
        kindn = _KIND_NAMES.get(kind, kind)
        self._put(ev, group, "__domain__", self.domain)
        self._put(ev, group, "__entity_type__", self._type_key(kindn))
        self._put(ev, group, "__entity_id__",
                  self._gen_key(kindn, namespace, name))
        self._put(ev, group, "__method__", method)
        self._put(ev, group, "__first_observed_time__", str(first))
        self._put(ev, group, "__last_observed_time__", str(now))
        self._put(ev, group, "__keep_alive_seconds__",
                  str(self.interval * 2))
        self._put(ev, group, "__category__", "entity")
        self._put(ev, group, "cluster_id", self.cluster_id)
        self._put(ev, group, "kind", kindn)
        self._put(ev, group, "name", name)
        if creation:
            self._put(ev, group, "create_time", creation)

    def _emit_entity(self, group, kind: str, obj: dict, now: int,
                     seen: set) -> None:
        meta = _meta(obj)
        ns = meta.get("namespace", "")
        name = meta.get("name", "")
        key = f"{_KIND_NAMES.get(kind, kind)}|{ns}|{name}"
        method = "Update" if key in self._first_seen else "Add"
        first = self._first_seen.setdefault(key, now)
        seen.add(key)
        ev = group.add_log_event(now)
        self._common_entity_fields(ev, group, kind, ns, name, method, first,
                                   now, meta.get("creationTimestamp", ""))
        if ns:
            self._put(ev, group, "namespace", ns)
        if self.enable_labels:
            self._put(ev, group, "labels", _jdump(meta.get("labels") or {}))
        if self.enable_annotations:
            self._put(ev, group, "annotations",
                      _jdump(meta.get("annotations") or {}))
        spec = obj.get("spec", {}) or {}
        status = obj.get("status", {}) or {}
        if kind == "Pod":
            self._put(ev, group, "status", status.get("phase", ""))
            self._put(ev, group, "instance_ip", status.get("podIP", ""))
            containers = [{"name": c.get("name", ""),
                           "image": c.get("image", "")}
                          for c in spec.get("containers", []) or []]
            self._put(ev, group, "containers", _jdump(containers))
            if self.container_entities:
                self._emit_containers(group, obj, now, seen)
        elif kind == "Node":
            addrs = {a.get("type"): a.get("address")
                     for a in status.get("addresses", []) or []}
            self._put(ev, group, "internal_ip",
                      addrs.get("InternalIP", ""))
            self._put(ev, group, "hostname", addrs.get("Hostname", ""))
            info = status.get("nodeInfo", {}) or {}
            self._put(ev, group, "os", info.get("osImage", ""))
            self._put(ev, group, "kubelet_version",
                      info.get("kubeletVersion", ""))
        elif kind == "Service":
            self._put(ev, group, "cluster_ip", spec.get("clusterIP", ""))
            self._put(ev, group, "type", spec.get("type", ""))
            self._put(ev, group, "selector",
                      _jdump(spec.get("selector") or {}))
        elif kind in ("Deployment", "ReplicaSet", "StatefulSet"):
            self._put(ev, group, "replicas",
                      str(spec.get("replicas", "")))
            self._put(ev, group, "ready_replicas",
                      str(status.get("readyReplicas", 0)))
        elif kind == "Job":
            self._put(ev, group, "succeeded", str(status.get("succeeded", 0)))
        elif kind == "CronJob":
            self._put(ev, group, "schedule", spec.get("schedule", ""))
        elif kind == "PersistentVolumeClaim":
            self._put(ev, group, "volume_name", spec.get("volumeName", ""))
            self._put(ev, group, "phase", status.get("phase", ""))
        elif kind == "PersistentVolume":
            self._put(ev, group, "phase", status.get("phase", ""))
            self._put(ev, group, "storage_class",
                      spec.get("storageClassName", ""))

    def _emit_containers(self, group, pod: dict, now: int,
                         seen: set) -> None:
        meta = _meta(pod)
        ns = meta.get("namespace", "")
        pod_name = meta.get("name", "")
        for c in (pod.get("spec", {}) or {}).get("containers", []) or []:
            ev = group.add_log_event(now)
            cname = c.get("name", "")
            # containers join the same first-seen/last-keys diff as the
            # kind-level snapshot: Add on first sight, Delete when the
            # owning pod's list no longer contains them
            key = f"container|{ns}|{pod_name + cname}"
            method = "Update" if key in self._first_seen else "Add"
            first = self._first_seen.setdefault(key, now)
            seen.add(key)
            self._common_entity_fields(ev, group, "container", ns,
                                       pod_name + cname, method, first,
                                       now)
            self._put(ev, group, "name", cname)
            self._put(ev, group, "pod_name", pod_name)
            self._put(ev, group, "pod_namespace", ns)
            self._put(ev, group, "image", c.get("image", ""))
            res = c.get("resources", {}) or {}
            for field, source in (("cpu_request", "requests"),
                                  ("cpu_limit", "limits")):
                self._put(ev, group, field,
                          (res.get(source) or {}).get("cpu", ""))
            for field, source in (("memory_request", "requests"),
                                  ("memory_limit", "limits")):
                self._put(ev, group, field,
                          (res.get(source) or {}).get("memory", ""))
            if self.links.get("Pod2Container"):
                self._emit_link(group, now, "Pod", ns, pod_name,
                                "container", ns, pod_name + cname,
                                self.links["Pod2Container"])

    # -- link emission -------------------------------------------------------

    def _emit_link(self, group, now: int, src_kind: str, src_ns: str,
                   src_name: str, dst_kind: str, dst_ns: str, dst_name: str,
                   relation: str, src_domain: str = "",
                   dst_domain: str = "") -> None:
        ev = group.add_log_event(now)
        self._put(ev, group, "__src_domain__", src_domain or self.domain)
        self._put(ev, group, "__src_entity_type__", self._type_key(src_kind))
        self._put(ev, group, "__src_entity_id__",
                  self._gen_key(src_kind, src_ns, src_name))
        self._put(ev, group, "__dest_domain__", dst_domain or self.domain)
        self._put(ev, group, "__dest_entity_type__", self._type_key(dst_kind))
        self._put(ev, group, "__dest_entity_id__",
                  self._gen_key(dst_kind, dst_ns, dst_name))
        self._put(ev, group, "__relation_type__", relation)
        self._put(ev, group, "__method__", "Update")
        self._put(ev, group, "__first_observed_time__", str(now))
        self._put(ev, group, "__last_observed_time__", str(now))
        self._put(ev, group, "__keep_alive_seconds__",
                  str(self.interval * 2))
        self._put(ev, group, "__category__", "entity_link")

    def _emit_links(self, group, snaps: Dict[str, List[dict]],
                    now: int) -> None:
        links = self.links
        # Node → Pod placement
        if links.get("Node2Pod"):
            for pod in snaps.get("Pod", []):
                node = (pod.get("spec", {}) or {}).get("nodeName", "")
                if node:
                    m = _meta(pod)
                    self._emit_link(group, now, "Node", "", node, "Pod",
                                    m.get("namespace", ""),
                                    m.get("name", ""), links["Node2Pod"])
        # ownerReferences chains
        for child_kind, owner_kind, switch in _OWNER_LINKS:
            rel = links.get(switch)
            if not rel:
                continue
            for obj in snaps.get(child_kind, []):
                m = _meta(obj)
                for ref in m.get("ownerReferences", []) or []:
                    if ref.get("kind") == owner_kind:
                        self._emit_link(group, now, owner_kind,
                                        m.get("namespace", ""),
                                        ref.get("name", ""), child_kind,
                                        m.get("namespace", ""),
                                        m.get("name", ""), rel)
        # Deployment → Pod transitively via ReplicaSet name prefix
        if links.get("Deployment2Pod"):
            rs_owner = {}
            for rs in snaps.get("ReplicaSet", []):
                m = _meta(rs)
                for ref in m.get("ownerReferences", []) or []:
                    if ref.get("kind") == "Deployment":
                        rs_owner[(m.get("namespace", ""),
                                  m.get("name", ""))] = ref.get("name", "")
            for pod in snaps.get("Pod", []):
                m = _meta(pod)
                for ref in m.get("ownerReferences", []) or []:
                    dep = rs_owner.get((m.get("namespace", ""),
                                        ref.get("name", "")))
                    if ref.get("kind") == "ReplicaSet" and dep:
                        self._emit_link(group, now, "Deployment",
                                        m.get("namespace", ""), dep, "Pod",
                                        m.get("namespace", ""),
                                        m.get("name", ""),
                                        links["Deployment2Pod"])
        # Service → Pod via label selectors
        if links.get("Service2Pod"):
            for svc in snaps.get("Service", []):
                sel = (svc.get("spec", {}) or {}).get("selector") or {}
                if not sel:
                    continue
                sm = _meta(svc)
                for pod in snaps.get("Pod", []):
                    pm = _meta(pod)
                    if pm.get("namespace") != sm.get("namespace"):
                        continue
                    plabels = pm.get("labels") or {}
                    if all(plabels.get(k) == v for k, v in sel.items()):
                        self._emit_link(group, now, "Service",
                                        sm.get("namespace", ""),
                                        sm.get("name", ""), "Pod",
                                        pm.get("namespace", ""),
                                        pm.get("name", ""),
                                        links["Service2Pod"])
        # Ingress → Service backends
        if links.get("Ingress2Service"):
            for ing in snaps.get("Ingress", []):
                im = _meta(ing)
                for rule in (ing.get("spec", {}) or {}).get("rules", []) or []:
                    paths = ((rule.get("http") or {}).get("paths") or [])
                    for p in paths:
                        svc = ((p.get("backend") or {})
                               .get("service") or {}).get("name", "")
                        if svc:
                            self._emit_link(group, now, "Ingress",
                                            im.get("namespace", ""),
                                            im.get("name", ""), "Service",
                                            im.get("namespace", ""), svc,
                                            links["Ingress2Service"])
        # Pod → PVC / ConfigMap volumes
        for switch, vol_key, vol_name_key, dst_kind in (
                ("Pod2PersistentVolumeClaim", "persistentVolumeClaim",
                 "claimName", "PersistentVolumeClaim"),
                ("Pod2ConfigMap", "configMap", "name", "ConfigMap")):
            rel = links.get(switch)
            if not rel:
                continue
            for pod in snaps.get("Pod", []):
                m = _meta(pod)
                for vol in (pod.get("spec", {}) or {}).get("volumes", []) or []:
                    ref = vol.get(vol_key) or {}
                    target = ref.get(vol_name_key, "")
                    if target:
                        self._emit_link(group, now, "Pod",
                                        m.get("namespace", ""),
                                        m.get("name", ""), dst_kind,
                                        m.get("namespace", ""), target, rel)
        # Namespace → contained kinds
        for kind, switch in _NS_LINKS:
            rel = links.get(switch)
            if not rel:
                continue
            for obj in snaps.get(kind, []):
                m = _meta(obj)
                ns = m.get("namespace", "")
                if ns:
                    self._emit_link(group, now, "Namespace", "", ns,
                                    _KIND_NAMES.get(kind, kind), ns,
                                    m.get("name", ""), rel)
        # Cluster → Node / Namespace
        for kind, switch in (("Node", "Cluster2Node"),
                             ("Namespace", "Cluster2Namespace"),
                             ("PersistentVolume", "Cluster2PersistentVolume"),
                             ("StorageClass", "Cluster2StorageClass")):
            rel = links.get(switch)
            if not rel:
                continue
            for obj in snaps.get(kind, []):
                m = _meta(obj)
                self._emit_link(group, now, "cluster", "", "", kind, "",
                                m.get("name", ""), rel)

    def _emit_cluster(self, group, now: int) -> None:
        ev = group.add_log_event(now)
        self._common_entity_fields(ev, group, "cluster", "", "", "Update",
                                   now, now)
        self._put(ev, group, "cluster_name", self.cluster_name)
        self._put(ev, group, "region_id", self.cluster_region)
