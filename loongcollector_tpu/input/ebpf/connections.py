"""Connection tracking + L7 request/response aggregation.

Reference: core/ebpf/plugin/network_observer/ConnectionManager.cpp (conn
table keyed by the kernel's connection id, fed by ctrl/data/stats events,
bounded size, idle GC via Iterations()) and NetworkObserverManager.cpp
(pairs request/response records per connection, converts them to spans,
logs and APP-level rollup metrics).

Mapping onto the v2 driver ABI: NETWORK_OBSERVE events with call_name
`conn_connect` / `conn_accept` / `conn_close` are control events,
`conn_stats` carries byte counters in flags, and payload-bearing events
are data events.  The manager:

* tracks per-(pid, fd) connection state (tuple, role, byte counters);
* sniffs L7 protocol per connection (sticky once detected);
* matches each response to the oldest outstanding request (FIFO — HTTP/1.x
  and the RESP/MySQL protocols answer in order) → one SPAN-shaped record
  with latency;
* aggregates rollup metrics per (protocol, remote, status-class):
  request count, error count, latency sum/max, bytes in/out — the
  observer's metrics stream.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from .protocol_http import parse_http
from .protocol_mysql import parse_mysql
from .protocol_redis import parse_redis

MAX_CONNECTIONS = 5000           # reference ConnectionManager default
MAX_PENDING_REQS = 64            # per-connection outstanding requests
IDLE_CLOSE_S = 120.0


def sniff_l7(payload: bytes):
    """Protocol detection order mirrors the reference's protocol matrix
    (core/ebpf/protocol/): HTTP (self-describing first line), then RESP
    (typed first byte), then MySQL (length-framed packets)."""
    rec = parse_http(payload)
    if rec is not None:
        return "http", rec
    rec = parse_redis(payload)
    if rec is not None:
        return "redis", rec
    rec = parse_mysql(payload)
    if rec is not None:
        return "mysql", rec
    return "raw", None


@dataclass
class L7Span:
    """One matched request/response exchange."""

    protocol: str
    pid: int
    ktime: int
    local_addr: str
    remote_addr: str
    start_ns: int
    end_ns: int
    name: str = ""           # http: METHOD path; redis/mysql: command
    status: str = "ok"       # ok / error
    status_code: str = ""    # http status / mysql error code
    attributes: Dict[str, str] = field(default_factory=dict)

    @property
    def latency_ns(self) -> int:
        return max(0, self.end_ns - self.start_ns)


@dataclass
class _Conn:
    pid: int
    fd: int
    ktime: int = 0
    local_addr: str = ""
    remote_addr: str = ""
    role: str = ""                   # client (connect) / server (accept)
    protocol: str = ""               # sticky after first successful sniff
    rx_bytes: int = 0
    tx_bytes: int = 0
    reported_rx: int = 0      # bytes already credited to a rollup cell
    reported_tx: int = 0
    last_seen: float = 0.0
    pending: Deque[Tuple[int, object, str]] = field(default_factory=deque)
    # (start_ns, request record, name)


class ConnStats:
    """Rollup metric cell (reference app-level metrics)."""

    __slots__ = ("count", "errors", "latency_sum_ns", "latency_max_ns",
                 "rx_bytes", "tx_bytes")

    def __init__(self) -> None:
        self.count = 0
        self.errors = 0
        self.latency_sum_ns = 0
        self.latency_max_ns = 0
        self.rx_bytes = 0
        self.tx_bytes = 0


class ConnectionManager:
    def __init__(self, max_connections: int = MAX_CONNECTIONS):
        self.max_connections = max_connections
        self._conns: Dict[Tuple[int, int], _Conn] = {}
        self._lock = threading.Lock()
        self._spans: List[L7Span] = []
        self._rollup: Dict[Tuple[str, str, str], ConnStats] = {}
        self.dropped_conns = 0
        self.unmatched_responses = 0

    # -- event intake -------------------------------------------------------

    def accept_ctrl(self, raw) -> None:
        """conn_connect / conn_accept / conn_close control events."""
        key = (raw.pid, raw.fd)
        with self._lock:
            if raw.call_name == "conn_close":
                self._conns.pop(key, None)
                return
            conn = self._conns.get(key)
            if conn is None:
                conn = self._new_conn_locked(raw)
            conn.role = ("server" if raw.call_name == "conn_accept"
                         else "client")
            conn.local_addr = raw.local_addr or conn.local_addr
            conn.remote_addr = raw.remote_addr or conn.remote_addr
            conn.last_seen = time.monotonic()

    def accept_stats(self, raw) -> None:
        """conn_stats: flags carries rx bytes, fd-adjacent counter in
        payload_len-free events; tx in the high half when present."""
        key = (raw.pid, raw.fd)
        with self._lock:
            conn = self._conns.get(key)
            if conn is None:
                conn = self._new_conn_locked(raw)
            rx = raw.flags & 0xFFFF
            tx = (raw.flags >> 16) & 0xFFFF
            conn.rx_bytes += rx
            conn.tx_bytes += tx
            conn.last_seen = time.monotonic()

    def accept_data(self, raw, proto: str = "",
                    rec=None) -> Optional[L7Span]:
        """Payload-bearing data event: match request/response, emit a span
        when an exchange completes.  The caller may pass an already-sniffed
        (proto, rec) so the payload is parsed exactly once per event."""
        key = (raw.pid, raw.fd)
        with self._lock:
            conn = self._conns.get(key)
            if conn is None:
                conn = self._new_conn_locked(raw)
            conn.last_seen = time.monotonic()
            if raw.local_addr:
                conn.local_addr = raw.local_addr
            if raw.remote_addr:
                conn.remote_addr = raw.remote_addr
            if raw.direction == "ingress":
                conn.rx_bytes += len(raw.payload)
            else:
                conn.tx_bytes += len(raw.payload)

            if rec is None:
                proto, rec = sniff_l7(raw.payload)
            if rec is None:
                return None
            if not conn.protocol:
                conn.protocol = proto
            elif proto != conn.protocol:
                # mid-stream continuation bytes can sniff differently;
                # the connection's protocol is sticky
                return None

            if rec.kind == "request":
                if len(conn.pending) >= MAX_PENDING_REQS:
                    conn.pending.popleft()   # shed oldest: bounded state
                name = self._request_name(proto, rec)
                conn.pending.append((raw.timestamp_ns, rec, name))
                return None

            # response: match the oldest outstanding request (in-order
            # protocols), or record an unmatched response
            if conn.pending:
                start_ns, req, name = conn.pending.popleft()
            else:
                self.unmatched_responses += 1
                start_ns, req, name = raw.timestamp_ns, None, ""
            span = self._build_span(conn, proto, req, rec, name,
                                    start_ns, raw.timestamp_ns, raw.ktime)
            self._spans.append(span)
            self._record_rollup(conn, span)
            return span

    # -- drain --------------------------------------------------------------

    def take_spans(self) -> List[L7Span]:
        with self._lock:
            spans, self._spans = self._spans, []
        return spans

    def take_rollup(self) -> Dict[Tuple[str, str, str], ConnStats]:
        with self._lock:
            roll, self._rollup = self._rollup, {}
        return roll

    def iterations(self) -> None:
        """Periodic GC (reference ConnectionManager::Iterations): drop idle
        connections so a leaky driver can't grow the table unbounded."""
        now = time.monotonic()
        with self._lock:
            for key, conn in list(self._conns.items()):
                if now - conn.last_seen > IDLE_CLOSE_S:
                    del self._conns[key]

    def connection_count(self) -> int:
        with self._lock:
            return len(self._conns)

    # -- internals ----------------------------------------------------------

    def _new_conn_locked(self, raw) -> _Conn:
        if len(self._conns) >= self.max_connections:
            # drop the least-recently-seen connection (bounded table)
            victim = min(self._conns.items(),
                         key=lambda kv: kv[1].last_seen)[0]
            del self._conns[victim]
            # connection-table eviction, not an event discard: no events
            # ride the evicted _Conn  # loonglint: disable=unledgered-drop
            self.dropped_conns += 1
        conn = _Conn(pid=raw.pid, fd=raw.fd, ktime=raw.ktime,
                     local_addr=raw.local_addr, remote_addr=raw.remote_addr,
                     last_seen=time.monotonic())
        self._conns[(raw.pid, raw.fd)] = conn
        return conn

    @staticmethod
    def _request_name(proto: str, rec) -> str:
        if proto == "http":
            return (rec.method.decode("utf-8", "replace") + " "
                    + rec.path.decode("utf-8", "replace"))
        cmd = getattr(rec, "command", b"") or b""
        if isinstance(cmd, bytes):
            cmd = cmd.decode("utf-8", "replace")
        return cmd

    def _build_span(self, conn: _Conn, proto: str, req, resp, name: str,
                    start_ns: int, end_ns: int, ktime: int) -> L7Span:
        status = "ok"
        code = ""
        attrs: Dict[str, str] = {}
        if proto == "http":
            code = str(resp.status)
            if resp.status >= 400:
                status = "error"
            if req is not None and req.host:
                attrs["host"] = req.host.decode("utf-8", "replace")
        elif proto == "redis":
            if getattr(resp, "error", b""):
                status = "error"
                attrs["error"] = resp.error.decode("utf-8", "replace")
        elif proto == "mysql":
            if getattr(resp, "error_code", 0):
                status = "error"
                code = str(resp.error_code)
                attrs["error"] = resp.error_message.decode(
                    "utf-8", "replace") if isinstance(
                        resp.error_message, bytes) else str(
                        resp.error_message)
            if req is not None and getattr(req, "sql", b""):
                sql = req.sql
                attrs["sql"] = (sql.decode("utf-8", "replace")
                                if isinstance(sql, bytes) else str(sql))
        return L7Span(protocol=proto, pid=conn.pid, ktime=ktime or conn.ktime,
                      local_addr=conn.local_addr,
                      remote_addr=conn.remote_addr, start_ns=start_ns,
                      end_ns=end_ns, name=name, status=status,
                      status_code=code, attributes=attrs)

    def _record_rollup(self, conn: _Conn, span: L7Span) -> None:
        key = (span.protocol, conn.remote_addr,
               span.status_code[:1] + "xx" if span.status_code else
               span.status)
        cell = self._rollup.get(key)
        if cell is None:
            cell = self._rollup[key] = ConnStats()
        cell.count += 1
        if span.status == "error":
            cell.errors += 1
        cell.latency_sum_ns += span.latency_ns
        cell.latency_max_ns = max(cell.latency_max_ns, span.latency_ns)
        # credit only the bytes since this connection last reported, so
        # concurrent connections accumulate instead of overwriting and a
        # long-lived connection is never double-counted across flushes
        cell.rx_bytes += conn.rx_bytes - conn.reported_rx
        cell.tx_bytes += conn.tx_bytes - conn.reported_tx
        conn.reported_rx = conn.rx_bytes
        conn.reported_tx = conn.tx_bytes
