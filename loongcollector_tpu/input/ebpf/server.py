"""EBPFServer + plugin managers.

Reference: core/ebpf/EBPFServer.h:73-100 (singleton InputRunner; poll thread
over the adapter) and core/ebpf/plugin/*/ managers:
  NetworkObserverManager — L7 parse (protocol/), connection enrichment
  ProcessSecurityManager / FileSecurityManager / NetworkSecurityManager
  (FileSecurityManager.cpp:217 pushes groups into process queues)
plus ProcessCacheManager enriching events with the process tree.

Events are batched per (source, pipeline): the manager accumulates raw
events briefly and flushes one event group — the columnar-friendly unit.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from ...models import PipelineEventGroup, SpanEvent
from ...pipeline.plugin.interface import Input, PluginContext
from ...utils.logger import get_logger
from .adapter import (EBPFAdapter, EventSource, RawKernelEvent, get_adapter)
from .connections import ConnectionManager, sniff_l7
from .proc_tree import ProcessTreeCache

log = get_logger("ebpf")

FLUSH_INTERVAL_S = 0.5
MAX_BATCH_EVENTS = 1024


class _SourceManager:
    """Per-source accumulation + flush (base of the reference's per-source
    plugin managers)."""

    def __init__(self, source: EventSource, server: "EBPFServer"):
        self.source = source
        self.server = server
        self.queue_key: Optional[int] = None
        self._pending: List[RawKernelEvent] = []
        self._lock = threading.Lock()
        self._last_flush = time.monotonic()

    def on_raw_event(self, ev: RawKernelEvent) -> None:
        with self._lock:
            self._pending.append(ev)
            should_flush = len(self._pending) >= MAX_BATCH_EVENTS
        if should_flush:
            self.flush()

    def maybe_flush(self) -> None:
        if time.monotonic() - self._last_flush >= FLUSH_INTERVAL_S:
            self.flush()

    # managers that accumulate state outside _pending (connection spans /
    # rollup cells) set this so their flush runs even with no raw events
    flush_when_empty = False

    def flush(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
            self._last_flush = time.monotonic()
        if (not pending and not self.flush_when_empty) \
                or self.queue_key is None:
            return
        group = self.build_group(pending)
        if group is not None and not group.empty():
            pqm = self.server.process_queue_manager
            if pqm is not None:
                pqm.push_queue(self.queue_key, group)

    def build_group(self, events: List[RawKernelEvent]
                    ) -> Optional[PipelineEventGroup]:  # pragma: no cover
        raise NotImplementedError


class NetworkObserverManager(_SourceManager):
    """L7 observer (reference NetworkObserverManager + ConnectionManager):
    control/stats events maintain the connection table; payload events emit
    the per-event log stream AND feed request/response matching, so each
    flush carries logs, completed-exchange SPANs (with latency) and the
    rollup metric cells — the observer's three output streams."""

    def __init__(self, source, server):
        super().__init__(source, server)
        self.connections = ConnectionManager()

    def on_raw_event(self, ev: RawKernelEvent) -> None:
        if ev.call_name in ("conn_connect", "conn_accept", "conn_close"):
            self.connections.accept_ctrl(ev)
            return
        if ev.call_name == "conn_stats":
            self.connections.accept_stats(ev)
            return
        if ev.payload:
            # sniff exactly once per event; build_group reuses the result
            proto, rec = sniff_l7(ev.payload)
            ev.l7 = (proto, rec)
            self.connections.accept_data(ev, proto, rec)
        super().on_raw_event(ev)

    flush_when_empty = True    # spans/metrics accumulate between flushes

    def build_group(self, events):
        group = PipelineEventGroup()
        sb = group.source_buffer
        tree = self.server.proc_tree
        for raw in events:
            # on_raw_event stashes the sniff result; events arriving by
            # other paths (tests, replays) sniff here instead
            proto, rec = getattr(raw, "l7", None) or \
                (sniff_l7(raw.payload) if raw.payload else ("raw", None))
            ev = group.add_log_event(raw.timestamp_ns // 1_000_000_000
                                     or int(time.time()))
            ent = tree.lookup(raw.pid, raw.ktime)
            ev.set_content(b"pid", sb.copy_string(str(raw.pid)))
            if ent is not None and ent.comm:
                ev.set_content(b"comm", sb.copy_string(ent.comm))
            ev.set_content(b"local_addr", sb.copy_string(raw.local_addr))
            ev.set_content(b"remote_addr", sb.copy_string(raw.remote_addr))
            ev.set_content(b"direction", sb.copy_string(raw.direction))
            ev.set_content(b"protocol", sb.copy_string(proto.encode()))
            if rec is None:
                continue
            ev.set_content(b"kind", sb.copy_string(rec.kind.encode()))
            if proto == "http":
                if rec.kind == "request":
                    ev.set_content(b"method", sb.copy_string(rec.method))
                    ev.set_content(b"path", sb.copy_string(rec.path))
                    if rec.host:
                        ev.set_content(b"host", sb.copy_string(rec.host))
                else:
                    ev.set_content(b"status",
                                   sb.copy_string(str(rec.status)))
                if rec.version:
                    ev.set_content(b"http_version",
                                   sb.copy_string(rec.version))
            elif proto == "redis":
                if rec.command:
                    ev.set_content(b"command", sb.copy_string(rec.command))
                if rec.key:
                    ev.set_content(b"key", sb.copy_string(rec.key))
                if rec.error:
                    ev.set_content(b"error", sb.copy_string(rec.error))
                elif rec.kind == "response":
                    ev.set_content(b"ok", sb.copy_string(
                        b"1" if rec.ok else b"0"))
            elif proto == "mysql":
                if rec.command:
                    ev.set_content(b"command", sb.copy_string(rec.command))
                if rec.sql:
                    ev.set_content(b"sql", sb.copy_string(rec.sql))
                if rec.kind == "response":
                    if rec.error_code:
                        ev.set_content(b"error_code", sb.copy_string(
                            str(rec.error_code)))
                        ev.set_content(b"error", sb.copy_string(
                            rec.error_message))
                    elif rec.column_count >= 0:
                        ev.set_content(b"columns", sb.copy_string(
                            str(rec.column_count)))
                    else:
                        ev.set_content(b"ok", sb.copy_string(
                            b"1" if rec.ok else b"0"))
        now = int(time.time())
        for span in self.connections.take_spans():
            sp = group.add_span_event(now)
            sp.name = span.name.encode()
            sp.kind = SpanEvent.Kind.SERVER
            sp.start_time_ns = span.start_ns
            sp.end_time_ns = span.end_ns
            sp.status = (SpanEvent.Status.ERROR if span.status == "error"
                         else SpanEvent.Status.OK)
            sp.set_attribute(b"protocol", sb.copy_string(span.protocol))
            sp.set_attribute(b"pid", sb.copy_string(str(span.pid)))
            sp.set_attribute(b"local_addr", sb.copy_string(span.local_addr))
            sp.set_attribute(b"remote_addr",
                             sb.copy_string(span.remote_addr))
            if span.status_code:
                sp.set_attribute(b"status_code",
                                 sb.copy_string(span.status_code))
            for k, v in span.attributes.items():
                sp.set_attribute(k.encode(), sb.copy_string(v))
            ent = tree.lookup(span.pid, span.ktime)
            if ent is not None and ent.comm:
                sp.set_attribute(b"comm", sb.copy_string(ent.comm))
        for (proto, remote, status), cell in \
                self.connections.take_rollup().items():
            mv = group.add_metric_event(now)
            mv.set_name(b"ebpf_l7_requests")
            mv.set_multi_value({
                b"count": cell.count,
                b"errors": cell.errors,
                b"latency_sum_ns": cell.latency_sum_ns,
                b"latency_max_ns": cell.latency_max_ns,
                b"rx_bytes": cell.rx_bytes,
                b"tx_bytes": cell.tx_bytes,
            })
            mv.set_tag(b"protocol", sb.copy_string(proto))
            mv.set_tag(b"remote", sb.copy_string(remote))
            mv.set_tag(b"status", sb.copy_string(status))
        group.set_tag(b"__source__", b"ebpf_network_observer")
        return group


class SecurityManager(_SourceManager):
    """Process/file/network security events (reference
    {Process,File,Network}SecurityManager).

    PROCESS_SECURITY exec/clone/exit events also drive the process-tree
    cache (reference ProcessCacheManager consumes the same stream), so
    every security event is enriched with the process AND parent blocks
    (AttachProcessData, ProcessCacheManager.cpp:248-291).  Driver event
    conventions: execve events carry the binary in `path` and the argument
    string in `payload`; clone/exit carry only identities."""

    def on_raw_event(self, ev: RawKernelEvent) -> None:
        if self.source is EventSource.PROCESS_SECURITY:
            tree = self.server.proc_tree
            name = ev.call_name
            if name in ("sys_execve", "execve"):
                binary = ev.path
                comm = binary.rsplit("/", 1)[-1] if binary else ""
                tree.on_execve(
                    ev.pid, ev.ktime, ppid=ev.ppid, comm=comm,
                    binary=binary,
                    args=ev.payload.decode("utf-8", "replace"))
            elif name in ("sys_clone", "clone", "sys_fork"):
                tree.on_clone(ev.pid, ev.ktime, ev.ppid)
            elif name in ("sys_exit", "exit", "sched_process_exit"):
                tree.on_exit(ev.pid, ev.ktime)
        super().on_raw_event(ev)

    def build_group(self, events):
        group = PipelineEventGroup()
        sb = group.source_buffer
        tree = self.server.proc_tree
        for raw in events:
            ev = group.add_log_event(raw.timestamp_ns // 1_000_000_000
                                     or int(time.time()))
            ev.set_content(b"pid", sb.copy_string(str(raw.pid)))
            ev.set_content(b"call_name", sb.copy_string(raw.call_name))
            tree.attach_process_data(raw.pid, raw.ktime, ev, sb)
            if raw.path:
                ev.set_content(b"path", sb.copy_string(raw.path))
            if raw.remote_addr:
                ev.set_content(b"remote_addr", sb.copy_string(raw.remote_addr))
        group.set_tag(b"__source__", b"ebpf_" + self.source.value.encode())
        return group


class CpuProfilingManager(_SourceManager):
    """On-CPU stack samples → profile events (reference CpuProfiler +
    cpu_profiling plugin manager): one LogEvent per aggregated
    (pid, stack) with a sample count per flush window."""

    def build_group(self, events):
        group = PipelineEventGroup()
        sb = group.source_buffer
        tree = self.server.proc_tree
        agg: Dict[tuple, int] = {}
        for raw in events:
            key = (raw.pid, tuple(raw.stack))
            agg[key] = agg.get(key, 0) + 1
        now = int(time.time())
        for (pid, stack), count in agg.items():
            ev = group.add_log_event(now)
            ent = tree.lookup(pid)
            ev.set_content(b"pid", sb.copy_string(str(pid)))
            if ent is not None and ent.comm:
                ev.set_content(b"comm", sb.copy_string(ent.comm))
            ev.set_content(b"stack", sb.copy_string(";".join(stack)))
            ev.set_content(b"count", sb.copy_string(str(count)))
        group.set_tag(b"__source__", b"ebpf_cpu_profiling")
        return group


class EBPFServer:
    _instance: Optional["EBPFServer"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self.adapter: EBPFAdapter = get_adapter()
        self.process_queue_manager = None
        self.proc_tree = ProcessTreeCache()
        self._managers: Dict[EventSource, _SourceManager] = {}
        self._thread: Optional[threading.Thread] = None
        self._running = False

    @classmethod
    def instance(cls) -> "EBPFServer":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def enable_plugin(self, source: EventSource, queue_key: int) -> bool:
        """Singleton per source: a reloaded pipeline reuses its queue key; a
        second pipeline claiming an active source is a config error."""
        mgr = self._managers.get(source)
        if mgr is not None and mgr.queue_key not in (None, queue_key):
            log.error("ebpf source %s already bound to another pipeline",
                      source.value)
            return False
        if mgr is None:
            if source is EventSource.NETWORK_OBSERVE:
                cls = NetworkObserverManager
            elif source is EventSource.CPU_PROFILING:
                cls = CpuProfilingManager
            else:
                cls = SecurityManager
            mgr = cls(source, self)
            self._managers[source] = mgr
        mgr.queue_key = queue_key
        ok = self.adapter.start_plugin(source, mgr.on_raw_event)
        self._ensure_thread()
        return ok

    def disable_plugin(self, source: EventSource,
                       queue_key: Optional[int] = None) -> bool:
        mgr = self._managers.get(source)
        if mgr is None:
            return True
        if queue_key is not None and mgr.queue_key != queue_key:
            return True  # someone else owns the source now
        self._managers.pop(source, None)
        mgr.flush()
        return self.adapter.stop_plugin(source)

    def _ensure_thread(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._run, name="ebpf-server",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        # stop driver delivery FIRST so no events arrive after the flush
        for source in list(self._managers):
            self.adapter.stop_plugin(source)
        self._running = False
        if self._thread:
            self._thread.join(timeout=2)
            self._thread = None
        for mgr in self._managers.values():
            mgr.flush()

    def _run(self) -> None:
        last_gc = time.monotonic()
        while self._running:
            time.sleep(0.1)
            for mgr in list(self._managers.values()):
                try:
                    mgr.maybe_flush()
                except Exception:  # noqa: BLE001
                    log.exception("ebpf flush failed")
            now = time.monotonic()
            if now - last_gc >= 5.0:
                last_gc = now
                try:
                    self.proc_tree.clear_expired()
                    netobs = self._managers.get(EventSource.NETWORK_OBSERVE)
                    if netobs is not None:
                        netobs.connections.iterations()
                except Exception:  # noqa: BLE001
                    log.exception("ebpf gc failed")


# ---------------------------------------------------------------------------
# input plugin shims (reference plugin/input/Input{NetworkObserver,...}.cpp)
# ---------------------------------------------------------------------------


class _EBPFInputBase(Input):
    source: EventSource = EventSource.NETWORK_OBSERVE
    is_singleton = True

    def start(self) -> bool:
        server = EBPFServer.instance()
        return server.enable_plugin(self.source, self.context.process_queue_key)

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        return EBPFServer.instance().disable_plugin(
            self.source, self.context.process_queue_key)


class InputNetworkObserver(_EBPFInputBase):
    name = "input_network_observer"
    source = EventSource.NETWORK_OBSERVE


class InputProcessSecurity(_EBPFInputBase):
    name = "input_process_security"
    source = EventSource.PROCESS_SECURITY


class InputFileSecurity(_EBPFInputBase):
    name = "input_file_security"
    source = EventSource.FILE_SECURITY


class InputNetworkSecurity(_EBPFInputBase):
    name = "input_network_security"
    source = EventSource.NETWORK_SECURITY


class InputCpuProfiling(_EBPFInputBase):
    name = "input_cpu_profiling"
    source = EventSource.CPU_PROFILING
