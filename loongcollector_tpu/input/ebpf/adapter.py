"""eBPF driver adapter boundary.

Reference: core/ebpf/EBPFAdapter.cpp:149-231 — the server dlopens the eBPF
driver library (BPF program loading, perf-buffer polling) and receives raw
events through registered callbacks; plugin managers consume them.

This framework keeps the same boundary: `EBPFAdapter` is the abstract driver
interface; `MockAdapter` replays synthetic/recorded raw events (the only
driver usable in unprivileged containers — kernel BPF needs CAP_BPF and a
compiled driver, loaded here the same way via `SoAdapter` when present).
"""

from __future__ import annotations

import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


class EventSource(enum.Enum):
    NETWORK_OBSERVE = "network_observe"
    PROCESS_SECURITY = "process_security"
    FILE_SECURITY = "file_security"
    NETWORK_SECURITY = "network_security"
    CPU_PROFILING = "cpu_profiling"


@dataclass
class RawKernelEvent:
    """A raw event from the driver (what the perf buffer would deliver)."""

    source: EventSource
    pid: int = 0
    timestamp_ns: int = 0
    ppid: int = -1             # parent pid (-1 unknown)
    ktime: int = 0             # process start ktime: (pid, ktime) is the
                               # stable process identity across pid reuse
    # network events
    fd: int = -1
    local_addr: str = ""
    remote_addr: str = ""
    direction: str = ""        # ingress / egress
    payload: bytes = b""       # captured L7 bytes
    # security events
    call_name: str = ""        # e.g. security_file_permission, sys_execve
    path: str = ""
    flags: int = 0
    # profiling
    stack: List[str] = field(default_factory=list)


Callback = Callable[[RawKernelEvent], None]


class EBPFAdapter:
    """Driver interface (reference EBPFAdapter): start/stop per source,
    callbacks deliver raw events on the poll thread."""

    def start_plugin(self, source: EventSource, callback: Callback) -> bool:
        raise NotImplementedError

    def stop_plugin(self, source: EventSource) -> bool:
        raise NotImplementedError

    def suspend_plugin(self, source: EventSource) -> bool:
        return True

    def resume_plugin(self, source: EventSource) -> bool:
        return True


class MockAdapter(EBPFAdapter):
    """Replay adapter: feed() injects events; optionally a generator thread
    produces a synthetic stream (used by tests and the bench harness)."""

    def __init__(self) -> None:
        self._callbacks: Dict[EventSource, Callback] = {}
        self._lock = threading.Lock()

    def start_plugin(self, source: EventSource, callback: Callback) -> bool:
        with self._lock:
            self._callbacks[source] = callback
        return True

    def stop_plugin(self, source: EventSource) -> bool:
        with self._lock:
            self._callbacks.pop(source, None)
        return True

    def feed(self, event: RawKernelEvent) -> bool:
        with self._lock:
            cb = self._callbacks.get(event.source)
        if cb is None:
            return False
        cb(event)
        return True


# --------------------------------------------------------------------------
# dlopen'd driver ABI (native/ebpf_driver_abi.h)
#
# Reference: core/ebpf/EBPFAdapter.cpp:149-231 — the agent dlopens the
# driver library and talks through a versioned vtable.  SoAdapter is that
# boundary: ctypes mirrors of the C structs (layout pinned by
# tests/test_ebpf_abi.py), version/size handshake at load, callbacks
# delivered from the driver's poll thread.  The in-tree simulation driver
# (native/libloong_ebpf_sim.so) implements the same table a real kernel
# driver would.

import ctypes
import os

ABI_VERSION = 2
CALLNAME_MAX = 32
PATH_MAX = 128
ADDR_MAX = 64
PAYLOAD_MAX = 4096
STACK_DEPTH = 32
FRAME_MAX = 96

_SOURCE_TO_U32 = {
    EventSource.NETWORK_OBSERVE: 0,
    EventSource.PROCESS_SECURITY: 1,
    EventSource.FILE_SECURITY: 2,
    EventSource.NETWORK_SECURITY: 3,
    EventSource.CPU_PROFILING: 4,
}
_U32_TO_SOURCE = {v: k for k, v in _SOURCE_TO_U32.items()}
_DIRECTION = {0: "", 1: "ingress", 2: "egress"}
_DIRECTION_TO_U16 = {"": 0, "ingress": 1, "egress": 2}


class CEvent(ctypes.Structure):
    _fields_ = [
        ("timestamp_ns", ctypes.c_uint64),
        ("source", ctypes.c_uint32),
        ("pid", ctypes.c_int32),
        ("fd", ctypes.c_int32),
        ("flags", ctypes.c_uint32),
        ("direction", ctypes.c_uint16),
        ("stack_depth", ctypes.c_uint16),
        ("payload_len", ctypes.c_uint32),
        ("ppid", ctypes.c_int32),
        ("reserved0", ctypes.c_uint32),
        ("ktime", ctypes.c_uint64),
        ("call_name", ctypes.c_char * CALLNAME_MAX),
        ("path", ctypes.c_char * PATH_MAX),
        ("local_addr", ctypes.c_char * ADDR_MAX),
        ("remote_addr", ctypes.c_char * ADDR_MAX),
        ("payload", ctypes.c_uint8 * PAYLOAD_MAX),
        ("stack", (ctypes.c_char * FRAME_MAX) * STACK_DEPTH),
    ]


_CB = ctypes.CFUNCTYPE(None, ctypes.POINTER(CEvent), ctypes.c_void_p)


class CDriver(ctypes.Structure):
    _fields_ = [
        ("abi_version", ctypes.c_uint32),
        ("event_size", ctypes.c_uint32),
        ("start", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_uint32, _CB,
                                   ctypes.c_void_p)),
        ("stop", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_uint32)),
        ("suspend", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_uint32)),
        ("resume", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_uint32)),
        ("inject", ctypes.CFUNCTYPE(ctypes.c_int,
                                    ctypes.POINTER(CEvent))),
    ]


def default_driver_path() -> str:
    env = os.environ.get("LOONG_EBPF_DRIVER")
    if env:
        return env
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))), "native",
        "libloong_ebpf_sim.so")


def _event_to_c(ev: RawKernelEvent) -> CEvent:
    c = CEvent()
    c.timestamp_ns = ev.timestamp_ns
    c.source = _SOURCE_TO_U32[ev.source]
    c.pid = ev.pid
    c.fd = ev.fd
    c.flags = ev.flags
    c.ppid = ev.ppid
    c.ktime = ev.ktime
    c.direction = _DIRECTION_TO_U16.get(ev.direction, 0)
    c.call_name = ev.call_name.encode()[:CALLNAME_MAX - 1]
    c.path = ev.path.encode()[:PATH_MAX - 1]
    c.local_addr = ev.local_addr.encode()[:ADDR_MAX - 1]
    c.remote_addr = ev.remote_addr.encode()[:ADDR_MAX - 1]
    payload = ev.payload[:PAYLOAD_MAX]
    c.payload_len = len(payload)
    ctypes.memmove(c.payload, payload, len(payload))
    frames = ev.stack[:STACK_DEPTH]
    c.stack_depth = len(frames)
    for i, fr in enumerate(frames):
        c.stack[i].value = fr.encode()[:FRAME_MAX - 1]
    return c


def _event_from_c(c: CEvent) -> RawKernelEvent:
    # one C memcpy — slicing the c_uint8 array would materialize a PyLong
    # per byte on the delivery hot path
    payload = ctypes.string_at(c.payload, c.payload_len)
    stack = [c.stack[i].value.decode("utf-8", "replace")
             for i in range(c.stack_depth)]
    return RawKernelEvent(
        source=_U32_TO_SOURCE.get(c.source, EventSource.NETWORK_OBSERVE),
        pid=c.pid, timestamp_ns=c.timestamp_ns, fd=c.fd,
        ppid=c.ppid, ktime=c.ktime,
        local_addr=c.local_addr.decode("utf-8", "replace"),
        remote_addr=c.remote_addr.decode("utf-8", "replace"),
        direction=_DIRECTION.get(c.direction, ""),
        payload=payload,
        call_name=c.call_name.decode("utf-8", "replace"),
        path=c.path.decode("utf-8", "replace"),
        flags=c.flags, stack=stack)


class AbiMismatch(RuntimeError):
    pass


class SoAdapter(EBPFAdapter):
    """dlopen a driver library implementing the loong_ebpf_driver ABI.

    Performs the version/size handshake at load; keeps the ctypes callback
    objects alive for as long as their source is started (the driver holds
    raw function pointers)."""

    def __init__(self, so_path: Optional[str] = None):
        path = so_path or default_driver_path()
        self._lib = ctypes.CDLL(path)
        self._lib.loong_ebpf_driver_get.restype = ctypes.POINTER(CDriver)
        drv = self._lib.loong_ebpf_driver_get()
        if not drv:
            raise AbiMismatch(f"{path}: loong_ebpf_driver_get returned NULL")
        self._drv = drv.contents
        if self._drv.abi_version != ABI_VERSION:
            raise AbiMismatch(
                f"{path}: driver ABI v{self._drv.abi_version}, "
                f"collector speaks v{ABI_VERSION}")
        if self._drv.event_size != ctypes.sizeof(CEvent):
            raise AbiMismatch(
                f"{path}: event struct {self._drv.event_size} B, "
                f"collector expects {ctypes.sizeof(CEvent)} B")
        self.path = path
        self._cbs: Dict[EventSource, object] = {}   # active holders
        # trampolines are NEVER freed: the driver's poll thread may be
        # mid-invocation when stop() returns (stop only deregisters under
        # the driver lock; an already-copied cb pointer can still run).
        # Freeing the ctypes thunk there is a native use-after-free.
        # Start/stop cycles are rare (pipeline reloads), so the retired
        # list stays tiny over an agent's lifetime.
        self._retired_cbs: List[object] = []
        self._lock = threading.Lock()

    def start_plugin(self, source: EventSource, callback: Callback) -> bool:
        def c_cb(ev_ptr, _user):
            try:
                callback(_event_from_c(ev_ptr.contents))
            except Exception:  # noqa: BLE001 — never unwind into C
                pass

        holder = _CB(c_cb)
        rc = self._drv.start(_SOURCE_TO_U32[source], holder, None)
        if rc == -2:   # ESTATE: already running (e.g. pipeline reload that
            # skipped stop) — rebind like MockAdapter by stop+start
            self._drv.stop(_SOURCE_TO_U32[source])
            with self._lock:
                old = self._cbs.pop(source, None)
                if old is not None:
                    self._retired_cbs.append(old)
            rc = self._drv.start(_SOURCE_TO_U32[source], holder, None)
        if rc != 0:
            return False
        with self._lock:
            self._cbs[source] = holder
        return True

    def stop_plugin(self, source: EventSource) -> bool:
        rc = self._drv.stop(_SOURCE_TO_U32[source])
        with self._lock:
            holder = self._cbs.pop(source, None)
            if holder is not None:
                self._retired_cbs.append(holder)
        return rc == 0

    def suspend_plugin(self, source: EventSource) -> bool:
        return self._drv.suspend(_SOURCE_TO_U32[source]) == 0

    def resume_plugin(self, source: EventSource) -> bool:
        return self._drv.resume(_SOURCE_TO_U32[source]) == 0

    def feed(self, event: RawKernelEvent) -> bool:
        """Inject through the driver's ABI hook (simulation drivers only)."""
        c = _event_to_c(event)
        return self._drv.inject(ctypes.byref(c)) == 0


def try_load_so_adapter() -> Optional["SoAdapter"]:
    path = default_driver_path()
    if not os.path.exists(path):
        return None
    try:
        return SoAdapter(path)
    except (OSError, AbiMismatch):
        return None


_default_adapter: Optional[EBPFAdapter] = None
_adapter_lock = threading.Lock()


def get_adapter() -> EBPFAdapter:
    """Process-wide adapter.  Prefers the dlopen'd driver (real or
    simulation .so) — the same code path a kernel driver would use; falls
    back to the in-process mock when no library is present."""
    global _default_adapter
    with _adapter_lock:
        if _default_adapter is None:
            _default_adapter = try_load_so_adapter() or MockAdapter()
        return _default_adapter


def set_adapter(adapter: EBPFAdapter) -> None:
    global _default_adapter
    with _adapter_lock:
        _default_adapter = adapter
