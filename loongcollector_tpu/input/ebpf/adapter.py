"""eBPF driver adapter boundary.

Reference: core/ebpf/EBPFAdapter.cpp:149-231 — the server dlopens the eBPF
driver library (BPF program loading, perf-buffer polling) and receives raw
events through registered callbacks; plugin managers consume them.

This framework keeps the same boundary: `EBPFAdapter` is the abstract driver
interface; `MockAdapter` replays synthetic/recorded raw events (the only
driver usable in unprivileged containers — kernel BPF needs CAP_BPF and a
compiled driver, loaded here the same way via `SoAdapter` when present).
"""

from __future__ import annotations

import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


class EventSource(enum.Enum):
    NETWORK_OBSERVE = "network_observe"
    PROCESS_SECURITY = "process_security"
    FILE_SECURITY = "file_security"
    NETWORK_SECURITY = "network_security"
    CPU_PROFILING = "cpu_profiling"


@dataclass
class RawKernelEvent:
    """A raw event from the driver (what the perf buffer would deliver)."""

    source: EventSource
    pid: int = 0
    timestamp_ns: int = 0
    # network events
    fd: int = -1
    local_addr: str = ""
    remote_addr: str = ""
    direction: str = ""        # ingress / egress
    payload: bytes = b""       # captured L7 bytes
    # security events
    call_name: str = ""        # e.g. security_file_permission, sys_execve
    path: str = ""
    flags: int = 0
    # profiling
    stack: List[str] = field(default_factory=list)


Callback = Callable[[RawKernelEvent], None]


class EBPFAdapter:
    """Driver interface (reference EBPFAdapter): start/stop per source,
    callbacks deliver raw events on the poll thread."""

    def start_plugin(self, source: EventSource, callback: Callback) -> bool:
        raise NotImplementedError

    def stop_plugin(self, source: EventSource) -> bool:
        raise NotImplementedError

    def suspend_plugin(self, source: EventSource) -> bool:
        return True

    def resume_plugin(self, source: EventSource) -> bool:
        return True


class MockAdapter(EBPFAdapter):
    """Replay adapter: feed() injects events; optionally a generator thread
    produces a synthetic stream (used by tests and the bench harness)."""

    def __init__(self) -> None:
        self._callbacks: Dict[EventSource, Callback] = {}
        self._lock = threading.Lock()

    def start_plugin(self, source: EventSource, callback: Callback) -> bool:
        with self._lock:
            self._callbacks[source] = callback
        return True

    def stop_plugin(self, source: EventSource) -> bool:
        with self._lock:
            self._callbacks.pop(source, None)
        return True

    def feed(self, event: RawKernelEvent) -> bool:
        with self._lock:
            cb = self._callbacks.get(event.source)
        if cb is None:
            return False
        cb(event)
        return True


_default_adapter: Optional[EBPFAdapter] = None
_adapter_lock = threading.Lock()


def get_adapter() -> EBPFAdapter:
    """Process-wide adapter; defaults to the mock (driver .so loading slots
    in here when a privileged driver build exists)."""
    global _default_adapter
    with _adapter_lock:
        if _default_adapter is None:
            _default_adapter = MockAdapter()
        return _default_adapter


def set_adapter(adapter: EBPFAdapter) -> None:
    global _default_adapter
    with _adapter_lock:
        _default_adapter = adapter
