"""L7 Redis (RESP) protocol parsing for captured network payloads.

Reference: core/ebpf/protocol/redis/ — RESP2 framing: requests are arrays
of bulk strings (*N / $len), responses are simple strings (+), errors (-),
integers (:), bulk ($) or arrays (*). Inline commands are accepted for
requests.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

MAX_PREVIEW = 256

# commands we recognise for the inline form (strict: random text that
# happens to lack RESP markers must not parse as Redis)
_KNOWN = {b"GET", b"SET", b"DEL", b"INCR", b"DECR", b"EXPIRE", b"TTL",
          b"PING", b"ECHO", b"EXISTS", b"HGET", b"HSET", b"HDEL", b"LPUSH",
          b"RPUSH", b"LPOP", b"RPOP", b"LRANGE", b"SADD", b"SREM", b"AUTH",
          b"SELECT", b"SUBSCRIBE", b"PUBLISH", b"XADD", b"ZADD", b"MGET",
          b"MSET", b"KEYS", b"SCAN", b"INFO", b"CONFIG", b"CLUSTER"}


@dataclass
class RedisRecord:
    kind: str = ""            # request | response
    command: bytes = b""
    key: bytes = b""
    ok: bool = False
    error: bytes = b""
    value_preview: bytes = b""


def _bulk_strings(payload: bytes, n: int, pos: int) -> List[bytes]:
    out: List[bytes] = []
    for _ in range(n):
        if pos >= len(payload) or payload[pos:pos + 1] != b"$":
            break
        nl = payload.find(b"\r\n", pos)
        if nl < 0:
            break
        try:
            ln = int(payload[pos + 1:nl])
        except ValueError:
            break
        if ln < 0:
            out.append(b"")
            pos = nl + 2
            continue
        out.append(bytes(payload[nl + 2:nl + 2 + ln]))
        pos = nl + 2 + ln + 2
    return out


def parse_redis(payload: bytes) -> Optional[RedisRecord]:
    if not payload:
        return None
    first = payload[:1]
    rec = RedisRecord()
    if first == b"*":
        nl = payload.find(b"\r\n")
        if nl < 0:
            return None
        try:
            n = int(payload[1:nl])
        except ValueError:
            return None
        args = _bulk_strings(payload, min(n, 8), nl + 2)
        if args:
            # request (array of bulk strings): command + key
            rec.kind = "request"
            rec.command = args[0].upper()
            if len(args) > 1:
                rec.key = args[1][:MAX_PREVIEW]
            return rec
        rec.kind = "response"
        rec.ok = True
        rec.value_preview = b"*%d" % n
        return rec
    if first == b"+":
        rec.kind = "response"
        rec.ok = True
        rec.value_preview = payload[1:payload.find(b"\r\n")][:MAX_PREVIEW] \
            if b"\r\n" in payload else payload[1:MAX_PREVIEW]
        return rec
    if first == b"-":
        rec.kind = "response"
        rec.error = payload[1:payload.find(b"\r\n")][:MAX_PREVIEW] \
            if b"\r\n" in payload else payload[1:MAX_PREVIEW]
        return rec
    if first == b":":
        rec.kind = "response"
        rec.ok = True
        rec.value_preview = payload[1:payload.find(b"\r\n")][:MAX_PREVIEW] \
            if b"\r\n" in payload else payload[1:MAX_PREVIEW]
        return rec
    if first == b"$":
        nl = payload.find(b"\r\n")
        if nl < 0:
            return None
        rec.kind = "response"
        rec.ok = payload[1:nl] != b"-1"
        rec.value_preview = bytes(payload[nl + 2:nl + 2 + MAX_PREVIEW]
                                  .rstrip(b"\r\n"))
        return rec
    # inline command (request without RESP framing)
    line = payload.split(b"\r\n", 1)[0].split(b"\n", 1)[0]
    parts = line.split()
    if parts and parts[0].upper() in _KNOWN:
        rec.kind = "request"
        rec.command = parts[0].upper()
        if len(parts) > 1:
            rec.key = parts[1][:MAX_PREVIEW]
        return rec
    return None
