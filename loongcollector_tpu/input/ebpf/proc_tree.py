"""Event-driven process-tree cache.

Reference: core/ebpf/plugin/ProcessCacheManager.cpp + ProcessCache.cpp —
the kernel driver delivers execve/clone/exit events; the cache keys entries
by (pid, ktime) (`data_event_id`) so pid reuse cannot mis-attribute, links
each entry to its parent, refcounts entries so a parent outlives its
children's events, and enriches security/observer events with the process
and parent metadata (AttachProcessData, ProcessCacheManager.cpp:248-291).

This implementation keeps those semantics on the v2 driver ABI:

* `on_execve` — insert/replace the (pid, ktime) entry; parent resolved
  from (ppid, *latest*) and ref-held by the child.
* `on_clone` — child inherits the parent's image (comm/binary/args/cwd),
  parent ref-held.
* `on_exit` — entry enters a grace period (events already in flight still
  need enrichment — the reference keeps entries alive via refcounts and a
  cleanup queue), then releases its parent ref and expires.
* `/proc` warm-sync for processes that exec'd before the driver attached
  (ProcessSyncRetryableEvent analogue), performed lazily on miss.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

EXIT_GRACE_S = 10.0      # reference keeps exited entries until refs drain
MAX_ENTRIES = 16384


@dataclass
class ProcEntry:
    pid: int
    ktime: int
    ppid: int = -1
    comm: str = ""
    binary: str = ""
    args: str = ""
    cwd: str = ""
    user: str = ""
    container_id: str = ""
    parent: Optional["ProcEntry"] = None
    refcnt: int = 1
    exited_at: float = 0.0       # monotonic; 0 = alive
    exec_id: str = field(default="", compare=False)

    def __post_init__(self) -> None:
        if not self.exec_id:
            self.exec_id = f"{self.pid}:{self.ktime}"


class ProcessTreeCache:
    """(pid, ktime)-keyed process cache with parent linkage + refcounts."""

    NEG_TTL_S = 30.0   # cache failed /proc lookups (exited pids) this long

    def __init__(self, max_entries: int = MAX_ENTRIES):
        self._by_id: Dict[Tuple[int, int], ProcEntry] = {}
        self._latest: Dict[int, ProcEntry] = {}   # pid -> newest entry
        self._neg: Dict[int, float] = {}          # pid -> expiry (monotonic)
        self._lock = threading.Lock()
        self.max_entries = max_entries
        self.misses = 0
        self.hits = 0

    # -- driver-event ingestion --------------------------------------------

    def on_execve(self, pid: int, ktime: int, ppid: int = -1,
                  comm: str = "", binary: str = "", args: str = "",
                  cwd: str = "", container_id: str = "") -> ProcEntry:
        with self._lock:
            parent = self._latest.get(ppid) if ppid >= 0 else None
            ent = ProcEntry(pid=pid, ktime=ktime, ppid=ppid, comm=comm,
                            binary=binary or comm, args=args, cwd=cwd,
                            container_id=container_id, parent=parent)
            if parent is not None:
                parent.refcnt += 1
                if not ent.container_id:
                    ent.container_id = parent.container_id
            old = self._latest.get(pid)
            if old is not None and old.ktime != ktime:
                # same pid re-exec'd: the old image expires once its
                # in-flight events drain
                old.exited_at = old.exited_at or time.monotonic()
            replaced = self._by_id.get((pid, ktime))
            if replaced is not None and replaced.parent is not None:
                # same (pid, ktime) re-inserted (ktime is the process START
                # time, stable across execve): release the old entry's
                # parent ref or the parent can never be collected
                replaced.parent.refcnt -= 1
            self._by_id[(pid, ktime)] = ent
            self._latest[pid] = ent
            self._shrink_locked()
            return ent

    def on_clone(self, pid: int, ktime: int, ppid: int) -> ProcEntry:
        with self._lock:
            parent = self._latest.get(ppid)
            ent = ProcEntry(pid=pid, ktime=ktime, ppid=ppid, parent=parent)
            if parent is not None:
                parent.refcnt += 1
                # a cloned child runs the parent's image until it execs
                ent.comm = parent.comm
                ent.binary = parent.binary
                ent.args = parent.args
                ent.cwd = parent.cwd
                ent.user = parent.user
                ent.container_id = parent.container_id
            self._by_id[(pid, ktime)] = ent
            self._latest[pid] = ent
            self._shrink_locked()
            return ent

    def on_exit(self, pid: int, ktime: int = 0) -> None:
        with self._lock:
            ent = (self._by_id.get((pid, ktime)) if ktime
                   else self._latest.get(pid))
            if ent is not None and not ent.exited_at:
                ent.exited_at = time.monotonic()

    # -- lookup / enrichment -----------------------------------------------

    def lookup(self, pid: int, ktime: int = 0) -> Optional[ProcEntry]:
        with self._lock:
            ent = (self._by_id.get((pid, ktime)) if ktime
                   else self._latest.get(pid))
        if ent is not None:
            self.hits += 1
            return ent
        ent = self._proc_sync(pid)
        if ent is None:
            self.misses += 1
        return ent

    def attach_process_data(self, pid: int, ktime: int, ev, sb) -> bool:
        """Enrich a log event with process + parent metadata (reference
        AttachProcessData: exec_id, pid, binary, args, cwd, container,
        then the parent block).  Returns False on cache miss."""
        ent = self.lookup(pid, ktime)
        if ent is None:
            return False
        ev.set_content(b"exec_id", sb.copy_string(ent.exec_id))
        ev.set_content(b"process_pid", sb.copy_string(str(ent.pid)))
        if ent.comm:
            ev.set_content(b"comm", sb.copy_string(ent.comm))
        if ent.binary:
            ev.set_content(b"binary", sb.copy_string(ent.binary))
        if ent.args:
            ev.set_content(b"arguments", sb.copy_string(ent.args))
        if ent.cwd:
            ev.set_content(b"cwd", sb.copy_string(ent.cwd))
        if ent.user:
            ev.set_content(b"user", sb.copy_string(ent.user))
        if ent.container_id:
            ev.set_content(b"container_id",
                           sb.copy_string(ent.container_id))
        parent = ent.parent
        if parent is not None:
            ev.set_content(b"parent_exec_id", sb.copy_string(parent.exec_id))
            ev.set_content(b"parent_pid", sb.copy_string(str(parent.pid)))
            if parent.binary:
                ev.set_content(b"parent_binary",
                               sb.copy_string(parent.binary))
            if parent.args:
                ev.set_content(b"parent_arguments",
                               sb.copy_string(parent.args))
        return True

    # -- maintenance --------------------------------------------------------

    def clear_expired(self) -> int:
        """Drop exited entries past their grace period whose refs drained
        (reference ClearExpiredCache + the cleanup retryable event)."""
        now = time.monotonic()
        dropped = 0
        with self._lock:
            for key, ent in list(self._by_id.items()):
                if ent.exited_at and now - ent.exited_at > EXIT_GRACE_S \
                        and ent.refcnt <= 1:
                    del self._by_id[key]
                    if self._latest.get(ent.pid) is ent:
                        del self._latest[ent.pid]
                    if ent.parent is not None:
                        ent.parent.refcnt -= 1
                    # process-cache eviction, not an event discard
                    # loonglint: disable=unledgered-drop
                    dropped += 1
        return dropped

    def size(self) -> int:
        with self._lock:
            return len(self._by_id)

    def _shrink_locked(self) -> None:
        if len(self._by_id) <= self.max_entries:
            return
        # ForceShrink analogue: exited-first, then oldest ktime
        victims = sorted(self._by_id.items(),
                         key=lambda kv: (not kv[1].exited_at, kv[1].ktime))
        for key, ent in victims[: len(self._by_id) // 4]:
            del self._by_id[key]
            if self._latest.get(ent.pid) is ent:
                del self._latest[ent.pid]
            if ent.parent is not None:
                ent.parent.refcnt -= 1

    def _proc_sync(self, pid: int) -> Optional[ProcEntry]:
        """Lazy /proc warm-start for pre-attach processes.  Failed lookups
        (exited/never-existed pids) are negative-cached so event floods for
        dead pids don't repeat open("/proc/N/...") per event."""
        now = time.monotonic()
        with self._lock:
            exp = self._neg.get(pid)
            if exp is not None:
                if exp > now:
                    return None
                del self._neg[pid]
        try:
            with open(f"/proc/{pid}/comm") as f:
                comm = f.read().strip()
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                args = f.read().replace(b"\0", b" ").decode(
                    "utf-8", "replace").strip()
            ppid = -1
            try:
                with open(f"/proc/{pid}/stat") as f:
                    ppid = int(f.read().rsplit(")", 1)[1].split()[1])
            except (OSError, ValueError, IndexError):
                pass
            cwd = ""
            try:
                cwd = os.readlink(f"/proc/{pid}/cwd")
            except OSError:
                pass
        except OSError:
            with self._lock:
                if len(self._neg) > 4096:
                    self._neg = {k: v for k, v in self._neg.items()
                                 if v > now}
                self._neg[pid] = now + self.NEG_TTL_S
            return None
        with self._lock:
            ent = self._latest.get(pid)
            if ent is None:
                ent = ProcEntry(pid=pid, ktime=0, ppid=ppid, comm=comm,
                                binary=comm, args=args, cwd=cwd,
                                parent=self._latest.get(ppid))
                if ent.parent is not None:
                    ent.parent.refcnt += 1
                self._by_id[(pid, 0)] = ent
                self._latest[pid] = ent
            return ent
