"""L7 MySQL protocol parsing for captured network payloads.

Reference: core/ebpf/protocol/mysql/ — the network observer decodes the
MySQL client/server packet framing (3-byte LE length + sequence id) into
command records (COM_QUERY text, prepared-statement ops) and response
outcomes (OK / ERR with code + message / result set).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

COMMANDS = {
    0x01: b"QUIT", 0x02: b"INIT_DB", 0x03: b"QUERY", 0x04: b"FIELD_LIST",
    0x0E: b"PING", 0x16: b"STMT_PREPARE", 0x17: b"STMT_EXECUTE",
    0x19: b"STMT_CLOSE", 0x1C: b"STMT_FETCH",
}

MAX_SQL = 1024


@dataclass
class MySQLRecord:
    kind: str = ""            # request | response
    command: bytes = b""      # QUERY / STMT_PREPARE / ...
    sql: bytes = b""
    ok: bool = False
    error_code: int = 0
    error_message: bytes = b""
    column_count: int = -1


def parse_mysql(payload: bytes) -> Optional[MySQLRecord]:
    """One captured segment starting at a packet boundary → record.

    Framing check is strict (declared length must cover the payload we
    see, capped by capture truncation) so random text never misparses.
    """
    if len(payload) < 5:
        return None
    plen = payload[0] | (payload[1] << 8) | (payload[2] << 16)
    seq = payload[3]
    if plen == 0 or plen > (1 << 20):
        return None   # implausible frame: not MySQL
    body = payload[4:4 + plen]
    if len(body) < 1:
        return None
    complete = len(payload) - 4 >= plen
    # incomplete frames are only trusted when the capture obviously hit
    # its snapshot cap — random text has a garbage length that neither
    # completes nor looks truncated-by-capture
    if not complete and len(payload) < 1024:
        return None
    first = body[0]
    rec = MySQLRecord()
    if seq == 0 and first in COMMANDS:
        rec.kind = "request"
        rec.command = COMMANDS[first]
        if first in (0x03, 0x16, 0x02, 0x04):   # text follows the command
            rec.sql = bytes(body[1:MAX_SQL + 1])
        return rec
    if seq == 0:
        return None   # client packet with unknown command: not MySQL
    if seq > 7:
        return None   # responses start at low sequence ids; random bytes
        # in the seq slot are the main false-positive source
    rec.kind = "response"
    if first == 0x00:
        rec.ok = True
    elif first == 0xFF:
        if len(body) < 3:
            return None
        rec.error_code = body[1] | (body[2] << 8)
        msg = body[3:]
        if msg.startswith(b"#") and len(msg) > 6:
            msg = msg[6:]             # skip SQLSTATE marker
        rec.error_message = bytes(msg[:256])
    elif first == 0xFE and plen < 9:
        rec.ok = True                 # EOF packet
    elif 0x01 <= first <= 0xFA:
        rec.column_count = first      # result-set header (lenenc small int)
    else:
        return None
    return rec
