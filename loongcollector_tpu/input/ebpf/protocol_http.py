"""L7 HTTP protocol parsing for captured network payloads.

Reference: core/ebpf/protocol/http/ — the network observer parses captured
request/response bytes into structured records (method, path, version,
status, headers of interest).

Request-line/status-line extraction is span-based so batches of payloads can
flow through the same columnar machinery as log lines; header scanning is a
bounded host pass (headers live in the first KB).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

_METHODS = (b"GET", b"POST", b"PUT", b"DELETE", b"HEAD", b"OPTIONS",
            b"PATCH", b"CONNECT", b"TRACE")


@dataclass
class HTTPRecord:
    kind: str = ""            # request | response
    method: bytes = b""
    path: bytes = b""
    version: bytes = b""
    status: int = 0
    host: bytes = b""
    content_length: int = -1
    user_agent: bytes = b""


def parse_http(payload: bytes, max_headers: int = 32) -> Optional[HTTPRecord]:
    """Parse the first request/status line + interesting headers."""
    end = payload.find(b"\r\n")
    if end < 0:
        end = payload.find(b"\n")
        if end < 0:
            return None
    first = payload[:end]
    rec = HTTPRecord()
    if first.startswith(b"HTTP/"):
        parts = first.split(b" ", 2)
        if len(parts) < 2 or not parts[1].isdigit():
            return None
        rec.kind = "response"
        rec.version = parts[0]
        rec.status = int(parts[1])
    else:
        parts = first.split(b" ")
        if len(parts) != 3 or parts[0] not in _METHODS:
            return None
        rec.kind = "request"
        rec.method, rec.path, rec.version = parts
    # headers
    pos = end + (2 if payload[end:end + 2] == b"\r\n" else 1)
    for _ in range(max_headers):
        nxt = payload.find(b"\n", pos)
        if nxt < 0:
            break
        line = payload[pos:nxt].rstrip(b"\r")
        pos = nxt + 1
        if not line:
            break
        k, sep, v = line.partition(b":")
        if not sep:
            continue
        key = k.strip().lower()
        val = v.strip()
        if key == b"host":
            rec.host = val
        elif key == b"content-length" and val.isdigit():
            rec.content_length = int(val)
        elif key == b"user-agent":
            rec.user_agent = val
    return rec
