"""service_kafka — Kafka consumer-group ingest.

Reference: plugins/input/kafka/input_kafka.go (sarama ConsumerGroup wrap);
here the wire protocol lives in flusher/kafka_client.py (KafkaConsumer —
JoinGroup/SyncGroup/Heartbeat/Fetch/OffsetCommit) and this plugin runs the
consume loop on a service thread, emitting one event per record with
topic/partition/offset (+ optional key) fields, committing consumed
positions after each pushed batch (at-least-once, like the reference's
MarkMessage-after-collect).

Config keys mirror the reference: Brokers, Topics, ConsumerGroup, ClientID,
Offset (oldest|newest), Assignor (range|roundrobin), MaxMessageLen,
SASLUsername/SASLPassword, plus TLS{...} passthrough.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ..flusher.kafka_client import KafkaConsumer, KafkaError
from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger

log = get_logger("input_kafka")


class InputKafka(Input):
    name = "service_kafka"

    def __init__(self) -> None:
        super().__init__()
        self._consumer: Optional[KafkaConsumer] = None
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._max_len = 512 * 1024
        self._fields_extend = False
        # test hook: how long the poll loop sleeps after an empty poll
        self._idle_sleep = 0.2
        # set when the last polled batch could not be delivered downstream
        self._dirty_tail = False

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self._brokers = config.get("Brokers") or []
        self._topics = config.get("Topics") or []
        self._group = config.get("ConsumerGroup") or ""
        if not self._brokers or not self._topics or not self._group:
            log.error("service_kafka requires Brokers, Topics and "
                      "ConsumerGroup")
            return False
        self._client_id = config.get("ClientID") or "loongcollector-tpu"
        self._offset = (config.get("Offset") or "oldest").lower()
        self._assignor = (config.get("Assignor") or "range").lower()
        self._max_len = int(config.get("MaxMessageLen") or 512 * 1024)
        self._fields_extend = bool(config.get("FieldsExtend"))
        sasl = None
        if config.get("SASLUsername") and config.get("SASLPassword"):
            sasl = {"Mechanism": config.get("SASLMechanism", "PLAIN"),
                    "Username": config["SASLUsername"],
                    "Password": config["SASLPassword"]}
        self._sasl = sasl
        self._tls = config.get("TLS")
        return True

    def start(self) -> bool:
        self._consumer = KafkaConsumer(
            self._brokers, self._group, self._topics,
            client_id=self._client_id, offset_reset=self._offset,
            assignor=self._assignor, tls=self._tls, sasl=self._sasl)
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="kafka-consume")
        self._thread.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self._running = False
        if self._thread is not None:
            # the poll thread owns the sockets; wait out its longest
            # blocking request (10s socket timeout) before touching them
            self._thread.join(timeout=15)
            dead = not self._thread.is_alive()
            self._thread = None
        else:
            dead = True
        if self._consumer is not None and dead:
            try:
                self._consumer.close(commit=not self._dirty_tail)
            except Exception:  # noqa: BLE001
                pass
            self._consumer = None
        return True

    # -- consume loop --------------------------------------------------------

    def _loop(self) -> None:
        cons = self._consumer            # stop() may null the attribute
        backoff = 1.0
        while self._running:
            try:
                records = cons.poll(max_wait_ms=200)
            except Exception as e:  # noqa: BLE001 # loonglint: disable=unledgered-drop
                # a malformed broker response (struct.error included) must
                # retry, not kill the consume thread (reference retries
                # Consume forever); nothing was consumed, so there is no
                # event in hand for the ledger to account
                log.warning("kafka consume error: %r (retrying)", e)
                cons._joined = False
                deadline = time.monotonic() + min(backoff, 5.0)
                backoff = min(backoff * 2, 5.0)
                while self._running and time.monotonic() < deadline:
                    time.sleep(0.1)
                continue
            backoff = 1.0
            if not records:
                time.sleep(self._idle_sleep)
                continue
            if not self._push(records, cons):
                # undelivered (stop during backpressure): committing now —
                # or at close — would drop the batch
                self._dirty_tail = True
                continue
            try:
                cons.commit()
            except Exception as e:  # noqa: BLE001 — same retry contract
                # as poll: a truncated commit response must not kill the
                # thread; positions recommit on the next cycle
                log.warning("kafka offset commit failed: %r", e)

    def _push(self, records, cons=None) -> bool:
        """Returns True when the group reached the process queue."""
        group = PipelineEventGroup()
        sb = group.source_buffer
        now = int(time.time())
        for rec in records:
            value = rec.value[: self._max_len]
            ev = group.add_log_event(
                rec.timestamp // 1000 if rec.timestamp > 0 else now)
            ev.set_content(b"content", sb.copy_string(value))
            if self._fields_extend:
                ev.set_content(b"__topic__",
                               sb.copy_string(rec.topic.encode()))
                ev.set_content(b"__partition__", sb.copy_string(
                    str(rec.partition).encode()))
                ev.set_content(b"__offset__", sb.copy_string(
                    str(rec.offset).encode()))
                if rec.key:
                    ev.set_content(b"__key__", sb.copy_string(rec.key))
        group.set_tag(b"__source__", b"kafka")
        pqm = self.context.process_queue_manager
        if pqm is None:
            return False
        while self._running:
            if pqm.push_queue(self.context.process_queue_key, group):
                return True
            # backpressure can outlast the group session timeout — keep
            # heartbeating so the coordinator doesn't evict us mid-stall
            if cons is not None:
                try:
                    cons._maybe_heartbeat()
                except Exception:  # noqa: BLE001
                    pass
            time.sleep(0.01)
        return False
