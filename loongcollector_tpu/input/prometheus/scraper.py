"""Prometheus scrape scheduling.

Reference: core/prometheus/PrometheusInputRunner.h:33 + schedulers/ —
TargetSubscriberScheduler (HTTP service discovery subscription) and
per-target ScrapeScheduler on a shared timer; StreamScraper pushes parsed
chunks straight into process queues (component/StreamScraper.cpp:119).

Here: a runner thread schedules static targets (and optional HTTP SD
refresh) with per-target jitter; scrapes via http.client; bodies parse
through text_parser into metric groups; relabel configs apply to both
target and sample labels.
"""

from __future__ import annotations

import hashlib
import http.client
import threading
import time
from typing import Any, Dict, List, Optional
from urllib.parse import urlparse

from ...models import PipelineEventGroup
from ...monitor import ledger
from ...pipeline.plugin.interface import Input, PluginContext
from ...utils.logger import get_logger
from .relabel import RelabelConfigList, relabel_metric_event
from .text_parser import parse_exposition

log = get_logger("prometheus")


def _ledger_scrape_drop(pqm, key: int, group: PipelineEventGroup,
                        reason: str) -> None:
    """A scrape group refused at the admit gate never crossed ``ingest``
    (push_queue only ledgers admitted groups), so the discard records an
    ingest+drop PAIR: the loss is visible in the boundary matrix and
    reason-tagged while the conservation residual stays zero by design."""
    if not ledger.is_on():
        return
    q = pqm.get_queue(key)
    if q is not None:
        pipeline = q.pipeline_name
    else:
        # pipeline removed mid-scrape: the queue is gone — the manager's
        # tombstone keeps the loss attributable to the right pipeline
        pipeline = getattr(pqm, "retired_pipeline_name",
                           lambda _k: "")(key)
    ledger.record(pipeline, ledger.B_INGEST, len(group), group.data_size(),
                  tag="scrape_refused")
    ledger.record(pipeline, ledger.B_DROP, len(group), group.data_size(),
                  tag=reason)


class ScrapeTarget:
    def __init__(self, url: str, labels: Optional[Dict[str, str]] = None):
        self.url = url
        self.labels = labels or {}
        # deterministic jitter spreads targets across the interval
        self.jitter = int(hashlib.md5(url.encode()).hexdigest()[:4], 16) / 0xFFFF
        self.last_scrape = 0.0
        self.up = False
        self.discovered = False  # came from HTTP SD (replaced on refresh)

    def due(self, now: float, interval: float) -> bool:
        if self.last_scrape == 0.0:
            # phase-shift the first scrape so targets spread uniformly over
            # the interval, then use the elapsed-time rule
            self.last_scrape = now - interval * (1.0 - self.jitter)
            return False
        return now - self.last_scrape >= interval


class ScrapeJob:
    def __init__(self, name: str, config: Dict[str, Any], queue_key: int):
        self.name = name
        self.queue_key = queue_key
        self.interval = float(config.get("ScrapeIntervalSeconds", 30))
        self.timeout = float(config.get("ScrapeTimeoutSeconds", 10))
        self.metric_relabel = RelabelConfigList(
            config.get("MetricRelabelConfigs", []))
        # target relabeling applies at discovery time (reference
        # TargetSubscriberScheduler + Relabel.cpp)
        self.target_relabel = RelabelConfigList(
            config.get("RelabelConfigs", []))
        self.targets: List[ScrapeTarget] = []
        for t in config.get("StaticTargets", config.get("Targets", [])):
            if isinstance(t, str):
                self.targets.append(ScrapeTarget(_normalize_url(t)))
            else:
                self.targets.append(ScrapeTarget(
                    _normalize_url(t.get("url", t.get("Host", ""))),
                    t.get("labels", {})))
        # HTTP service discovery (http_sd format: a JSON list of
        # {"targets": [...], "labels": {...}} groups)
        self.sd_url: str = config.get("HttpSDUrl", "")
        self.sd_interval = float(config.get("HttpSDIntervalSeconds", 60))
        self.last_sd = 0.0

    def refresh_sd(self, fetch) -> None:
        """Re-pull discovery targets; static targets are kept, discovered
        ones replaced (keyed by URL so jitter/last_scrape state persists)."""
        import json as _json
        body, ok = fetch(self.sd_url, self.timeout)
        if not ok:
            return
        try:
            groups = _json.loads(body)
        except ValueError:
            log.warning("bad http_sd payload from %s", self.sd_url)
            return
        def target_key(url, labels):
            return (url, tuple(sorted(labels.items())))

        existing = {target_key(t.url, t.labels): t
                    for t in self.targets if t.discovered}
        fresh: List[ScrapeTarget] = []
        seen = set()
        for grp in groups if isinstance(groups, list) else []:
            labels = {str(k): str(v)
                      for k, v in (grp.get("labels") or {}).items()}
            for addr in grp.get("targets", []):
                labels2 = dict(labels)
                # the per-target address overrides any group-level
                # __address__ (prometheus semantics); relabel may rewrite it
                labels2["__address__"] = str(addr)
                out = self.target_relabel.process(labels2)
                if out is None:
                    continue  # dropped by relabel
                url = _normalize_url(out.pop("__address__", str(addr)))
                # internal __meta_* / __* labels never reach sample output
                out = {k: v for k, v in out.items() if not k.startswith("__")}
                key = target_key(url, out)
                if key in seen:
                    continue  # exact duplicate (same address AND labelset)
                seen.add(key)
                t = existing.get(key)
                if t is None:
                    t = ScrapeTarget(url, out)
                    t.discovered = True
                fresh.append(t)
        self.targets = [t for t in self.targets if not t.discovered] + fresh


def _normalize_url(t: str) -> str:
    if t.startswith("http://") or t.startswith("https://"):
        return t
    return f"http://{t}/metrics"


class StreamScraper:
    """Streaming scrape: response bytes flow through line-aligned chunks
    into bounded event groups pushed mid-scrape (reference
    component/StreamScraper.cpp:119 — the body is never buffered whole, so
    a 100 MB federate endpoint cannot balloon the agent RSS).

    feed() keeps the trailing partial line; every MAX_GROUP_SAMPLES parsed
    samples (or MAX_GROUP_BYTES raw bytes) one group ships with a
    stream-index tag; finish() flushes the tail and appends the scrape
    auto-metrics (up, scrape_duration_seconds, scrape_samples_scraped)."""

    MAX_GROUP_SAMPLES = 512
    MAX_GROUP_BYTES = 1 << 20

    def __init__(self, job: "ScrapeJob", target: ScrapeTarget, push):
        self.job = job
        self.target = target
        self.push = push
        self._tail = b""
        self._group: Optional[PipelineEventGroup] = None
        self._group_bytes = 0
        self.stream_index = 0
        self.samples_scraped = 0
        self.raw_size = 0

    def feed(self, chunk: bytes) -> None:
        self.raw_size += len(chunk)
        data = self._tail + chunk
        nl = data.rfind(b"\n")
        if nl < 0:
            self._tail = data
            return
        complete, self._tail = data[: nl + 1], data[nl + 1:]
        self._parse_into_group(complete)

    def finish(self, duration_s: float, up: bool) -> None:
        if self._tail and up:
            # a failed scrape's tail may be truncated mid-number — shipping
            # it would emit a corrupt-but-plausible sample next to up=0
            self._parse_into_group(self._tail + b"\n")
        self._tail = b""
        self._flush_group()
        # auto-metrics ride their own group and are EXEMPT from
        # metric_relabel_configs (prometheus never relabels synthetic
        # series — a keep rule must not break target-health alerting)
        from ...models import SourceBuffer
        group = PipelineEventGroup(SourceBuffer())
        sb = group.source_buffer
        now = int(time.time())
        for name, value in ((b"up", 1.0 if up else 0.0),
                            (b"scrape_duration_seconds", duration_s),
                            (b"scrape_samples_scraped",
                             float(self.samples_scraped))):
            ev = group.add_metric_event(now)
            ev.set_name(sb.copy_string(name))
            ev.set_value(value)
            for k, v in self.target.labels.items():
                ev.set_tag(sb.copy_string(k), sb.copy_string(v))
        group.set_tag(b"job", self.job.name)
        group.set_tag(b"__stream_index__", str(self.stream_index))
        self.stream_index += 1
        self.push(self.job.queue_key, group)

    # -- internals ----------------------------------------------------------

    def _ensure_group(self) -> PipelineEventGroup:
        if self._group is None:
            from ...models import SourceBuffer
            self._group = PipelineEventGroup(SourceBuffer())
            self._group_bytes = 0
        return self._group

    def _parse_into_group(self, data: bytes) -> None:
        # batch by LINES so group sizes respect MAX_GROUP_SAMPLES even when
        # one network read carries thousands of samples
        lines = data.split(b"\n")
        if lines and lines[-1] == b"":
            lines.pop()
        i = 0
        while i < len(lines):
            group = self._ensure_group()
            room = max(self.MAX_GROUP_SAMPLES - len(group.events), 1)
            batch = lines[i:i + room]
            i += len(batch)
            block = b"\n".join(batch) + b"\n"
            before = len(group.events)
            parse_exposition(block, group=group)
            self.samples_scraped += len(group.events) - before
            self._group_bytes += len(block)
            if len(group.events) >= self.MAX_GROUP_SAMPLES or \
                    self._group_bytes >= self.MAX_GROUP_BYTES:
                self._flush_group()

    def _flush_group(self) -> None:
        group = self._group
        self._group = None
        if group is None:
            return
        self._apply_labels(group)
        if group.empty():
            return    # every sample relabel-dropped: nothing to push
        group.set_tag(b"job", self.job.name)
        group.set_tag(b"__stream_index__", str(self.stream_index))
        self.stream_index += 1
        self.push(self.job.queue_key, group)

    def _apply_labels(self, group: PipelineEventGroup) -> None:
        job, target = self.job, self.target
        if not (job.metric_relabel.rules or target.labels):
            return
        sb = group.source_buffer
        group._events = [
            ev for ev in group.events
            if relabel_metric_event(ev, sb, job.metric_relabel,
                                    extra_labels=target.labels)]


class PrometheusInputRunner:
    _instance: Optional["PrometheusInputRunner"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._jobs: Dict[str, ScrapeJob] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self.process_queue_manager = None
        self.dropped_groups = 0   # watermark-rejected past the pace window

    @classmethod
    def instance(cls) -> "PrometheusInputRunner":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def register(self, job: ScrapeJob) -> None:
        with self._lock:
            self._jobs[job.name] = job

    def unregister(self, name: str) -> None:
        with self._lock:
            self._jobs.pop(name, None)

    def start(self) -> None:
        with self._lock:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(target=self._run, name="prometheus",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=3)
            self._thread = None

    def _run(self) -> None:
        while self._running:
            time.sleep(0.5)
            with self._lock:
                jobs = list(self._jobs.values())
            now = time.monotonic()
            for job in jobs:
                if job.sd_url and now - job.last_sd >= job.sd_interval:
                    job.last_sd = now
                    try:
                        job.refresh_sd(self._fetch)
                    except Exception:  # noqa: BLE001
                        log.exception("http_sd refresh failed: %s", job.sd_url)
                for target in list(job.targets):
                    if target.due(now, job.interval):
                        target.last_scrape = now
                        try:
                            self.scrape_one(job, target)
                        except Exception:  # noqa: BLE001
                            log.exception("scrape failed: %s", target.url)

    def scrape_one(self, job: ScrapeJob, target: ScrapeTarget) -> None:
        pqm = self.process_queue_manager

        def push(key, group):
            if pqm is None:
                return
            # pace on the watermark like the file input does: a slow
            # pipeline back-pressures the scrape instead of silently
            # dropping mid-stream groups
            deadline = time.monotonic() + job.timeout
            while not pqm.push_queue(key, group):
                if pqm.get_queue(key) is None:
                    # pipeline removed mid-scrape: the queue is gone, not
                    # full — waiting would stall every job on this thread
                    self.dropped_groups += 1
                    _ledger_scrape_drop(pqm, key, group, "pipeline_removed")
                    return
                if time.monotonic() > deadline:
                    self.dropped_groups += 1
                    _ledger_scrape_drop(pqm, key, group, "scrape_shed")
                    log.warning("scrape group dropped: queue %d full", key)
                    return
                time.sleep(0.01)

        scraper = StreamScraper(job, target, push)
        t0 = time.monotonic()
        ok = self._fetch_stream(target.url, job.timeout, scraper.feed)
        target.up = ok
        scraper.finish(time.monotonic() - t0, ok)

    @staticmethod
    def _fetch_stream(url: str, timeout: float, sink) -> bool:
        """Chunked GET: every read lands in `sink` immediately (the
        StreamScraper), so the body is never held whole."""
        conn = None
        try:
            u = urlparse(url)
            conn_cls = (http.client.HTTPSConnection if u.scheme == "https"
                        else http.client.HTTPConnection)
            conn = conn_cls(u.netloc, timeout=timeout)
            path = u.path or "/metrics"
            if u.query:
                path += "?" + u.query
            conn.request("GET", path,
                         headers={"Accept": "text/plain", "User-Agent":
                                  "loongcollector-tpu/0.1"})
            resp = conn.getresponse()
            ok = 200 <= resp.status < 300
            while True:
                chunk = resp.read(64 * 1024)
                if not chunk:
                    break
                if ok:
                    sink(chunk)
            return ok
        except (OSError, http.client.HTTPException):
            return False
        finally:
            if conn is not None:
                conn.close()

    @classmethod
    def _fetch(cls, url: str, timeout: float):
        """Buffered GET (service-discovery payloads): same connection path
        as the streaming fetch, with an accumulate-all sink."""
        chunks: List[bytes] = []
        ok = cls._fetch_stream(url, timeout, chunks.append)
        return b"".join(chunks), ok


class InputPrometheus(Input):
    name = "input_prometheus"
    is_singleton = True

    def __init__(self) -> None:
        super().__init__()
        self.job: Optional[ScrapeJob] = None

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        scrape_config = config.get("ScrapeConfig", config)
        self.job = ScrapeJob(
            scrape_config.get("job_name", context.pipeline_name),
            scrape_config, context.process_queue_key)
        return bool(self.job.targets or self.job.sd_url)

    def start(self) -> bool:
        runner = PrometheusInputRunner.instance()
        self.job.queue_key = self.context.process_queue_key
        runner.register(self.job)
        runner.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        if self.job:
            PrometheusInputRunner.instance().unregister(self.job.name)
        return True
