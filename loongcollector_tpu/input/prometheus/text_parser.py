"""Prometheus exposition-format text parser.

Reference: core/prometheus/labels/TextParser.cpp — parses scrape bodies
(`metric{label="v",...} value [timestamp]`) into MetricEvents.  Vectorised
first pass (line split via the native/numpy splitter), then a compact
per-line FSM for the label block; HELP/TYPE/comment lines are skipped.
"""

from __future__ import annotations

import math
import time
from typing import List, Optional, Tuple

import numpy as np

from ...models import PipelineEventGroup, SourceBuffer


def _parse_labels(seg: bytes) -> Optional[List[Tuple[bytes, bytes]]]:
    """Parses `name="value",...` (no surrounding braces)."""
    out: List[Tuple[bytes, bytes]] = []
    i, n = 0, len(seg)
    while i < n:
        while i < n and seg[i] in b" \t,":
            i += 1
        if i >= n:
            break
        eq = seg.find(b"=", i)
        if eq < 0:
            return None
        name = seg[i:eq].strip()
        i = eq + 1
        if i >= n or seg[i] != 0x22:  # '"'
            return None
        i += 1
        val = bytearray()
        while i < n:
            c = seg[i]
            if c == 0x5C and i + 1 < n:  # backslash escape
                nxt = seg[i + 1]
                if nxt == 0x6E:  # \n
                    val.append(0x0A)
                else:
                    val.append(nxt)
                i += 2
                continue
            if c == 0x22:
                break
            val.append(c)
            i += 1
        if i >= n or seg[i] != 0x22:
            return None
        i += 1
        out.append((bytes(name), bytes(val)))
    return out


def parse_value(tok: bytes) -> Optional[float]:
    t = tok.strip().lower()
    if t in (b"nan",):
        return math.nan
    if t in (b"+inf", b"inf"):
        return math.inf
    if t == b"-inf":
        return -math.inf
    try:
        return float(tok)
    except ValueError:
        return None


def parse_exposition(body: bytes, default_ts: Optional[int] = None,
                     group: Optional[PipelineEventGroup] = None
                     ) -> PipelineEventGroup:
    """Scrape body → MetricEvent group (one event per sample)."""
    if group is None:
        group = PipelineEventGroup(SourceBuffer(len(body) + 1024))
    sb = group.source_buffer
    now = default_ts if default_ts is not None else int(time.time())
    for line in body.split(b"\n"):
        line = line.strip()
        if not line or line.startswith(b"#"):
            continue
        # metric name ends at '{' or whitespace
        brace = line.find(b"{")
        labels: List[Tuple[bytes, bytes]] = []
        if brace >= 0:
            close = line.rfind(b"}")
            if close < brace:
                continue
            name = line[:brace].strip()
            parsed = _parse_labels(line[brace + 1 : close])
            if parsed is None:
                continue
            labels = parsed
            rest = line[close + 1 :].split()
        else:
            parts = line.split()
            if len(parts) < 2:
                continue
            name = parts[0]
            rest = parts[1:]
        if not rest or not name:
            continue
        value = parse_value(rest[0])
        if value is None:
            continue
        ts = now
        if len(rest) > 1:
            try:
                ts = int(rest[1]) // 1000  # exposition ts is milliseconds
            except ValueError:
                pass
        ev = group.add_metric_event(ts)
        ev.set_name(sb.copy_string(name))
        ev.set_value(value)
        for k, v in labels:
            ev.set_tag(sb.copy_string(k), sb.copy_string(v))
    return group
