"""Prometheus relabel_config semantics.

Reference: core/prometheus/labels/Relabel.cpp — full relabel actions:
replace, keep, drop, keepequal, dropequal, hashmod, labelmap, labeldrop,
labelkeep.  Applied to scrape-discovery targets and to sample labels
(ProcessorPromRelabelMetricNative).
"""

from __future__ import annotations

import hashlib
import re
from typing import Dict, List, Optional


KNOWN_ACTIONS = frozenset({
    "replace", "keep", "drop", "keepequal", "dropequal", "hashmod",
    "lowercase", "uppercase", "labelmap", "labeldrop", "labelkeep",
    "dropmetric",
})


class RelabelUnsupported(ValueError):
    """Config names an action this implementation does not have.  Raised at
    CONFIG time: silently passing labels through an unknown action would
    surface as data corruption, not an error (reference Relabel.cpp returns
    Action::UNDEFINED and fails the config load)."""


class RelabelRule:
    def __init__(self, config: dict):
        self.source_labels: List[str] = list(config.get("source_labels", []))
        self.separator: str = config.get("separator", ";")
        self.target_label: str = config.get("target_label", "")
        self.regex = re.compile(config.get("regex", "(.*)"))
        self.modulus: int = int(config.get("modulus", 0) or 0)
        self.replacement: str = config.get("replacement", "$1")
        self.action: str = config.get("action", "replace").lower()
        if self.action not in KNOWN_ACTIONS:
            raise RelabelUnsupported(
                f"unknown relabel action {self.action!r}")
        # dropmetric (reference extension): drop the sample when its
        # __name__ is in match_list
        self.match_list = set(config.get("match_list", []))
        if self.action == "dropmetric":
            if not self.match_list:
                raise RelabelUnsupported("dropmetric requires match_list")
            self.source_labels = ["__name__"]
        if self.action in ("lowercase", "uppercase", "hashmod") \
                and not self.target_label:
            # an empty target would silently create a label named "" —
            # prometheus requires target_label for these actions
            raise RelabelUnsupported(
                f"{self.action} requires target_label")

    def _concat(self, labels: Dict[str, str]) -> str:
        return self.separator.join(labels.get(k, "") for k in self.source_labels)

    def apply(self, labels: Dict[str, str]) -> Optional[Dict[str, str]]:
        """Returns updated labels, or None if the target is dropped."""
        val = self._concat(labels)
        act = self.action
        if act == "keep":
            return labels if self.regex.fullmatch(val) else None
        if act == "drop":
            return None if self.regex.fullmatch(val) else labels
        if act == "keepequal":
            return labels if val == labels.get(self.target_label, "") else None
        if act == "dropequal":
            return None if val == labels.get(self.target_label, "") else labels
        if act == "replace":
            m = self.regex.fullmatch(val)
            if m is None:
                return labels
            target = _expand(self.target_label or "$0", m)
            replacement = _expand(self.replacement, m)
            out = dict(labels)
            if target:
                if replacement:
                    out[target] = replacement
                else:
                    out.pop(target, None)
            return out
        if act == "lowercase":
            out = dict(labels)
            out[self.target_label] = val.lower()
            return out
        if act == "uppercase":
            out = dict(labels)
            out[self.target_label] = val.upper()
            return out
        if act == "dropmetric":
            return None if val in self.match_list else labels
        if act == "hashmod":
            if self.modulus <= 0:
                return labels
            h = int.from_bytes(
                hashlib.md5(val.encode()).digest()[-8:], "big")
            out = dict(labels)
            out[self.target_label] = str(h % self.modulus)
            return out
        if act == "labelmap":
            out = dict(labels)
            for k, v in labels.items():
                m = self.regex.fullmatch(k)
                if m:
                    out[_expand(self.replacement, m)] = v
            return out
        if act == "labeldrop":
            return {k: v for k, v in labels.items()
                    if not self.regex.fullmatch(k)}
        if act == "labelkeep":
            return {k: v for k, v in labels.items()
                    if self.regex.fullmatch(k)}
        return labels


def _expand(template: str, m: "re.Match") -> str:
    """$1 / ${1} style expansion."""
    def sub(mm):
        idx = mm.group(1) or mm.group(2)
        try:
            return m.group(int(idx)) or ""
        except (IndexError, ValueError):
            return ""
    return re.sub(r"\$(?:(\d+)|\{(\d+)\})", sub, template)


class RelabelConfigList:
    def __init__(self, configs: List[dict]):
        self.rules = [RelabelRule(c) for c in (configs or [])]

    def process(self, labels: Dict[str, str]) -> Optional[Dict[str, str]]:
        for rule in self.rules:
            labels = rule.apply(labels)
            if labels is None:
                return None
        return labels


def relabel_metric_event(ev, sb, rules: "RelabelConfigList",
                         extra_labels=None, scrub_meta: bool = False) -> bool:
    """Apply relabel rules to one MetricEvent in place.

    Shared by the stream scraper and processor_prom_relabel_metric_native so
    the decode/__name__-expose/rename/re-tag semantics cannot diverge.
    Returns False when the sample is dropped by the rules."""
    labels = {k.decode("utf-8", "replace"): str(v)
              for k, v in ev.tags.items()}
    if extra_labels:
        labels.update(extra_labels)
    if getattr(ev, "name", None) is not None:
        labels.setdefault("__name__", ev.name.to_str())
    out = rules.process(labels)
    if out is None:
        return False
    new_name = out.pop("__name__", None)
    if new_name is not None and (
            ev.name is None or new_name != ev.name.to_str()):
        ev.set_name(sb.copy_string(new_name))
    if scrub_meta:
        out = {k: v for k, v in out.items() if not k.startswith("__")}
    ev.tags.clear()
    for k, v in out.items():
        ev.set_tag(sb.copy_string(k), sb.copy_string(v))
    return True
