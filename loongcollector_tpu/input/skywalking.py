"""input_skywalking — SkyWalking v3 trace segment ingest (gRPC).

Reference: plugins/input/skywalkingv3/ — gRPC receivers for the SkyWalking
agent data-collect protocol.  This input serves the trace surface:
`/skywalking.v3.TraceSegmentReportService/collect` (client-streaming
SegmentObject) plus the JVM-free management no-ops agents probe
(`ManagementService/keepAlive` style calls answered with an empty
Commands message).

SegmentObject wire decode (language-agnostic data-collect-protocol):

  SegmentObject { traceId=1, traceSegmentId=2, spans=3, service=4,
                  serviceInstance=5 }
  SpanObject    { spanId=1, parentSpanId=2, startTime=3(ms), endTime=4(ms),
                  refs=5, operationName=6, peer=7, spanType=8, spanLayer=9,
                  componentId=10, isError=11, tags=12, logs=13 }
  KeyStringValuePair { key=1, value=2 }

Spans become native SpanEvents (models/events.py) so downstream
processors/serializers treat SkyWalking traffic like any other trace
source.  Decoding reuses the generic proto reader (config/agent_v2_pb).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..config.agent_v2_pb import iter_fields
from ..models import PipelineEventGroup
from ..models.events import SpanEvent
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger

log = get_logger("skywalking")

try:
    import grpc
except ImportError:  # pragma: no cover
    grpc = None

# SpanType: Entry=0 (server), Exit=1 (client), Local=2
_KIND_MAP = {0: SpanEvent.Kind.SERVER, 1: SpanEvent.Kind.CLIENT,
             2: SpanEvent.Kind.INTERNAL}


def _parse_kv(data: bytes):
    k = v = b""
    for f, wt, val in iter_fields(data):
        if f == 1 and wt == 2:
            k = bytes(val)
        elif f == 2 and wt == 2:
            v = bytes(val)
    return k, v


def decode_segment(data: bytes) -> PipelineEventGroup:
    """One SegmentObject → one group of SpanEvents."""
    group = PipelineEventGroup()
    trace_id = b""
    segment_id = b""
    service = b""
    instance = b""
    raw_spans: List[bytes] = []
    for f, wt, v in iter_fields(data):
        if f == 1 and wt == 2:
            trace_id = bytes(v)
        elif f == 2 and wt == 2:
            segment_id = bytes(v)
        elif f == 3 and wt == 2:
            raw_spans.append(bytes(v))
        elif f == 4 and wt == 2:
            service = bytes(v)
        elif f == 5 and wt == 2:
            instance = bytes(v)
    if service:
        group.set_tag(b"service.name", service)
    if instance:
        group.set_tag(b"service.instance", instance)
    for raw in raw_spans:
        span_id = parent_id = 0
        start_ms = end_ms = 0
        name = peer = b""
        span_type = 0     # proto3 default: absent field = Entry (server)
        is_error = False
        tags: List = []
        for f, wt, v in iter_fields(raw):
            if f == 1 and wt == 0:
                span_id = v
            elif f == 2 and wt == 0:
                # parentSpanId is -1 for root spans (signed varint)
                parent_id = v - (1 << 64) if v >= (1 << 63) else v
            elif f == 3 and wt == 0:
                start_ms = v
            elif f == 4 and wt == 0:
                end_ms = v
            elif f == 6 and wt == 2:
                name = bytes(v)
            elif f == 7 and wt == 2:
                peer = bytes(v)
            elif f == 8 and wt == 0:
                span_type = v
            elif f == 11 and wt == 0:
                is_error = bool(v)
            elif f == 12 and wt == 2:
                tags.append(_parse_kv(bytes(v)))
        ev = SpanEvent(timestamp=start_ms // 1000)
        ev.trace_id = trace_id
        ev.span_id = b"%s-%d" % (segment_id, span_id)
        if parent_id >= 0:
            ev.parent_span_id = b"%s-%d" % (segment_id, parent_id)
        ev.name = name
        ev.kind = _KIND_MAP.get(span_type, SpanEvent.Kind.UNSPECIFIED)
        ev.start_time_ns = start_ms * 1_000_000
        ev.end_time_ns = end_ms * 1_000_000
        ev.status = (SpanEvent.Status.ERROR if is_error
                     else SpanEvent.Status.OK)
        if peer:
            ev.set_attribute(b"net.peer.name", peer)
        for k, v in tags:
            ev.set_attribute(k, v)
        group.events.append(ev)
    return group


class InputSkywalking(Input):
    name = "input_skywalking"

    def __init__(self) -> None:
        super().__init__()
        self.address = "0.0.0.0:11800"
        self._server = None
        self._port = 0

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.address = config.get("Address", self.address)
        host, sep, port = self.address.rpartition(":")
        if not sep or not port.isdigit():
            return False
        self._host, self._bind_port = host, int(port)
        return grpc is not None

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> bool:
        if grpc is None:
            log.error("grpcio unavailable; input_skywalking disabled")
            return False
        inp = self

        def collect(request_iterator, context):
            n = 0
            for raw in request_iterator:
                try:
                    group = decode_segment(raw)
                except ValueError:
                    continue
                if len(group):
                    pqm = inp.context.process_queue_manager
                    if pqm is not None:
                        pqm.push_queue(inp.context.process_queue_key, group)
                        n += 1
            log.debug("skywalking collect: %d segments", n)
            return b""    # empty Commands message

        def keep_alive(request: bytes, context) -> bytes:
            return b""    # empty Commands

        raw_codec = dict(request_deserializer=lambda b: b,
                         response_serializer=lambda b: b)
        trace_svc = grpc.method_handlers_generic_handler(
            "skywalking.v3.TraceSegmentReportService",
            {"collect": grpc.stream_unary_rpc_method_handler(
                collect, **raw_codec)})
        mgmt_svc = grpc.method_handlers_generic_handler(
            "skywalking.v3.ManagementService",
            {"reportInstanceProperties": grpc.unary_unary_rpc_method_handler(
                keep_alive, **raw_codec),
             "keepAlive": grpc.unary_unary_rpc_method_handler(
                keep_alive, **raw_codec)})
        from concurrent.futures import ThreadPoolExecutor
        self._server = grpc.server(thread_pool=ThreadPoolExecutor(
            max_workers=4))
        self._server.add_generic_rpc_handlers((trace_svc, mgmt_svc))
        bound = self._server.add_insecure_port(
            f"{self._host}:{self._bind_port}")
        if bound == 0:
            log.error("skywalking bind %s failed", self.address)
            return False
        self._port = bound
        self._server.start()
        log.info("skywalking v3 gRPC listening on %s:%d", self._host, bound)
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        if self._server is not None:
            self._server.stop(grace=1)
            self._server = None
        return True
