"""input_redis — Redis INFO metrics polling.

Reference: plugins/input/redis (go-redis INFO collector). Speaks RESP
directly over a socket: optional AUTH, then `INFO <section>` on an
interval; numeric fields of the reply become MetricEvents tagged with the
target address (matching the Go plugin's field mapping).
"""

from __future__ import annotations

import socket
import time
from typing import Any, Dict, List

from ..models import MetricValue, PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext
from ..utils.logger import get_logger
from ..utils.net import host_port
from .polling_base import PollingInput

log = get_logger("redis")


def _read_reply(sock: socket.socket) -> bytes:
    """One RESP reply (simple string / error / integer / bulk)."""
    buf = b""
    while b"\r\n" not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            raise OSError("connection closed")
        buf += chunk
    head, rest = buf.split(b"\r\n", 1)
    kind = head[:1]
    if kind in (b"+", b":"):
        return head[1:]
    if kind == b"-":
        raise OSError(f"redis error: {head[1:].decode(errors='replace')}")
    if kind == b"$":
        n = int(head[1:])
        if n < 0:
            return b""
        while len(rest) < n + 2:
            chunk = sock.recv(4096)
            if not chunk:
                raise OSError("connection closed mid-bulk")
            rest += chunk
        return rest[:n]
    raise OSError(f"unexpected RESP reply {head[:16]!r}")


def _resp_command(*args: bytes) -> bytes:
    """RESP array framing: argument values are opaque (a password with a
    space or CRLF must not split into extra arguments or inject commands)."""
    out = b"*%d\r\n" % len(args)
    for a in args:
        out += b"$%d\r\n%s\r\n" % (len(a), a)
    return out


def redis_info(host: str, port: int, password: str = "",
               section: str = "", timeout: float = 5.0) -> Dict[str, str]:
    sock = socket.create_connection((host, port), timeout=timeout)
    try:
        if password:
            sock.sendall(_resp_command(b"AUTH", password.encode()))
            _read_reply(sock)
        args = (b"INFO", section.encode()) if section else (b"INFO",)
        sock.sendall(_resp_command(*args))
        raw = _read_reply(sock)
    finally:
        sock.close()
    out: Dict[str, str] = {}
    for line in raw.splitlines():
        if not line or line.startswith(b"#"):
            continue
        k, sep, v = line.partition(b":")
        if sep:
            out[k.decode(errors="replace")] = v.decode(errors="replace")
    return out


class InputRedis(PollingInput):
    name = "input_redis"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.targets: List[str] = list(config.get("Targets", []))
        self.password = config.get("Password", "")
        self.section = config.get("Section", "")
        self.interval = float(config.get("IntervalSecs", 30.0))
        return bool(self.targets)

    def poll_once(self) -> None:
        pqm = self.context.process_queue_manager
        for target in self.targets:
            host, port = host_port(target, 6379)
            try:
                info = redis_info(host, port, self.password, self.section)
            except (OSError, ValueError) as e:
                log.warning("redis poll %s failed: %s", target, e)
                continue
            if pqm is None or not info:
                continue
            group = PipelineEventGroup()
            now = int(time.time())
            for key, val in info.items():
                try:
                    num = float(val)
                except ValueError:
                    continue  # numeric fields only (the Go plugin's choice)
                ev = group.add_metric_event(now)
                ev.name = f"redis_{key}".encode()
                ev.value = MetricValue(num)
                ev.set_tag(b"target", target.encode())
            if len(group):
                group.set_tag(b"__source__", b"redis")
                pqm.push_queue(self.context.process_queue_key, group)
