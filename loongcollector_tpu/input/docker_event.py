"""service_docker_event + metric_debug_file.

Reference: plugins/input/docker/event/input_docker_event.go (Docker
Engine /events stream → _time_nano_/_action_/_type_/_id_ + actor
attributes) and plugins/input/debugfile/input_debug_file.go (read a file
once at init, re-emit its first LineLimit lines each round).

The event stream rides the same AF_UNIX Engine-API transport as
container discovery (container_manager._UnixHTTPConnection).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger
from .polling_base import PollingInput

log = get_logger("docker_event")


class ServiceDockerEvent(Input):
    name = "service_docker_event"

    def __init__(self) -> None:
        super().__init__()
        self._thread: Optional[threading.Thread] = None
        self._running = False

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.ignore_attributes = bool(config.get("IgnoreAttributes", False))
        from ..container_manager import DOCKER_SOCK
        self.sock_path = str(config.get("SocketPath")
                             or os.environ.get("LOONG_DOCKER_SOCK",
                                               DOCKER_SOCK))
        return True

    def start(self) -> bool:
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="docker-events")
        self._thread.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=3)
            self._thread = None
        return True

    def _run(self) -> None:
        import http.client
        backoff = 1.0
        while self._running:
            if not os.path.exists(self.sock_path):
                time.sleep(min(backoff, 30))
                backoff = min(backoff * 2, 30)
                continue
            try:
                self._stream_events()
                backoff = 1.0
            except (OSError, http.client.HTTPException) as e:
                # a flapping daemon raises BadStatusLine/IncompleteRead, not
                # just OSError — either way: drop the connection and back off
                log.warning("docker event stream lost: %s", e)
            time.sleep(min(backoff, 30))
            backoff = min(backoff * 2, 30)

    def _stream_events(self) -> None:
        from ..container_manager import _UnixHTTPConnection
        conn = _UnixHTTPConnection(self.sock_path, timeout=5.0)
        conn.request("GET", "/events")
        resp = conn.getresponse()
        if resp.status != 200:
            conn.close()
            raise OSError(f"/events HTTP {resp.status}")
        buf = b""
        try:
            while self._running:
                try:
                    chunk = resp.read1(65536)
                except TimeoutError:
                    continue       # idle stream — keep waiting
                except socket.timeout:
                    continue
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, _, buf = buf.partition(b"\n")
                    if line.strip():
                        self._emit(line)
        finally:
            conn.close()

    def _emit(self, line: bytes) -> None:
        try:
            msg = json.loads(line)
        except ValueError:
            return
        group = PipelineEventGroup()
        sb = group.source_buffer
        ev = group.add_log_event(int(time.time()))

        def put(k: str, v: str) -> None:
            ev.set_content(sb.copy_string(k.encode()),
                           sb.copy_string(str(v).encode()))

        put("_time_nano_", str(msg.get("timeNano", 0)))
        put("_action_", msg.get("Action", ""))
        put("_type_", msg.get("Type", ""))
        put("_id_", (msg.get("Actor") or {}).get("ID", msg.get("id", "")))
        if not self.ignore_attributes:
            for k, v in ((msg.get("Actor") or {})
                         .get("Attributes") or {}).items():
                put(k, v)
        group.set_tag(b"__source__", b"docker_event")
        pqm = self.context.process_queue_manager
        if pqm is not None:
            pqm.push_queue(self.context.process_queue_key, group)


class InputDebugFile(PollingInput):
    """metric_debug_file: load InputFilePath once (first LineLimit lines),
    emit them as one event per round."""

    name = "metric_debug_file"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.field_name = str(config.get("FieldName", "content"))
        limit = int(config.get("LineLimit", 1000))
        self.interval = int(config.get("IntervalMs", 10000)) / 1000.0
        path = str(config.get("InputFilePath", ""))
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                lines: List[str] = []
                for line in f:
                    lines.append(line.rstrip("\n"))
                    if len(lines) >= limit:
                        break
        except OSError as e:
            log.error("metric_debug_file: %s", e)
            return False
        self._body = "\n".join(lines)
        return True

    def poll_once(self) -> None:
        group = PipelineEventGroup()
        sb = group.source_buffer
        ev = group.add_log_event(int(time.time()))
        ev.set_content(sb.copy_string(self.field_name.encode()),
                       sb.copy_string(self._body.encode()))
        group.set_tag(b"__source__", b"debug_file")
        pqm = self.context.process_queue_manager
        if pqm is not None:
            pqm.push_queue(self.context.process_queue_key, group)
