"""Shared scaffolding for external-agent supervisors (telegraf, jmxfetch).

One place for the lifecycle both managers need: a per-directory singleton
registry, a wake-event supervision loop calling an overridable `_tick()`,
and terminate→kill process teardown.
"""

from __future__ import annotations

import subprocess
import threading
from typing import Dict, Optional

from ..utils.logger import get_logger

log = get_logger("supervisor")


def sanitize_name(name: str, default: str = "cfg") -> str:
    """Config names become filenames: keep [alnum.-_], replace the rest."""
    out = "".join(c if c.isalnum() or c in "-_." else "_"
                  for c in (name or default))
    return out or default


class ProcessSupervisor:
    """Singleton-per-base-dir manager with a wake-driven tick loop."""

    check_interval_s: float = 30.0
    _instances: Dict[str, "ProcessSupervisor"] = {}
    _instances_lock = threading.Lock()

    @classmethod
    def get(cls, base_dir: str) -> "ProcessSupervisor":
        with cls._instances_lock:
            key = f"{cls.__name__}:{base_dir}"
            inst = cls._instances.get(key)
            if inst is None:
                inst = cls._instances[key] = cls(base_dir)
            return inst

    def __init__(self, base_dir: str) -> None:
        self.base_dir = base_dir
        self._lock = threading.Lock()
        self._proc: Optional[subprocess.Popen] = None
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._running = False

    # -- the loop ------------------------------------------------------------

    def _tick(self) -> None:  # pragma: no cover - abstract
        """One supervision round; runs with no locks held."""
        raise NotImplementedError

    def _on_start(self) -> None:
        """Hook: extra threads/servers to start with the loop."""

    def _on_stop(self) -> None:
        """Hook: teardown after the loop exits (process already killed)."""

    def wake(self) -> None:
        self._wake.set()

    def start_loop(self) -> None:
        with self._lock:
            if self._running:
                return
            self._running = True
        # _on_start BEFORE the run loop exists: subclasses snapshot state
        # there (e.g. telegraf's log tail position) that must precede any
        # side effect of the first tick — starting the loop first let the
        # fresh process's own startup output race the snapshot
        try:
            self._on_start()
        except BaseException:
            with self._lock:
                self._running = False   # a failed hook must not wedge
            raise                       # future start_loop() calls
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=type(self).__name__)
        self._thread.start()

    def stop_loop(self) -> None:
        with self._lock:
            self._running = False
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=3)
            self._thread = None
        self.kill_proc()
        self._on_stop()

    @property
    def running(self) -> bool:
        with self._lock:
            return self._running

    def _run(self) -> None:
        while True:
            with self._lock:
                if not self._running:
                    return
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — supervision must survive
                log.exception("%s tick failed", type(self).__name__)
            self._wake.wait(timeout=self.check_interval_s)
            self._wake.clear()

    # -- process management --------------------------------------------------

    def proc_alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def kill_proc(self) -> None:
        if self._proc is not None:
            try:
                self._proc.terminate()
                self._proc.wait(timeout=5)
            except (OSError, subprocess.TimeoutExpired):
                try:
                    self._proc.kill()
                except OSError:
                    pass
            self._proc = None
