"""input_http_server — generic HTTP ingestion endpoint (+ OTLP/HTTP logs).

Reference: plugins/input/httpserver/input_http_server.go (generic HTTP
ingest with per-format decoders) and plugins/input/opentelemetry (OTLP
receiver). One threaded HTTP server per input instance; bodies may be
gzip/deflate-encoded.

Formats:
  * raw    — each non-empty line becomes one event ("content")
  * json   — one JSON object, or an array of objects → one event each
  * ndjson — one JSON object per line
  * influx — Influx line protocol → multi-value MetricEvents (telegraf)
  * statsd — (dog)statsd lines → MetricEvents
  * otlp   — ExportLogsServiceRequest JSON (resourceLogs→scopeLogs→
             logRecords); InputOTLP presets this and the /v1/logs path
"""

from __future__ import annotations

import gzip
import http.server
import json
import threading
import time
import zlib
from typing import Any, Dict, Optional

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger

log = get_logger("http_server")


def _decode_body(headers, body: bytes) -> bytes:
    enc = (headers.get("Content-Encoding") or "").lower()
    if enc == "gzip":
        return gzip.decompress(body)
    if enc == "deflate":
        try:
            return zlib.decompress(body)
        except zlib.error:
            return zlib.decompress(body, -zlib.MAX_WBITS)  # raw deflate
    return body


def _obj_event(group: PipelineEventGroup, obj: Dict[str, Any],
               ts: int) -> None:
    sb = group.source_buffer
    ev = group.add_log_event(int(obj.get("__time__", ts)))
    for k, v in obj.items():
        if k == "__time__":
            continue
        val = v if isinstance(v, str) else json.dumps(v, ensure_ascii=False)
        ev.set_content(sb.copy_string(str(k).encode()),
                       sb.copy_string(val.encode()))


def parse_body(fmt: str, body: bytes, group: PipelineEventGroup) -> int:
    """Decoded body → events in `group`; returns the event count."""
    now = int(time.time())
    sb = group.source_buffer
    n = 0
    if fmt == "raw":
        for line in body.splitlines():
            if line:
                ev = group.add_log_event(now)
                ev.set_content(b"content", sb.copy_string(line))
                n += 1
    elif fmt == "json":
        data = json.loads(body)
        for obj in (data if isinstance(data, list) else [data]):
            _obj_event(group, obj, now)
            n += 1
    elif fmt == "ndjson":
        for line in body.splitlines():
            if line.strip():
                _obj_event(group, json.loads(line), now)
                n += 1
    elif fmt == "otlp":
        data = json.loads(body)
        for rl in data.get("resourceLogs", []):
            rattrs = {a["key"]: _attr_val(a.get("value", {}))
                      for a in rl.get("resource", {}).get("attributes", [])}
            for sl in rl.get("scopeLogs", []):
                for rec in sl.get("logRecords", []):
                    ev = group.add_log_event(
                        int(int(rec.get("timeUnixNano", 0)) // 1_000_000_000)
                        or now)
                    body_v = rec.get("body", {})
                    ev.set_content(b"content", sb.copy_string(
                        str(_attr_val(body_v)).encode()))
                    sev = rec.get("severityText")
                    if sev:
                        ev.set_content(b"severity",
                                       sb.copy_string(sev.encode()))
                    for a in rec.get("attributes", []):
                        ev.set_content(
                            sb.copy_string(a["key"].encode()),
                            sb.copy_string(
                                str(_attr_val(a.get("value", {}))).encode()))
                    for k, v in rattrs.items():
                        ev.set_content(sb.copy_string(f"resource.{k}".encode()),
                                       sb.copy_string(str(v).encode()))
                    n += 1
    elif fmt in ("influx", "influxdb"):
        from .metric_protocols import parse_influx_lines
        n = parse_influx_lines(body, group)
    elif fmt == "statsd":
        from .metric_protocols import parse_statsd_packet
        n = parse_statsd_packet(body, group)
    else:
        raise ValueError(f"unknown format {fmt!r}")
    return n


def _attr_val(v: Dict[str, Any]):
    for key in ("stringValue", "intValue", "doubleValue", "boolValue"):
        if key in v:
            return v[key]
    return json.dumps(v, ensure_ascii=False) if v else ""


class InputHTTPServer(Input):
    name = "input_http_server"
    default_format = "json"
    default_address = "0.0.0.0:12345"

    def __init__(self) -> None:
        super().__init__()
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.fmt = (config.get("Format") or self.default_format).lower()
        self.address = config.get("Address", self.default_address)
        # a decoder EXTENSION ref (reference ext_default_decoder) overrides
        # the built-in Format parsing
        dec_ref = config.get("Decoder")
        self._decoder_ext = (context.get_extension(str(dec_ref))
                             if dec_ref else None)
        if dec_ref and self._decoder_ext is None:
            return False
        host, sep, port = self.address.rpartition(":")
        if not sep or not port.isdigit():
            log.error("%s Address must be host:port, got %r",
                      self.name, self.address)
            return False
        self._host, self._port = host, int(port)
        return self.fmt in ("raw", "json", "ndjson", "otlp",
                            "influx", "influxdb", "statsd")

    def start(self) -> bool:
        inp = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                try:
                    body = _decode_body(self.headers, body)
                    if inp._decoder_ext is not None:
                        groups = inp._decoder_ext.decode(body, self.headers)
                        count = sum(len(g) for g in groups)
                        group = groups[0] if groups else PipelineEventGroup()
                    else:
                        group = PipelineEventGroup()
                        count = parse_body(inp.fmt, body, group)
                        groups = [group]
                except Exception as e:  # noqa: BLE001 — corrupt gzip raises
                    # EOFError/zlib.error, bad JSON shapes AttributeError/
                    # KeyError: ALL malformed input is a client 400, never
                    # a handler crash
                    self.send_response(400)
                    self.end_headers()
                    self.wfile.write(str(e).encode()[:200])
                    return
                pqm = inp.context.process_queue_manager
                ok = True
                if count and pqm is not None:
                    for g in groups:
                        g.set_tag(b"__source__", self.client_address[0]
                                  .encode())
                        ok = pqm.push_queue(inp.context.process_queue_key,
                                            g) and ok
                self.send_response(200 if ok else 429)
                self.end_headers()
                self.wfile.write(b"{}" if ok else b"busy")

            def log_message(self, *a):
                pass

        try:
            self._server = http.server.ThreadingHTTPServer(
                (self._host, self._port), Handler)
        except OSError as e:
            log.error("%s bind %s failed: %s", self.name, self.address, e)
            return False
        self._port = self._server.server_port   # resolves port 0 for tests
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name=self.name, daemon=True)
        self._thread.start()
        return True

    @property
    def port(self) -> int:
        return self._port

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        return True


class InputOTLP(InputHTTPServer):
    """OTLP/HTTP logs receiver (plugins/input/opentelemetry)."""

    name = "input_otlp"
    default_format = "otlp"
    default_address = "0.0.0.0:4318"
