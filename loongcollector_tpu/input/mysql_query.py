"""service_mysql — periodic SQL collection with column checkpointing.

Reference: plugins/input/mysql/mysql.go (DSN + StateMent with optional
``?`` checkpoint placeholder, CheckPointColumn int/time, PageSize
pagination via LIMIT, MaxSyncSize) over the shared rdb shape
(plugins/input/rdb/rdb.go → rdb_base.RdbPollingInput here).

The wire client is the repo's own MySQL protocol implementation
(binlog_protocol.py: handshake + mysql_native_password + COM_QUERY text
result sets) — no external driver.
"""

from __future__ import annotations

import socket
from typing import List, Optional, Tuple

from . import binlog_protocol as bp
from .rdb_base import RdbPollingInput


class MySQLQueryClient:
    """Minimal connection wrapper: connect/auth once, COM_QUERY many."""

    def __init__(self, host: str, port: int, user: str, password: str,
                 database: str = "", connect_timeout: float = 5.0,
                 read_timeout: float = 30.0):
        self.host, self.port = host, port
        self.user, self.password = user, password
        self.database = database
        self.connect_timeout = connect_timeout
        self.read_timeout = read_timeout
        self._sock: Optional[socket.socket] = None

    def connect(self) -> None:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.connect_timeout)
        sock.settimeout(self.read_timeout)
        seq, greeting = bp.read_packet(sock)
        salt, _plugin, _caps = bp.parse_handshake(greeting)
        bp.write_packet(sock, seq + 1, bp.build_auth_response(
            self.user, self.password, salt))
        _, resp = bp.read_packet(sock)
        bp.check_ok(resp)
        self._sock = sock
        if self.database:
            self.query(f"USE `{self.database}`")

    def query(self, sql: str) -> Tuple[List[bytes],
                                       List[List[Optional[bytes]]]]:
        if self._sock is None:
            self.connect()
        bp.write_packet(self._sock, 0, bytes([bp.COM_QUERY]) + sql.encode())
        return bp.read_result_set(self._sock)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class InputMysql(RdbPollingInput):
    """service_mysql: StateMent may contain one ``?`` placeholder replaced
    by the (quoted) checkpoint value; with Limit=true, LIMIT pages are
    fetched until a short page, MaxSyncSize, or a stuck checkpoint."""

    name = "service_mysql"
    placeholder = "?"
    default_port = 3306
    source_tag = b"mysql"
    limit_clause = "LIMIT {offset}, {page_size}"

    def _escape_string(self, val: str) -> str:
        # MySQL's default sql_mode treats backslash as an escape character
        return val.replace("\\", "\\\\").replace("'", "''")

    def _make_client(self) -> MySQLQueryClient:
        return MySQLQueryClient(self.host, self.port, self.user,
                                self.password, self.database,
                                self.connect_timeout, self.read_timeout)

    @property
    def client_errors(self) -> Tuple[type, ...]:
        return (bp.MySQLError, OSError)
