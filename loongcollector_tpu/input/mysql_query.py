"""service_mysql — periodic SQL collection with column checkpointing.

Reference: plugins/input/mysql/mysql.go (DSN + StateMent with optional
``?`` checkpoint placeholder, CheckPointColumn int/time, PageSize
pagination via LIMIT, MaxSyncSize) and plugins/input/rdb/rdb.go (the
shared rdb collection shape that pgsql/mssql reuse).

The wire client is the repo's own MySQL protocol implementation
(binlog_protocol.py: handshake + mysql_native_password + COM_QUERY text
result sets) — no external driver.
"""

from __future__ import annotations

import socket
import time
from typing import Any, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext
from ..utils.logger import get_logger
from . import binlog_protocol as bp
from .polling_base import PollingInput

log = get_logger("mysql_query")


class MySQLQueryClient:
    """Minimal connection wrapper: connect/auth once, COM_QUERY many."""

    def __init__(self, host: str, port: int, user: str, password: str,
                 database: str = "", connect_timeout: float = 5.0,
                 read_timeout: float = 30.0):
        self.host, self.port = host, port
        self.user, self.password = user, password
        self.database = database
        self.connect_timeout = connect_timeout
        self.read_timeout = read_timeout
        self._sock: Optional[socket.socket] = None

    def connect(self) -> None:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.connect_timeout)
        sock.settimeout(self.read_timeout)
        seq, greeting = bp.read_packet(sock)
        salt, _plugin, _caps = bp.parse_handshake(greeting)
        bp.write_packet(sock, seq + 1, bp.build_auth_response(
            self.user, self.password, salt))
        _, resp = bp.read_packet(sock)
        bp.check_ok(resp)
        self._sock = sock
        if self.database:
            self.query(f"USE `{self.database}`")

    def query(self, sql: str) -> Tuple[List[bytes], List[List[Optional[bytes]]]]:
        if self._sock is None:
            self.connect()
        bp.write_packet(self._sock, 0, bytes([bp.COM_QUERY]) + sql.encode())
        return bp.read_result_set(self._sock)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class InputMysql(PollingInput):
    """service_mysql: StateMent may contain one ``?`` placeholder replaced
    by the checkpoint value; with Limit=true, ``LIMIT PageSize`` pages are
    fetched until a short page or MaxSyncSize rows."""

    name = "service_mysql"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        addr = str(config.get("Address", "127.0.0.1:3306"))
        host, _, port = addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port) if port.isdigit() else 3306
        self.user = str(config.get("User", "root"))
        self.password = str(config.get("Password", ""))
        self.database = str(config.get("DataBase", ""))
        self.statement = str(config.get("StateMent", ""))
        sp = config.get("StateMentPath")
        if not self.statement and sp:
            try:
                with open(str(sp), encoding="utf-8") as f:
                    self.statement = f.read().strip()
            except OSError as e:
                log.error("service_mysql: StateMentPath unreadable: %s", e)
                return False
        if not self.statement:
            log.error("service_mysql: StateMent is required")
            return False
        self.use_checkpoint = bool(config.get("CheckPoint", False))
        self.cp_column = str(config.get("CheckPointColumn", ""))
        self.cp_type = str(config.get("CheckPointColumnType", "int"))
        self.cp_value = str(config.get("CheckPointStart", "0"))
        self.limit = bool(config.get("Limit", False))
        self.page_size = int(config.get("PageSize", 100))
        self.max_sync_size = int(config.get("MaxSyncSize", 0))
        self.interval = int(config.get("IntervalMs", 60000)) / 1000.0
        self.connect_timeout = int(config.get("DialTimeOutMs", 5000)) / 1000.0
        self.read_timeout = int(config.get("ReadTimeOutMs", 30000)) / 1000.0
        self._client: Optional[MySQLQueryClient] = None
        if self.use_checkpoint and not self.cp_column:
            log.error("service_mysql: CheckPoint requires CheckPointColumn")
            return False
        return True

    # client injection point for tests
    def _get_client(self) -> MySQLQueryClient:
        if self._client is None:
            self._client = MySQLQueryClient(
                self.host, self.port, self.user, self.password,
                self.database, self.connect_timeout, self.read_timeout)
        return self._client

    def _build_sql(self, page: int) -> str:
        sql = self.statement
        cp_paged = self.use_checkpoint and "?" in sql
        if cp_paged:
            val = self.cp_value
            if self.cp_type == "time":
                val = f"'{val}'"
            sql = sql.replace("?", val, 1)
        if self.limit and "limit" not in sql.lower():
            # when the checkpoint placeholder drives pagination, each page's
            # WHERE clause already advances past collected rows — adding a
            # row offset on top would skip PageSize rows per page
            offset = 0 if cp_paged else page * self.page_size
            sql = f"{sql} LIMIT {offset}, {self.page_size}"
        return sql

    def poll_once(self) -> None:
        client = self._get_client()
        rows_total = 0
        page = 0
        group = PipelineEventGroup()
        sb = group.source_buffer
        now = int(time.time())
        try:
            while True:
                names, rows = client.query(self._build_sql(page))
                cp_idx = -1
                if self.use_checkpoint and self.cp_column:
                    try:
                        cp_idx = names.index(self.cp_column.encode())
                    except ValueError:
                        cp_idx = -1
                for row in rows:
                    ev = group.add_log_event(now)
                    for name, val in zip(names, row):
                        ev.set_content(sb.copy_string(name),
                                       sb.copy_string(val or b"null"))
                    if cp_idx >= 0 and row[cp_idx] is not None:
                        self.cp_value = row[cp_idx].decode("utf-8", "replace")
                rows_total += len(rows)
                page += 1
                if not self.limit or len(rows) < self.page_size:
                    break
                if self.max_sync_size and rows_total >= self.max_sync_size:
                    break
        except (bp.MySQLError, OSError) as e:
            log.warning("service_mysql poll failed: %s", e)
            if self._client is not None:
                self._client.close()
                self._client = None
            if not len(group):
                return
        group.set_tag(b"__source__", b"mysql")
        pqm = self.context.process_queue_manager
        if pqm is not None and len(group):
            pqm.push_queue(self.context.process_queue_key, group)

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        out = super().stop(is_pipeline_removing)
        if self._client is not None:
            self._client.close()
            self._client = None
        return out
