"""Shared scaffold for interval-polling inputs (SNMP, Redis, …).

Subclasses implement `poll_once()`; the base owns the thread lifecycle and
the interruptible sleep. A poll failure can never kill the thread.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..pipeline.plugin.interface import Input
from ..utils.logger import get_logger

log = get_logger("polling_input")


class PollingInput(Input):
    interval: float = 30.0

    def __init__(self) -> None:
        super().__init__()
        self._thread: Optional[threading.Thread] = None
        self._running = False

    def poll_once(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def start(self) -> bool:
        self._running = True
        self._thread = threading.Thread(target=self._run, name=self.name,
                                        daemon=True)
        self._thread.start()
        return True

    def _run(self) -> None:
        while self._running:
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — polling must survive anything
                log.exception("%s poll round failed", self.name)
            # 0.1s slices keep stop() responsive; min one slice so a tiny
            # interval never degenerates into a busy loop
            for _ in range(max(1, int(self.interval * 10))):
                if not self._running:
                    return
                time.sleep(0.1)

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=3)
            self._thread = None
        return True
