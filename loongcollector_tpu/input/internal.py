"""Internal self-monitor inputs.

Reference: core/plugin/input/InputInternalMetrics.cpp / InputInternalAlarms
.cpp — singleton inputs that bind the SelfMonitorServer's converted event
groups to a normal pipeline (SURVEY.md §2.6 self-monitor pipelines).
"""

from __future__ import annotations

import time
from typing import Any, Dict

from ..container_manager import ContainerManager
from ..models import PipelineEventGroup
from ..monitor.self_monitor import SelfMonitorServer
from ..pipeline.plugin.interface import Input, PluginContext


class InputInternalMetrics(Input):
    name = "input_internal_metrics"
    is_singleton = True

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        interval = config.get("IntervalSeconds")
        if interval:
            SelfMonitorServer.instance().interval_s = float(interval)
        return True

    def start(self) -> bool:
        server = SelfMonitorServer.instance()
        server.set_metrics_pipeline(self.context.process_queue_key)
        server.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        SelfMonitorServer.instance().set_metrics_pipeline(None)
        return True


class InputInternalMatchedContainerInfo(Input):
    """Ships container discovery diffs as events (reference
    InputInternalMatchedContainerInfo + ContainerManager.cpp:325)."""

    name = "input_internal_matched_container_info"
    is_singleton = True

    def __init__(self) -> None:
        super().__init__()
        self._callback = None

    def start(self) -> bool:
        mgr = ContainerManager.instance()
        queue_key = self.context.process_queue_key

        def on_diff(added, removed) -> bool:
            group = PipelineEventGroup()
            sb = group.source_buffer
            now = int(time.time())
            for info, action in ([(c, "added") for c in added]
                                 + [(c, "removed") for c in removed]):
                ev = group.add_log_event(now)
                ev.set_content(b"action", sb.copy_string(action))
                ev.set_content(b"container_id", sb.copy_string(info.id))
                ev.set_content(b"container_name", sb.copy_string(info.name))
                if info.k8s_pod:
                    ev.set_content(b"pod", sb.copy_string(info.k8s_pod))
                    ev.set_content(b"namespace",
                                   sb.copy_string(info.k8s_namespace))
            group.set_tag(b"__source__", b"matched_container_info")
            server = SelfMonitorServer.instance()
            if server.process_queue_manager is None or group.empty():
                return True
            return server.process_queue_manager.push_queue(queue_key, group)

        self._callback = on_diff
        if not mgr.set_on_diff(on_diff):
            from ..utils.logger import get_logger
            get_logger("internal").error(
                "matched_container_info already bound to another pipeline")
            return False
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        mgr = ContainerManager.instance()
        # only the owning pipeline clears the consumer slot
        if mgr.on_diff is self._callback:
            mgr.set_on_diff(None)
        return True


class InputInternalAlarms(Input):
    name = "input_internal_alarms"
    is_singleton = True

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        return True

    def start(self) -> bool:
        server = SelfMonitorServer.instance()
        server.set_alarms_pipeline(self.context.process_queue_key)
        server.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        SelfMonitorServer.instance().set_alarms_pipeline(None)
        return True
