"""Internal self-monitor inputs.

Reference: core/plugin/input/InputInternalMetrics.cpp / InputInternalAlarms
.cpp — singleton inputs that bind the SelfMonitorServer's converted event
groups to a normal pipeline (SURVEY.md §2.6 self-monitor pipelines).
"""

from __future__ import annotations

from typing import Any, Dict

from ..monitor.self_monitor import SelfMonitorServer
from ..pipeline.plugin.interface import Input, PluginContext


class InputInternalMetrics(Input):
    name = "input_internal_metrics"
    is_singleton = True

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        interval = config.get("IntervalSeconds")
        if interval:
            SelfMonitorServer.instance().interval_s = float(interval)
        return True

    def start(self) -> bool:
        server = SelfMonitorServer.instance()
        server.set_metrics_pipeline(self.context.process_queue_key)
        server.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        SelfMonitorServer.instance().set_metrics_pipeline(None)
        return True


class InputInternalAlarms(Input):
    name = "input_internal_alarms"
    is_singleton = True

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        return True

    def start(self) -> bool:
        server = SelfMonitorServer.instance()
        server.set_alarms_pipeline(self.context.process_queue_key)
        server.start()
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        SelfMonitorServer.instance().set_alarms_pipeline(None)
        return True
