"""service_pgsql — periodic PostgreSQL collection (rdb family).

Reference: plugins/input/rdb/pgsql/pgsql.go over the shared rdb shape
(plugins/input/rdb/rdb.go → rdb_base.RdbPollingInput here: StateMent
with $1 checkpoint placeholder, Limit/PageSize/MaxSyncSize,
CheckPointColumn).

The wire client speaks the PostgreSQL v3 frontend protocol directly
(StartupMessage → cleartext/md5 password auth → simple Query →
RowDescription/DataRow): no external driver.  SCRAM-SHA-256-only servers
are reported as unsupported rather than silently failing.
"""

from __future__ import annotations

import hashlib
import socket
import struct
from typing import List, Optional, Tuple

from ..utils.logger import get_logger
from .rdb_base import RdbPollingInput

log = get_logger("pgsql")


class PgError(Exception):
    pass


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack("!I", len(payload) + 4) + payload


class PgClient:
    """Minimal v3-protocol client: simple query over one connection."""

    def __init__(self, host: str, port: int, user: str, password: str,
                 database: str, connect_timeout: float = 5.0,
                 read_timeout: float = 30.0):
        self.host, self.port = host, port
        self.user, self.password = user, password
        self.database = database or user
        self.connect_timeout = connect_timeout
        self.read_timeout = read_timeout
        self._sock: Optional[socket.socket] = None

    # -- wire ----------------------------------------------------------------

    def _read_msg(self) -> Tuple[bytes, bytes]:
        hdr = self._read_exact(5)
        tag = hdr[:1]
        n = struct.unpack("!I", hdr[1:])[0] - 4
        return tag, self._read_exact(n)

    def _read_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise PgError("connection closed")
            out += chunk
        return out

    def connect(self) -> None:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.connect_timeout)
        sock.settimeout(self.read_timeout)
        self._sock = sock
        params = (f"user\x00{self.user}\x00database\x00{self.database}\x00"
                  "client_encoding\x00UTF8\x00\x00").encode()
        payload = struct.pack("!I", 196608) + params   # protocol 3.0
        sock.sendall(struct.pack("!I", len(payload) + 4) + payload)
        while True:
            tag, body = self._read_msg()
            if tag == b"R":
                code = struct.unpack("!I", body[:4])[0]
                if code == 0:                      # AuthenticationOk
                    continue
                if code == 3:                      # cleartext
                    sock.sendall(_msg(b"p", self.password.encode() + b"\x00"))
                elif code == 5:                    # md5
                    salt = body[4:8]
                    inner = hashlib.md5(
                        (self.password + self.user).encode()).hexdigest()
                    digest = hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    sock.sendall(_msg(b"p", b"md5" + digest.encode()
                                      + b"\x00"))
                else:
                    raise PgError(f"unsupported auth method {code} "
                                  "(SCRAM not implemented)")
            elif tag == b"E":
                raise PgError(self._err_text(body))
            elif tag == b"Z":                      # ReadyForQuery
                return
            # 'S' ParameterStatus / 'K' BackendKeyData: ignore

    @staticmethod
    def _err_text(body: bytes) -> str:
        parts = {}
        for field in body.split(b"\x00"):
            if field:
                parts[chr(field[0])] = field[1:].decode("utf-8", "replace")
        return parts.get("M", "server error")

    def query(self, sql: str) -> Tuple[List[bytes],
                                       List[List[Optional[bytes]]]]:
        if self._sock is None:
            self.connect()
        self._sock.sendall(_msg(b"Q", sql.encode() + b"\x00"))
        names: List[bytes] = []
        rows: List[List[Optional[bytes]]] = []
        error: Optional[str] = None
        while True:
            tag, body = self._read_msg()
            if tag == b"T":                        # RowDescription
                nfields = struct.unpack("!H", body[:2])[0]
                pos = 2
                names = []
                for _ in range(nfields):
                    end = body.index(b"\x00", pos)
                    names.append(body[pos:end])
                    pos = end + 1 + 18             # fixed per-field trailer
            elif tag == b"D":                      # DataRow
                nfields = struct.unpack("!H", body[:2])[0]
                pos = 2
                row: List[Optional[bytes]] = []
                for _ in range(nfields):
                    (ln,) = struct.unpack("!i", body[pos:pos + 4])
                    pos += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(body[pos:pos + ln])
                        pos += ln
                rows.append(row)
            elif tag == b"E":
                error = self._err_text(body)
            elif tag == b"Z":                      # ReadyForQuery
                if error:
                    raise PgError(error)
                return names, rows
            # 'C' CommandComplete / 'N' notices: ignore

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.sendall(_msg(b"X", b""))
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class InputPgsql(RdbPollingInput):
    """service_pgsql: StateMent may use $1 as the checkpoint placeholder
    (reference pgsql.go appends `LIMIT n OFFSET $2`; here checkpoint
    pagination keeps offset 0, like service_mysql)."""

    name = "service_pgsql"
    placeholder = "$1"
    default_port = 5432
    source_tag = b"pgsql"
    limit_clause = "LIMIT {page_size} OFFSET {offset}"

    def _make_client(self) -> PgClient:
        return PgClient(self.host, self.port, self.user or "postgres",
                        self.password, self.database,
                        self.connect_timeout, self.read_timeout)

    @property
    def client_errors(self) -> Tuple[type, ...]:
        return (PgError, OSError)
