"""MySQL client + binlog replication wire protocol (no client library).

Reference: plugins/input/canal/input_canal.go wraps go-mysql's canal; this
module speaks the public MySQL protocol directly: packet framing, the
HandshakeV10 / mysql_native_password auth exchange, COM_QUERY text result
sets (for SHOW MASTER STATUS / schema discovery), COM_REGISTER_SLAVE,
COM_BINLOG_DUMP, and row-based binlog event decoding (TABLE_MAP +
WRITE/UPDATE/DELETE_ROWS v1/v2) covering the standard column-type matrix
(ints, floats, NEWDECIMAL, VARCHAR/STRING/BLOB, DATE/DATETIME2/TIMESTAMP2/
TIME2/YEAR, BIT, ENUM/SET, JSON-as-bytes).

Pure parsing lives here (unit-testable on golden byte strings); the service
plugin and replication thread live in input/mysql_binlog.py.
"""

from __future__ import annotations

import hashlib
import socket
import struct
from typing import Dict, List, Optional, Tuple

# -- capability flags --------------------------------------------------------

CLIENT_LONG_PASSWORD = 1
CLIENT_LONG_FLAG = 1 << 2
CLIENT_PROTOCOL_41 = 1 << 9
CLIENT_SECURE_CONNECTION = 1 << 15
CLIENT_PLUGIN_AUTH = 1 << 19

# -- commands ---------------------------------------------------------------

COM_QUERY = 0x03
COM_BINLOG_DUMP = 0x12
COM_REGISTER_SLAVE = 0x15

# -- binlog event types -----------------------------------------------------

EV_QUERY = 2
EV_ROTATE = 4
EV_FORMAT_DESCRIPTION = 15
EV_XID = 16
EV_TABLE_MAP = 19
EV_WRITE_ROWS_V1 = 23
EV_UPDATE_ROWS_V1 = 24
EV_DELETE_ROWS_V1 = 25
EV_HEARTBEAT = 27
EV_WRITE_ROWS_V2 = 30
EV_UPDATE_ROWS_V2 = 31
EV_DELETE_ROWS_V2 = 32
EV_GTID = 33

# -- column types -----------------------------------------------------------

T_DECIMAL = 0
T_TINY = 1
T_SHORT = 2
T_LONG = 3
T_FLOAT = 4
T_DOUBLE = 5
T_NULL = 6
T_TIMESTAMP = 7
T_LONGLONG = 8
T_INT24 = 9
T_DATE = 10
T_TIME = 11
T_DATETIME = 12
T_YEAR = 13
T_VARCHAR = 15
T_BIT = 16
T_TIMESTAMP2 = 17
T_DATETIME2 = 18
T_TIME2 = 19
T_JSON = 245
T_NEWDECIMAL = 246
T_ENUM = 247
T_SET = 248
T_TINY_BLOB = 249
T_MEDIUM_BLOB = 250
T_LONG_BLOB = 251
T_BLOB = 252
T_VAR_STRING = 253
T_STRING = 254
T_GEOMETRY = 255


class MySQLError(Exception):
    pass


# ---------------------------------------------------------------------------
# packet framing + primitives
# ---------------------------------------------------------------------------


def read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise MySQLError("connection closed")
        buf += chunk
    return bytes(buf)


def read_packet(sock: socket.socket) -> Tuple[int, bytes]:
    """Returns (sequence, payload); reassembles 16MB-spanning payloads."""
    head = read_exact(sock, 4)
    length = head[0] | (head[1] << 8) | (head[2] << 16)
    seq = head[3]
    payload = read_exact(sock, length)
    while length == 0xFFFFFF:
        head = read_exact(sock, 4)
        length = head[0] | (head[1] << 8) | (head[2] << 16)
        seq = head[3]
        payload += read_exact(sock, length)
    return seq, payload


def write_packet(sock: socket.socket, seq: int, payload: bytes) -> None:
    while True:
        chunk = payload[:0xFFFFFF]
        payload = payload[0xFFFFFF:]
        sock.sendall(struct.pack("<I", len(chunk))[:3]
                     + bytes([seq & 0xFF]) + chunk)
        seq += 1
        if len(chunk) < 0xFFFFFF:
            return


def lenc_int(data: bytes, pos: int) -> Tuple[Optional[int], int]:
    """Length-encoded integer → (value | None for NULL, new_pos)."""
    b = data[pos]
    if b < 0xFB:
        return b, pos + 1
    if b == 0xFB:
        return None, pos + 1
    if b == 0xFC:
        return struct.unpack_from("<H", data, pos + 1)[0], pos + 3
    if b == 0xFD:
        v = data[pos + 1] | (data[pos + 2] << 8) | (data[pos + 3] << 16)
        return v, pos + 4
    return struct.unpack_from("<Q", data, pos + 1)[0], pos + 9


def lenc_str(data: bytes, pos: int) -> Tuple[Optional[bytes], int]:
    n, pos = lenc_int(data, pos)
    if n is None:
        return None, pos
    return data[pos : pos + n], pos + n


def nul_str(data: bytes, pos: int) -> Tuple[bytes, int]:
    end = data.index(0, pos)
    return data[pos:end], end + 1


# ---------------------------------------------------------------------------
# handshake / auth
# ---------------------------------------------------------------------------


def scramble_native(password: str, salt: bytes) -> bytes:
    """mysql_native_password: SHA1(p) XOR SHA1(salt + SHA1(SHA1(p)))."""
    if not password:
        return b""
    p1 = hashlib.sha1(password.encode()).digest()
    p2 = hashlib.sha1(p1).digest()
    mix = hashlib.sha1(salt + p2).digest()
    return bytes(a ^ b for a, b in zip(p1, mix))


def parse_handshake(payload: bytes) -> Tuple[bytes, str, int]:
    """HandshakeV10 → (auth salt, auth plugin name, capabilities)."""
    if payload[0] == 0xFF:
        code = struct.unpack_from("<H", payload, 1)[0]
        raise MySQLError(f"server error {code}: {payload[3:].decode(errors='replace')}")
    if payload[0] != 10:
        raise MySQLError(f"unsupported protocol version {payload[0]}")
    _, pos = nul_str(payload, 1)        # server version
    pos += 4                            # thread id
    salt = payload[pos : pos + 8]
    pos += 9                            # salt part 1 + filler
    caps = struct.unpack_from("<H", payload, pos)[0]
    pos += 2
    plugin = "mysql_native_password"
    if len(payload) > pos:
        pos += 1 + 2                    # charset, status
        caps |= struct.unpack_from("<H", payload, pos)[0] << 16
        pos += 2
        auth_len = payload[pos]
        pos += 1 + 10                   # reserved
        if caps & CLIENT_SECURE_CONNECTION:
            n = max(13, auth_len - 8)
            salt2 = payload[pos : pos + n].rstrip(b"\x00")
            salt = salt + salt2
            pos += n
        if caps & CLIENT_PLUGIN_AUTH:
            name, pos = nul_str(payload, pos)
            plugin = name.decode()
    return salt[:20], plugin, caps


def build_auth_response(user: str, password: str, salt: bytes) -> bytes:
    caps = (CLIENT_LONG_PASSWORD | CLIENT_LONG_FLAG | CLIENT_PROTOCOL_41
            | CLIENT_SECURE_CONNECTION | CLIENT_PLUGIN_AUTH)
    token = scramble_native(password, salt)
    out = struct.pack("<IIB", caps, 1 << 24, 33) + b"\x00" * 23
    out += user.encode() + b"\x00"
    out += bytes([len(token)]) + token
    out += b"mysql_native_password\x00"
    return out


def check_ok(payload: bytes) -> None:
    if payload and payload[0] == 0xFF:
        code = struct.unpack_from("<H", payload, 1)[0]
        msg = payload[9:].decode(errors="replace") \
            if len(payload) > 9 else ""
        raise MySQLError(f"server error {code}: {msg}")


# ---------------------------------------------------------------------------
# COM_QUERY text result sets
# ---------------------------------------------------------------------------


def read_result_set(sock: socket.socket
                    ) -> Tuple[List[bytes], List[List[Optional[bytes]]]]:
    """Reads a text-protocol result set → (column names, rows)."""
    _, payload = read_packet(sock)
    check_ok(payload)
    if payload[0] == 0x00:              # OK packet: no result set
        return [], []
    ncols, _ = lenc_int(payload, 0)
    names: List[bytes] = []
    for _ in range(ncols):
        _, cdef = read_packet(sock)
        pos = 0
        for _ in range(4):              # catalog, schema, table, org_table
            _, pos = lenc_str(cdef, pos)
        name, pos = lenc_str(cdef, pos)
        names.append(name or b"")
    _, eof = read_packet(sock)          # EOF (assumes no DEPRECATE_EOF)
    rows: List[List[Optional[bytes]]] = []
    while True:
        _, payload = read_packet(sock)
        check_ok(payload)
        if payload[0] == 0xFE and len(payload) < 9:
            return names, rows
        row: List[Optional[bytes]] = []
        pos = 0
        while pos < len(payload):
            v, pos = lenc_str(payload, pos)
            row.append(v)
        rows.append(row)


# ---------------------------------------------------------------------------
# binlog event decoding
# ---------------------------------------------------------------------------


class EventHeader:
    __slots__ = ("timestamp", "type_code", "server_id", "event_size",
                 "log_pos", "flags")

    def __init__(self, data: bytes):
        (self.timestamp, self.type_code, self.server_id, self.event_size,
         self.log_pos, self.flags) = struct.unpack_from("<IBIIIH", data, 0)


HEADER_LEN = 19


class TableMap:
    __slots__ = ("table_id", "schema", "table", "col_types", "col_meta",
                 "col_names", "signedness", "null_bitmap")

    def __init__(self, payload: bytes):
        self.table_id = int.from_bytes(payload[0:6], "little")
        pos = 8                          # table id (6) + flags (2)
        n = payload[pos]
        self.schema = payload[pos + 1 : pos + 1 + n].decode(errors="replace")
        pos += 1 + n + 1
        n = payload[pos]
        self.table = payload[pos + 1 : pos + 1 + n].decode(errors="replace")
        pos += 1 + n + 1
        ncols, pos = lenc_int(payload, pos)
        self.col_types = list(payload[pos : pos + ncols])
        pos += ncols
        meta_blob, pos = lenc_str(payload, pos)
        self.col_meta = self._parse_meta(meta_blob)
        nb = (ncols + 7) // 8
        self.null_bitmap = payload[pos : pos + nb]
        pos += nb
        self.col_names: Optional[List[str]] = None
        self.signedness: Optional[List[bool]] = None
        self._parse_optional_meta(payload, pos)

    def _parse_meta(self, blob: bytes) -> List[int]:
        out: List[int] = []
        pos = 0
        for t in self.col_types:
            if t in (T_VARCHAR, T_BIT, T_NEWDECIMAL, T_VAR_STRING):
                out.append(struct.unpack_from("<H", blob, pos)[0])
                pos += 2
            elif t in (T_STRING, T_ENUM, T_SET):
                # byte0 = real type bits, byte1 = length (big-endian pair)
                out.append((blob[pos] << 8) | blob[pos + 1])
                pos += 2
            elif t in (T_FLOAT, T_DOUBLE, T_BLOB, T_TINY_BLOB,
                       T_MEDIUM_BLOB, T_LONG_BLOB, T_GEOMETRY, T_JSON,
                       T_TIMESTAMP2, T_DATETIME2, T_TIME2):
                out.append(blob[pos])
                pos += 1
            else:
                out.append(0)
        return out

    def _parse_optional_meta(self, payload: bytes, pos: int) -> None:
        """binlog_row_metadata optional TLV block (MySQL 8.0+): we read
        SIGNEDNESS (1) and COLUMN_NAME (4)."""
        ncols = len(self.col_types)
        while pos + 2 <= len(payload):
            t = payload[pos]
            ln, pos2 = lenc_int(payload, pos + 1)
            val = payload[pos2 : pos2 + ln]
            pos = pos2 + ln
            if t == 1:                  # SIGNEDNESS: one bit per NUMERIC col
                numeric = {T_DECIMAL, T_NEWDECIMAL, T_TINY, T_SHORT,
                           T_INT24, T_LONG, T_LONGLONG, T_FLOAT, T_DOUBLE}
                bits = [False] * ncols
                k = 0
                for i, ct in enumerate(self.col_types):
                    if ct in numeric:
                        byte = val[k // 8] if k // 8 < len(val) else 0
                        bits[i] = bool(byte & (0x80 >> (k % 8)))
                        k += 1
                self.signedness = bits
            elif t == 4:                # COLUMN_NAME
                names = []
                p = 0
                while p < len(val):
                    n, p = lenc_int(val, p)
                    names.append(val[p : p + n].decode(errors="replace"))
                    p += n
                self.col_names = names


def _read_bitmap_indices(bitmap: bytes, ncols: int) -> List[int]:
    return [i for i in range(ncols) if bitmap[i // 8] & (1 << (i % 8))]


def _decimal_decode(data: bytes, precision: int, scale: int
                    ) -> Tuple[str, int]:
    """MySQL packed NEWDECIMAL → (decimal string, bytes consumed)."""
    dig2bytes = [0, 1, 1, 2, 2, 3, 3, 4, 4, 4]
    intg = precision - scale
    intg0, intg_rem = divmod(intg, 9)
    frac0, frac_rem = divmod(scale, 9)
    total = intg0 * 4 + dig2bytes[intg_rem] + frac0 * 4 + dig2bytes[frac_rem]
    raw = bytearray(data[:total])
    negative = not (raw[0] & 0x80)
    raw[0] ^= 0x80
    if negative:
        for i in range(len(raw)):
            raw[i] ^= 0xFF
    pos = 0
    int_part = 0
    if intg_rem:
        n = dig2bytes[intg_rem]
        int_part = int.from_bytes(raw[pos : pos + n], "big")
        pos += n
    for _ in range(intg0):
        int_part = int_part * 10**9 + int.from_bytes(raw[pos:pos+4], "big")
        pos += 4
    frac_digits = ""
    for _ in range(frac0):
        frac_digits += f"{int.from_bytes(raw[pos:pos+4], 'big'):09d}"
        pos += 4
    if frac_rem:
        n = dig2bytes[frac_rem]
        frac_digits += (f"{int.from_bytes(raw[pos:pos+n], 'big')}"
                        .zfill(frac_rem))
        pos += n
    sign = "-" if negative else ""
    if scale:
        return f"{sign}{int_part}.{frac_digits}", total
    return f"{sign}{int_part}", total


def decode_value(col_type: int, meta: int, data: bytes, pos: int,
                 unsigned: bool = False):
    """One column value → (python value, new_pos)."""
    if col_type == T_TINY:
        v = data[pos]
        if not unsigned and v >= 0x80:
            v -= 0x100
        return v, pos + 1
    if col_type == T_SHORT:
        v = struct.unpack_from("<H" if unsigned else "<h", data, pos)[0]
        return v, pos + 2
    if col_type == T_INT24:
        v = int.from_bytes(data[pos : pos + 3], "little")
        if not unsigned and v >= 0x800000:
            v -= 0x1000000
        return v, pos + 3
    if col_type == T_LONG:
        v = struct.unpack_from("<I" if unsigned else "<i", data, pos)[0]
        return v, pos + 4
    if col_type == T_LONGLONG:
        v = struct.unpack_from("<Q" if unsigned else "<q", data, pos)[0]
        return v, pos + 8
    if col_type == T_FLOAT:
        return struct.unpack_from("<f", data, pos)[0], pos + 4
    if col_type == T_DOUBLE:
        return struct.unpack_from("<d", data, pos)[0], pos + 8
    if col_type == T_YEAR:
        v = data[pos]
        return (1900 + v) if v else 0, pos + 1
    if col_type == T_DATE:
        v = int.from_bytes(data[pos : pos + 3], "little")
        return f"{v >> 9:04d}-{(v >> 5) & 15:02d}-{v & 31:02d}", pos + 3
    if col_type == T_TIME:
        v = int.from_bytes(data[pos : pos + 3], "little")
        return f"{v // 10000:02d}:{(v % 10000) // 100:02d}:{v % 100:02d}", \
            pos + 3
    if col_type == T_DATETIME:
        v = struct.unpack_from("<Q", data, pos)[0]
        d, t = divmod(v, 1000000)
        return (f"{d // 10000:04d}-{(d % 10000) // 100:02d}-{d % 100:02d} "
                f"{t // 10000:02d}:{(t % 10000) // 100:02d}:{t % 100:02d}"), \
            pos + 8
    if col_type == T_TIMESTAMP:
        return struct.unpack_from("<I", data, pos)[0], pos + 4
    if col_type == T_TIMESTAMP2:
        v = int.from_bytes(data[pos : pos + 4], "big")
        n = (meta + 1) // 2
        frac = int.from_bytes(data[pos + 4 : pos + 4 + n], "big") if n else 0
        if meta:
            return f"{v}.{frac:0{n * 2}d}"[: len(str(v)) + 1 + meta], \
                pos + 4 + n
        return v, pos + 4
    if col_type == T_DATETIME2:
        v = int.from_bytes(data[pos : pos + 5], "big") - 0x8000000000
        n = (meta + 1) // 2
        ym = (v >> 22) & 0x1FFFF
        out = (f"{ym // 13:04d}-{ym % 13:02d}-{(v >> 17) & 0x1F:02d} "
               f"{(v >> 12) & 0x1F:02d}:{(v >> 6) & 0x3F:02d}:{v & 0x3F:02d}")
        if n:
            frac = int.from_bytes(data[pos + 5 : pos + 5 + n], "big")
            out += f".{frac:0{n * 2}d}"[: 1 + meta]
        return out, pos + 5 + n
    if col_type == T_TIME2:
        v = int.from_bytes(data[pos : pos + 3], "big") - 0x800000
        n = (meta + 1) // 2
        sign = "-" if v < 0 else ""
        v = abs(v)
        out = (f"{sign}{(v >> 12) & 0x3FF:02d}:{(v >> 6) & 0x3F:02d}"
               f":{v & 0x3F:02d}")
        return out, pos + 3 + n
    if col_type in (T_VARCHAR, T_VAR_STRING):
        if meta < 256:
            n = data[pos]
            pos += 1
        else:
            n = struct.unpack_from("<H", data, pos)[0]
            pos += 2
        return data[pos : pos + n], pos + n
    if col_type == T_BIT:
        nbits = ((meta >> 8) * 8) + (meta & 0xFF)
        n = (nbits + 7) // 8
        return int.from_bytes(data[pos : pos + n], "big"), pos + n
    if col_type == T_NEWDECIMAL:
        precision = meta & 0xFF
        scale = meta >> 8
        s, used = _decimal_decode(data[pos:], precision, scale)
        return s, pos + used
    if col_type in (T_BLOB, T_TINY_BLOB, T_MEDIUM_BLOB, T_LONG_BLOB,
                    T_GEOMETRY, T_JSON):
        n = int.from_bytes(data[pos : pos + meta], "little")
        pos += meta
        return data[pos : pos + n], pos + n
    if col_type in (T_STRING, T_ENUM, T_SET):
        byte0 = meta >> 8
        byte1 = meta & 0xFF
        if byte0 and (byte0 & 0x30) != 0x30:
            real = byte0 | 0x30
            length = byte1 | (((byte0 & 0x30) ^ 0x30) << 4)
        else:
            real = byte0 or col_type
            length = byte1
        if real == T_ENUM:
            n = 1 if length < 256 else 2
            return int.from_bytes(data[pos : pos + n], "little"), pos + n
        if real == T_SET:
            return int.from_bytes(data[pos : pos + length], "little"), \
                pos + length
        if length < 256:
            n = data[pos]
            pos += 1
        else:
            n = struct.unpack_from("<H", data, pos)[0]
            pos += 2
        return data[pos : pos + n], pos + n
    raise MySQLError(f"unsupported column type {col_type}")


class RowsEvent:
    """Decoded WRITE/UPDATE/DELETE rows event."""

    __slots__ = ("action", "table", "rows")

    def __init__(self, action: str, table: TableMap,
                 rows: List):
        self.action = action            # insert | update | delete
        self.table = table
        self.rows = rows                # [values] or [(before, after)]


def parse_rows_event(type_code: int, payload: bytes,
                     tables: Dict[int, TableMap]) -> Optional[RowsEvent]:
    v2 = type_code >= EV_WRITE_ROWS_V2
    table_id = int.from_bytes(payload[0:6], "little")
    pos = 8                             # table id + flags
    if v2:
        extra_len = struct.unpack_from("<H", payload, pos)[0]
        pos += extra_len                # includes the 2 length bytes
    table = tables.get(table_id)
    if table is None:
        return None
    ncols, pos = lenc_int(payload, pos)
    nb = (ncols + 7) // 8
    present1 = payload[pos : pos + nb]
    pos += nb
    is_update = type_code in (EV_UPDATE_ROWS_V1, EV_UPDATE_ROWS_V2)
    present2 = present1
    if is_update:
        present2 = payload[pos : pos + nb]
        pos += nb
    cols1 = _read_bitmap_indices(present1, ncols)
    cols2 = _read_bitmap_indices(present2, ncols)

    def read_row(cols: List[int], p: int):
        nbm = (len(cols) + 7) // 8
        nulls = payload[p : p + nbm]
        p += nbm
        vals: Dict[int, object] = {}
        for k, ci in enumerate(cols):
            if nulls[k // 8] & (1 << (k % 8)):
                vals[ci] = None
                continue
            unsigned = bool(table.signedness[ci]) if table.signedness \
                and ci < len(table.signedness) else False
            v, p = decode_value(table.col_types[ci], table.col_meta[ci],
                                payload, p, unsigned)
            vals[ci] = v
        return vals, p

    rows = []
    while pos < len(payload):
        row1, pos = read_row(cols1, pos)
        if is_update:
            row2, pos = read_row(cols2, pos)
            rows.append((row1, row2))
        else:
            rows.append(row1)
    action = ("insert" if type_code in (EV_WRITE_ROWS_V1, EV_WRITE_ROWS_V2)
              else "update" if is_update else "delete")
    return RowsEvent(action, table, rows)


def parse_gtid(payload: bytes) -> str:
    sid = payload[1:17]
    gno = struct.unpack_from("<q", payload, 17)[0]
    import uuid
    return f"{uuid.UUID(bytes=sid)}:{gno}"


def parse_rotate(payload: bytes) -> Tuple[int, str]:
    pos8 = struct.unpack_from("<Q", payload, 0)[0]
    return pos8, payload[8:].decode(errors="replace")


def parse_query(payload: bytes) -> Tuple[str, str]:
    """QUERY_EVENT → (schema, query text)."""
    schema_len = payload[8]
    status_len = struct.unpack_from("<H", payload, 11)[0]
    pos = 13 + status_len
    schema = payload[pos : pos + schema_len].decode(errors='replace')
    pos += schema_len + 1
    return schema, payload[pos:].decode(errors="replace")
