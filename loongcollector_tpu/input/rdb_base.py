"""Shared rdb collection shape (reference plugins/input/rdb/rdb.go).

Both SQL inputs (service_mysql, service_pgsql) poll a statement on an
interval, optionally driven by a column checkpoint (placeholder token in
the statement) and LIMIT pagination.  This base owns config parsing,
SQL construction, the page loop, and event emission; subclasses provide
the wire client and dialect specifics.
"""

from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Optional, Tuple

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import PluginContext
from ..utils.logger import get_logger
from .polling_base import PollingInput

log = get_logger("rdb")

_MAX_PAGES = 10_000          # runaway-pagination backstop


class RdbPollingInput(PollingInput):
    """Config keys per the reference rdb shape: Address, User, Password,
    DataBase, StateMent(/Path), CheckPoint{,Column,ColumnType,Start},
    Limit, PageSize, MaxSyncSize, IntervalMs, DialTimeOutMs,
    ReadTimeOutMs."""

    placeholder = "?"          # checkpoint token in StateMent
    default_port = 0
    source_tag = b"rdb"
    # dialect: how a LIMIT page is appended
    limit_clause = "LIMIT {offset}, {page_size}"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        addr = str(config.get("Address", "127.0.0.1"))
        host, _, maybe_port = addr.rpartition(":")
        if host:                       # host:port form
            self.host = host
            port_s = maybe_port
        else:
            self.host = maybe_port or "127.0.0.1"
            port_s = ""
        self.port = int(config.get("Port", 0)
                        or (port_s if port_s.isdigit() else 0)
                        or self.default_port)
        self.user = str(config.get("User", ""))
        self.password = str(config.get("Password", ""))
        pp = config.get("PasswordPath")
        if not self.password and pp:
            try:
                with open(str(pp), encoding="utf-8") as f:
                    self.password = f.readline().strip()
            except OSError:
                pass
        self.database = str(config.get("DataBase", ""))
        self.statement = str(config.get("StateMent", ""))
        sp = config.get("StateMentPath")
        if not self.statement and sp:
            try:
                with open(str(sp), encoding="utf-8") as f:
                    self.statement = f.read().strip()
            except OSError as e:
                log.error("%s: StateMentPath unreadable: %s", self.name, e)
                return False
        if not self.statement:
            log.error("%s: StateMent is required", self.name)
            return False
        self.use_checkpoint = bool(config.get("CheckPoint", False))
        self.cp_column = str(config.get("CheckPointColumn", ""))
        self.cp_type = str(config.get("CheckPointColumnType", "int"))
        self.cp_value = str(config.get("CheckPointStart", "0"))
        if self.use_checkpoint and self.cp_column:
            # reference rdb.go persists the column checkpoint via
            # Context.GetCheckPoint/SaveCheckPoint — restarts resume from
            # the last collected value instead of re-ingesting everything
            saved = context.get_checkpoint(self._cp_key())
            if saved is not None:
                self.cp_value = saved
        self.limit = bool(config.get("Limit", False))
        self.page_size = int(config.get("PageSize", 100))
        self.max_sync_size = int(config.get("MaxSyncSize", 0))
        self.interval = int(config.get("IntervalMs", 60000)) / 1000.0
        self.connect_timeout = int(config.get("DialTimeOutMs",
                                              5000)) / 1000.0
        self.read_timeout = int(config.get("ReadTimeOutMs", 30000)) / 1000.0
        self._client = None
        if self.use_checkpoint and not self.cp_column:
            log.error("%s: CheckPoint requires CheckPointColumn", self.name)
            return False
        return True

    def _cp_key(self) -> str:
        return f"rdb_cp/{self.name}/{self.cp_column}"

    # -- dialect hooks -------------------------------------------------------

    def _make_client(self):  # pragma: no cover - abstract
        raise NotImplementedError

    @property
    def client_errors(self) -> Tuple[type, ...]:  # pragma: no cover
        return (OSError,)

    # -- shared machinery ----------------------------------------------------

    def _get_client(self):
        if self._client is None:
            self._client = self._make_client()
        return self._client

    def _escape_string(self, val: str) -> str:
        """Dialect hook: standard SQL doubles single quotes; dialects with
        backslash escapes (MySQL default sql_mode) override."""
        return val.replace("'", "''")

    def _quote_cp(self) -> str:
        """The checkpoint value is data read back from the database —
        never splice it raw (quote breakage at best, SQL injection via a
        monitored table at worst)."""
        val = self.cp_value
        if self.cp_type == "time":
            return "'" + self._escape_string(val) + "'"
        # int checkpoints must BE ints
        try:
            return str(int(val))
        except ValueError:
            try:
                return repr(float(val))
            except ValueError:
                return "0"

    @property
    def _cp_paged(self) -> bool:
        """True when the checkpoint placeholder drives pagination (the
        same SQL text CAN repeat across pages)."""
        return self.use_checkpoint and self.placeholder in self.statement

    def _build_sql(self, page: int) -> Tuple[str, bool]:
        """→ (sql, paged): paged=False means one iteration only."""
        sql = self.statement
        cp_paged = self._cp_paged
        if cp_paged:
            sql = sql.replace(self.placeholder, self._quote_cp(), 1)
        # word-boundary check: a column named `rate_limit` is not a LIMIT
        has_limit = re.search(r"\blimit\b", sql, re.IGNORECASE) is not None
        appended = False
        if self.limit and not has_limit:
            offset = 0 if cp_paged else page * self.page_size
            sql = sql + " " + self.limit_clause.format(
                offset=offset, page_size=self.page_size)
            appended = True
        return sql, appended

    def poll_once(self) -> None:
        client = self._get_client()
        rows_total = 0
        page = 0
        cp_paged = self._cp_paged
        last_cp = cp_at_start = self.cp_value
        group = PipelineEventGroup()
        sb = group.source_buffer
        now = int(time.time())
        try:
            while page < _MAX_PAGES:
                sql, paged = self._build_sql(page)
                names, rows = client.query(sql)
                cp_idx = -1
                if self.use_checkpoint and self.cp_column:
                    try:
                        cp_idx = names.index(self.cp_column.encode())
                    except ValueError:
                        cp_idx = -1
                for row in rows:
                    ev = group.add_log_event(now)
                    for name, val in zip(names, row):
                        ev.set_content(sb.copy_string(name),
                                       sb.copy_string(val
                                                      if val is not None
                                                      else b"null"))
                    if cp_idx >= 0 and row[cp_idx] is not None:
                        self.cp_value = row[cp_idx].decode("utf-8",
                                                           "replace")
                rows_total += len(rows)
                page += 1
                if not paged or len(rows) < self.page_size:
                    break
                if self.max_sync_size and rows_total >= self.max_sync_size:
                    break
                if cp_paged:
                    # placeholder-paged: the next page reruns the SAME sql
                    # unless the checkpoint advanced — a missing checkpoint
                    # column (cp_idx<0, e.g. aliased away) or NULL values
                    # would loop on identical rows forever
                    if cp_idx < 0 or self.cp_value == last_cp:
                        break
                    last_cp = self.cp_value
        except self.client_errors as e:  # noqa: B030 — dialect tuple
            log.warning("%s poll failed: %s", self.name, e)
            if self._client is not None:
                self._client.close()
                self._client = None
            if not len(group):
                return
        group.set_tag(b"__source__", self.source_tag)
        pqm = self.context.process_queue_manager
        if pqm is not None and len(group):
            pqm.push_queue(self.context.process_queue_key, group)
        if self.use_checkpoint and self.cp_value != cp_at_start:
            self.context.save_checkpoint(self._cp_key(), self.cp_value)

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        out = super().stop(is_pipeline_removing)
        if self._client is not None:
            self._client.close()
            self._client = None
        return out
