"""input_container_stdio — tail container stdout/stderr logs.

Reference: core/plugin/input/InputContainerStdio.cpp — binds container
discovery to file tailing with the container-log unwrap + partial-merge
inner processors (ProcessorParseContainerLogNative →
ProcessorMergeMultilineLogNative flag mode).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List

from ..container_manager import ContainerFilters, ContainerManager
from ..pipeline.plugin.interface import Input, PluginContext
from .file.file_server import FileServer
from .file.polling import FileDiscoveryConfig


class InputContainerStdio(Input):
    name = "input_container_stdio"

    def __init__(self) -> None:
        super().__init__()
        self.filters = ContainerFilters()
        self.fmt = "containerd_text"
        self.multiline: Dict[str, Any] = {}
        self.config_name = ""
        self._refresh_thread = None
        self._running = False
        self._tag_map: Dict[str, Dict[bytes, bytes]] = {}
        self._resolved: Dict[str, Any] = {}
        self._tag_lock = threading.Lock()

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.filters = ContainerFilters(config.get("ContainerFilters", config))
        self.fmt = config.get("Format", "containerd_text")
        self.multiline = config.get("Multiline", {}) or {}
        self.config_name = f"{context.pipeline_name}#stdio{id(self)}"
        return True

    def inner_processor_configs(self) -> List[Dict[str, Any]]:
        out = [
            {"Type": "processor_split_log_string_native"},
            {"Type": "processor_parse_container_log_native",
             "Format": self.fmt,
             "IgnoringStdout": bool(self.config.get("IgnoringStdout", False)),
             "IgnoringStderr": bool(self.config.get("IgnoringStderr", False))},
            {"Type": "processor_merge_multiline_log_native",
             "MergeType": "flag"},
        ]
        if self.multiline.get("StartPattern"):
            out.append({"Type": "processor_split_multiline_log_string_native",
                        "Multiline": self.multiline})
        return out

    def _matched_paths(self) -> List[str]:
        mgr = ContainerManager.instance()
        paths = []
        tag_map = {}
        for info in mgr.discover():
            if not info.log_path:
                continue  # no tailable path (e.g. non-K8s CRI container)
            if self.filters.match(info):
                paths.append(info.log_path)
                tags = {b"_container_name_": info.name.encode(),
                        b"_container_id_": info.id.encode()[:12]}
                if info.image:
                    tags[b"_image_name_"] = info.image.encode()
                if info.k8s_pod:
                    tags[b"_namespace_"] = info.k8s_namespace.encode()
                    tags[b"_pod_name_"] = info.k8s_pod.encode()
                for lk, lv in info.labels.items():
                    if lk.startswith("pod.label."):
                        tags[lk.encode()] = lv.encode()
                tag_map[info.log_path] = tags
        with self._tag_lock:
            self._tag_map = tag_map
            self._resolved.clear()   # concrete-path cache keys old patterns
        return paths

    def _tags_for(self, path: str):
        """Reader paths are concrete files; discovery paths may be globs —
        match either exactly or by pattern (reference external k8s tags:
        _namespace_/_pod_name_/_container_name_/_image_name_). Resolution
        is cached per concrete path: this runs on the FileServer drain hot
        path, once per chunk."""
        import fnmatch
        with self._tag_lock:
            if path in self._resolved:
                return self._resolved[path]
            tag_map = self._tag_map
        hit = tag_map.get(path)
        if hit is None:
            for pattern, tags in tag_map.items():
                if fnmatch.fnmatch(path, pattern):
                    hit = tags
                    break
        with self._tag_lock:
            if len(self._resolved) > 8192:
                self._resolved.clear()
            self._resolved[path] = hit
        return hit

    def start(self) -> bool:
        paths = self._matched_paths()
        fs = FileServer.instance()
        fs.add_config(self.config_name,
                      FileDiscoveryConfig(file_paths=paths or ["/nonexistent"]),
                      self.context.process_queue_key, tail_existing=True,
                      tag_provider=self._tags_for)
        fs.start()
        # periodic re-discovery updates the glob set (container churn)
        self._running = True
        self._refresh_thread = threading.Thread(
            target=self._refresh, name="stdio-discovery", daemon=True)
        self._refresh_thread.start()
        return True

    def _refresh(self) -> None:
        while self._running:
            time.sleep(5.0)
            try:
                FileServer.instance().update_config_paths(
                    self.config_name, self._matched_paths())
            except Exception:  # noqa: BLE001
                pass

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self._running = False
        FileServer.instance().remove_config(self.config_name)
        return True
