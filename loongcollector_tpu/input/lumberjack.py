"""input_lumberjack — Beats/Logstash lumberjack protocol server (v1+v2).

Reference: plugins/input/lumberjack/input_lumberjack.go — TCP listener
speaking the lumberjack framing Filebeat/winlogbeat ship with:

  frame   = version byte ('1'|'2') + type byte
  'W'     window size  (u32 BE): acks are expected per window
  'J'     json data    (u32 seq, u32 len, JSON doc)           [v2]
  'D'     data         (u32 seq, u32 pair_count, {klen,key,vlen,val}*) [v1]
  'C'     compressed   (u32 len, zlib block of concatenated frames)
  'A'     ack          (server → client: u32 seq)

The server acks the highest sequence once a window completes (and on
connection-level flush), which is what beats' publisher pipeline expects
for at-least-once delivery.  Each data frame becomes one LogEvent; nested
JSON values are flattened to their JSON text.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
import zlib
from typing import Any, Dict, Optional

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger

log = get_logger("lumberjack")


class _ConnState:
    __slots__ = ("window", "received", "max_seq", "version")

    def __init__(self):
        self.window = 0
        self.received = 0
        self.max_seq = 0
        self.version = b"2"     # acks echo the client's protocol version


class InputLumberjack(Input):
    name = "input_lumberjack"

    def __init__(self) -> None:
        super().__init__()
        self._server: Optional[socket.socket] = None
        self._threads = []
        self._running = False
        self.address = "0.0.0.0:5044"
        self._port = 0

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.address = config.get("BindAddress",
                                  config.get("Address", self.address))
        host, sep, port = self.address.rpartition(":")
        if not sep or not port.isdigit():
            return False
        self._host, self._port = host, int(port)
        return True

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> bool:
        try:
            self._server = socket.socket()
            self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._server.bind((self._host, self._port))
            self._server.listen(16)
            self._port = self._server.getsockname()[1]
        except OSError as e:
            log.error("lumberjack bind %s failed: %s", self.address, e)
            return False
        self._running = True
        t = threading.Thread(target=self._accept_loop,
                             name="lumberjack-accept", daemon=True)
        t.start()
        self._threads.append(t)
        log.info("lumberjack listening on %s:%d", self._host, self._port)
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        self._running = False
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
        return True

    # -- wire ---------------------------------------------------------------

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, addr = self._server.accept()
            except OSError:
                return
            # connection threads are daemons and NOT tracked: a reconnecting
            # beats fleet would accrete dead Thread objects without bound
            threading.Thread(target=self._serve_conn, args=(conn, addr),
                             name="lumberjack-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket, addr) -> None:
        from ..utils.netio import read_exact
        st = _ConnState()
        src = addr[0].encode()
        try:
            while self._running:
                hdr = read_exact(conn, 2)
                self._handle_frame(conn, hdr, st, src,
                                   lambda n: read_exact(conn, n))
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle_frame(self, conn, hdr: bytes, st: _ConnState, src: bytes,
                      read) -> None:
        version, ftype = hdr[0:1], hdr[1:2]
        if version in (b"1", b"2"):
            st.version = version
        if ftype == b"W":
            st.window = struct.unpack(">I", read(4))[0]
            st.received = 0
        elif ftype == b"J":
            seq = struct.unpack(">I", read(4))[0]
            ln = struct.unpack(">I", read(4))[0]
            doc = read(ln)
            self._emit_json(doc, src)
            self._track_ack(conn, st, seq)
        elif ftype == b"D":
            seq = struct.unpack(">I", read(4))[0]
            pairs = struct.unpack(">I", read(4))[0]
            fields = {}
            for _ in range(pairs):
                klen = struct.unpack(">I", read(4))[0]
                k = read(klen)
                vlen = struct.unpack(">I", read(4))[0]
                fields[k] = read(vlen)
            self._emit_fields(fields, src)
            self._track_ack(conn, st, seq)
        elif ftype == b"C":
            ln = struct.unpack(">I", read(4))[0]
            block = zlib.decompress(read(ln))
            pos = 0

            def block_read(n, _b=block):
                nonlocal pos
                if pos + n > len(_b):
                    raise ConnectionError("truncated compressed frame")
                out = _b[pos:pos + n]
                pos += n
                return out

            while pos < len(block):
                inner_hdr = block_read(2)
                self._handle_frame(conn, inner_hdr, st, src, block_read)
        else:
            raise ConnectionError(f"unknown lumberjack frame {ftype!r}")

    def _track_ack(self, conn, st: _ConnState, seq: int) -> None:
        st.received += 1
        st.max_seq = max(st.max_seq, seq)
        if st.window and st.received >= st.window:
            conn.sendall(st.version + b"A" + struct.pack(">I", st.max_seq))
            st.received = 0

    # -- events -------------------------------------------------------------

    def _emit_json(self, doc: bytes, src: bytes) -> None:
        group = PipelineEventGroup()
        sb = group.source_buffer
        ev = group.add_log_event(int(time.time()))
        try:
            parsed = json.loads(doc)
        except ValueError:
            parsed = None
        if isinstance(parsed, dict):
            for k, v in parsed.items():
                if not isinstance(v, str):
                    v = json.dumps(v, separators=(",", ":"))
                ev.set_content(sb.copy_string(str(k).encode()),
                               sb.copy_string(v.encode()))
        else:
            ev.set_content(sb.copy_string(b"content"), sb.copy_string(doc))
        self._push(group, src)

    def _emit_fields(self, fields: Dict[bytes, bytes], src: bytes) -> None:
        group = PipelineEventGroup()
        sb = group.source_buffer
        ev = group.add_log_event(int(time.time()))
        for k, v in fields.items():
            ev.set_content(sb.copy_string(k), sb.copy_string(v))
        self._push(group, src)

    def _push(self, group: PipelineEventGroup, src: bytes) -> None:
        group.set_tag(b"__source__", src)
        pqm = self.context.process_queue_manager if self.context else None
        if pqm is None:
            return
        # bounded retry, then FAIL the connection: an un-pushed frame must
        # never be acked (at-least-once) — dropping the conn makes the
        # beat reconnect and retransmit the unacknowledged window
        for _ in range(200):
            if pqm.push_queue(self.context.process_queue_key, group):
                return
            time.sleep(0.01)
        raise ConnectionError("process queue full; forcing retransmit")
