"""Input plugins (reference: core/plugin/input/ + core/file_server/)."""


def register_all(registry) -> None:
    from .file.input_file import InputFile, InputStaticFile

    registry.register_input("input_file", InputFile)
    registry.register_input("input_static_file_onetime", InputStaticFile)
