"""Input plugins (reference: core/plugin/input/ + core/file_server/)."""


def register_all(registry) -> None:
    from .file.input_file import InputFile, InputStaticFile
    from .host_monitor import (InputHostMeta, InputHostMonitor,
                               InputProcessEntity)
    from .internal import (InputInternalAlarms,
                           InputInternalMatchedContainerInfo,
                           InputInternalMetrics)
    from .prometheus.scraper import InputPrometheus
    from .ebpf.server import (InputCpuProfiling, InputFileSecurity,
                              InputNetworkObserver, InputNetworkSecurity,
                              InputProcessSecurity)
    from .forward import InputForward
    from .container_stdio import InputContainerStdio
    from .http_server import InputHTTPServer, InputOTLP
    from .journal import InputJournal
    from .kafka import InputKafka
    from .mqtt import InputMQTT
    from .mysql_binlog import InputCanal
    from .goprofile import InputGoProfile
    from .lumberjack import InputLumberjack
    from .redis import InputRedis
    from .skywalking import InputSkywalking
    from .snmp import InputSNMP
    from .syslog import InputSyslog

    registry.register_input("input_file", InputFile)
    registry.register_input("input_static_file_onetime", InputStaticFile)
    registry.register_input("input_host_monitor", InputHostMonitor)
    registry.register_input("input_host_meta", InputHostMeta)
    registry.register_input("input_process_entity", InputProcessEntity)
    registry.register_input("input_internal_metrics", InputInternalMetrics)
    registry.register_input("input_internal_alarms", InputInternalAlarms)
    registry.register_input("input_internal_matched_container_info",
                            InputInternalMatchedContainerInfo)
    registry.register_input("input_prometheus", InputPrometheus)
    registry.register_input("input_network_observer", InputNetworkObserver)
    registry.register_input("input_process_security", InputProcessSecurity)
    registry.register_input("input_file_security", InputFileSecurity)
    registry.register_input("input_network_security", InputNetworkSecurity)
    registry.register_input("input_cpu_profiling", InputCpuProfiling)
    registry.register_input("input_forward", InputForward)
    registry.register_input("input_container_stdio", InputContainerStdio)
    registry.register_input("input_syslog", InputSyslog)
    registry.register_input("input_http_server", InputHTTPServer)
    registry.register_input("input_otlp", InputOTLP)
    registry.register_input("input_journal", InputJournal)
    registry.register_input("input_mqtt", InputMQTT)
    registry.register_input("input_redis", InputRedis)
    registry.register_input("input_snmp", InputSNMP)
    registry.register_input("service_kafka", InputKafka)
    registry.register_input("input_kafka", InputKafka)
    registry.register_input("service_canal", InputCanal)
    registry.register_input("input_lumberjack", InputLumberjack)
    registry.register_input("service_lumberjack", InputLumberjack)
    registry.register_input("input_skywalking", InputSkywalking)
    registry.register_input("input_goprofile", InputGoProfile)
    registry.register_input("service_goprofile", InputGoProfile)
    from .jmxfetch import ServiceJmxFetch
    from .telegraf import ServiceTelegraf
    from .udpserver import InputUDPServer
    from .command import InputCommand
    from .docker_event import InputDebugFile, ServiceDockerEvent
    from .k8s_meta import ServiceK8sMeta
    from .mysql_query import InputMysql
    from .pgsql_query import InputPgsql
    from .probes import InputHTTPResponse, InputNetPing, InputNginxStatus
    registry.register_input("input_command", InputCommand)
    registry.register_input("metric_http", InputHTTPResponse)
    registry.register_input("metric_nginx_status", InputNginxStatus)
    registry.register_input("metric_input_netping", InputNetPing)
    registry.register_input("service_mysql", InputMysql)
    registry.register_input("service_pgsql", InputPgsql)
    registry.register_input("service_docker_event", ServiceDockerEvent)
    registry.register_input("metric_debug_file", InputDebugFile)
    registry.register_input("service_kubernetes_meta", ServiceK8sMeta)
    registry.register_input("service_udp_server", InputUDPServer)
    registry.register_input("input_udp_server", InputUDPServer)
    registry.register_input("service_telegraf", ServiceTelegraf)
    registry.register_input("service_jmxfetch", ServiceJmxFetch)
