"""service_jmxfetch — supervised JMXFetch (JVM MBean) collection.

Reference: plugins/input/jmxfetch/ — jmxfetch.go (plugin config: static
instances + bean filters), manager.go (singleton: renders conf.d YAML,
finds a JDK, supervises the jmxfetch java agent, and ingests its metrics
through a SHARED statsd UDP server dispatched by the `jmxfetch_ilogtail`
tag, manager.go:173), jmxfetch_inner.go (instance YAML shape).

The java/jar prerequisites are environment-gated: without them the
manager still renders YAML configs and runs the statsd listener (any
externally-launched jmxfetch pointed at the port works); supervision
kicks in when `java` and `jmxfetch.jar` exist.
"""

from __future__ import annotations

import os
import shutil
import socket
import subprocess
import threading
from typing import Any, Dict, List, Optional

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger
from .supervisor import ProcessSupervisor, sanitize_name
from .udpserver import SharedUDPServer

log = get_logger("jmxfetch")

DISPATCH_KEY = "jmxfetch_ilogtail"
_CHECK_INTERVAL_S = 5.0


def _yaml_scalar(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    s = str(v)
    if s == "" or any(c in s for c in ":#{}[],&*?|>'\"%@`"):
        return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'
    return s


def render_config_yaml(instances: List[Dict[str, Any]],
                       filters: List[Dict[str, Any]],
                       new_gc_metrics: bool) -> str:
    """Datadog-style jmxfetch YAML (reference Manager.updateFiles).
    Hand-rolled writer — the config shape is small and fixed, and the
    repo carries no YAML-emitter dependency."""
    out = ["init_config:",
           "  is_jmx: true",
           f"  new_gc_metrics: {_yaml_scalar(new_gc_metrics)}"]
    if filters:
        out.append("  conf:")
        for f in filters:
            out.append("    - include:")
            for k in ("domain", "bean_regex", "type", "name"):
                if f.get(k):
                    out.append(f"        {k}: {_yaml_scalar(f[k])}")
            attr = f.get("attribute")
            if isinstance(attr, list):
                out.append("        attribute:")
                for a in attr:
                    out.append(f"          - {_yaml_scalar(a)}")
            elif isinstance(attr, dict):
                out.append("        attribute:")
                for name, spec in attr.items():
                    out.append(f"          {name}:")
                    for sk, sv in spec.items():
                        out.append(f"            {sk}: {_yaml_scalar(sv)}")
    out.append("instances:")
    for inst in instances:
        out.append(f"  - name: {_yaml_scalar(inst['name'])}")
        for k in ("host", "port", "user", "password"):
            if inst.get(k) not in (None, ""):
                out.append(f"    {k}: {_yaml_scalar(inst[k])}")
        out.append("    collect_default_jvm_metrics: "
                   + _yaml_scalar(inst.get("default_jvm_metrics", True)))
        tags = inst.get("tags") or []
        if tags:
            out.append("    tags:")
            for t in sorted(tags):
                out.append(f"      - {_yaml_scalar(t)}")
    return "\n".join(out) + "\n"


class JmxFetchManager(ProcessSupervisor):
    """Singleton per install dir (reference GetJmxFetchManager)."""

    check_interval_s = _CHECK_INTERVAL_S

    def __init__(self, base_dir: str) -> None:
        super().__init__(base_dir)
        self.conf_dir = os.path.join(base_dir, "conf.d")
        self.jar_path = os.path.join(base_dir, "jmxfetch.jar")
        self._java_home = ""
        self._cfgs: Dict[str, dict] = {}
        self._server: Optional[SharedUDPServer] = None

    # -- plugin-facing API ---------------------------------------------------

    def config_java_home(self, jdk_path: str) -> None:
        with self._lock:
            if jdk_path:
                self._java_home = jdk_path

    def register(self, key: str, instances: List[Dict[str, Any]],
                 filters: List[Dict[str, Any]], new_gc_metrics: bool,
                 sink) -> None:
        with self._lock:
            self._cfgs[key] = {"instances": instances, "filters": filters,
                               "new_gc": new_gc_metrics, "sink": sink}
            started = self._running
        if not started:
            self.start_loop()
        else:
            with self._lock:
                if self._server is not None:
                    self._server.register(key, sink)
        self.wake()

    def unregister(self, key: str) -> None:
        with self._lock:
            self._cfgs.pop(key, None)
            empty = not self._cfgs
            if self._server is not None:
                self._server.unregister(key)
        try:
            os.unlink(os.path.join(self.conf_dir, key + ".yaml"))
        except OSError:
            pass
        self.wake()
        if empty:
            self.stop_loop()

    @property
    def statsd_port(self) -> int:
        with self._lock:
            return self._server.port if self._server is not None else 0

    # -- lifecycle -----------------------------------------------------------

    def _on_stop(self) -> None:
        with self._lock:
            if self._server is not None:
                self._server.stop()
                self._server = None

    def _tick(self) -> None:
        with self._lock:
            cfgs = dict(self._cfgs)
        self._ensure_server(cfgs)
        try:
            self._render(cfgs)
        except OSError as e:
            log.warning("jmxfetch conf render failed: %s", e)
        if cfgs:
            self._ensure_proc()
        else:
            self.kill_proc()

    def _ensure_server(self, cfgs: Dict[str, dict]) -> None:
        with self._lock:
            if self._server is None:
                self._server = SharedUDPServer("127.0.0.1:0", "statsd",
                                               DISPATCH_KEY)
                if not self._server.start():
                    self._server = None
                    return
            server = self._server
        for key, cfg in cfgs.items():
            server.register(key, cfg["sink"])

    def _render(self, cfgs: Dict[str, dict]) -> None:
        os.makedirs(self.conf_dir, exist_ok=True)
        for key, cfg in cfgs.items():
            insts = []
            for inst in cfg["instances"]:
                inst = dict(inst)
                tags = set(inst.get("tags") or [])
                tags.add(f"{DISPATCH_KEY}:{key}")
                inst["tags"] = sorted(tags)
                insts.append(inst)
            text = render_config_yaml(insts, cfg["filters"], cfg["new_gc"])
            path = os.path.join(self.conf_dir, key + ".yaml")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(text)
            os.replace(tmp, path)

    def _java_cmd(self) -> Optional[str]:
        with self._lock:
            home = self._java_home
        if home:
            cand = os.path.join(home, "bin", "java")
            return cand if os.path.exists(cand) else None
        cand = os.path.join(self.base_dir, "jdk", "bin", "java")
        if os.path.exists(cand):
            return cand
        return shutil.which("java")

    def _ensure_proc(self) -> None:
        if self.proc_alive():
            return
        java = self._java_cmd()
        if java is None or not os.path.exists(self.jar_path):
            return                      # degraded: configs + listener only
        port = self.statsd_port
        if not port:
            return
        try:
            self._proc = subprocess.Popen(
                [java, "-jar", self.jar_path,
                 "--reporter", f"statsd:127.0.0.1:{port}",
                 "--conf_directory", self.conf_dir, "collect"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                cwd=self.base_dir)
            log.info("jmxfetch started pid=%s statsd_port=%d",
                     self._proc.pid, port)
        except OSError as e:
            log.warning("jmxfetch start failed: %s", e)
            self._proc = None


def _instance_inner(port: int, host: str, user: str, password: str,
                    tags: Dict[str, str], default_jvm: bool) -> Dict[str, Any]:
    """reference NewInstanceInner: derived name + hostname/service tags."""
    hostname = os.environ.get("_node_name_") or socket.gethostname()
    tags = dict(tags or {})
    tags.setdefault("hostname", hostname)
    tags.setdefault("service", hostname)
    if host in ("localhost", "127.0.0.1"):
        name = f"{hostname}_{port}"
    else:
        name = f"{host}_{port}"
    name = sanitize_name(name)
    return {"name": name, "host": host, "port": port, "user": user,
            "password": password, "default_jvm_metrics": default_jvm,
            "tags": sorted(f"{k}:{v}" for k, v in tags.items())}


class ServiceJmxFetch(Input):
    """service_jmxfetch (plugins/input/jmxfetch/jmxfetch.go); config keys
    mirror the Go plugin: StaticInstances, Filters, NewGcMetrics,
    DefaultJvmMetrics, Tags, JDKPath.  DiscoveryMode (container-based
    instance discovery) is not wired — static instances only."""

    name = "service_jmxfetch"

    def __init__(self) -> None:
        super().__init__()
        self._manager: Optional[JmxFetchManager] = None
        self._key = ""

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.jdk_path = str(config.get("JDKPath", ""))
        self.new_gc = bool(config.get("NewGcMetrics", False))
        default_jvm = bool(config.get("DefaultJvmMetrics", True))
        common_tags = {str(k): str(v)
                       for k, v in (config.get("Tags") or {}).items()}
        cluster = str(config.get("Cluster", ""))
        if cluster:
            common_tags["cluster"] = cluster
        self.instances = []
        for inst in config.get("StaticInstances") or []:
            tags = dict(common_tags)
            tags.update({str(k): str(v)
                         for k, v in (inst.get("Tags") or {}).items()})
            self.instances.append(_instance_inner(
                int(inst.get("Port", 0)), str(inst.get("Host", "localhost")),
                str(inst.get("User", "")), str(inst.get("Password", "")),
                tags, default_jvm))
        self.filters = []
        for f in config.get("Filters") or []:
            inner: Dict[str, Any] = {
                "domain": f.get("Domain", ""),
                "bean_regex": f.get("BeanRegex", ""),
                "type": f.get("Type", ""),
                "name": f.get("Name", ""),
            }
            attrs = f.get("Attribute") or []
            if attrs:
                # list mode unless every entry has MetricType + Alias
                if all(a.get("MetricType") and a.get("Alias")
                       for a in attrs):
                    inner["attribute"] = {
                        a["Name"]: {"metric_type": a["MetricType"],
                                    "alias": a["Alias"]} for a in attrs}
                else:
                    inner["attribute"] = [a.get("Name", "") for a in attrs]
            self.filters.append(inner)
        base = config.get("JmxFetchHome") or os.path.join(
            os.environ.get("LOONG_THIRD_PARTY_DIR",
                           os.path.join(os.path.expanduser("~"),
                                        ".loongcollector", "thirdparty")),
            "jmxfetch")
        self._base_dir = str(base)
        if config.get("DiscoveryMode"):
            log.warning("service_jmxfetch DiscoveryMode is not supported; "
                        "configure StaticInstances")
        return bool(self.instances)

    def start(self) -> bool:
        self._manager = JmxFetchManager.get(self._base_dir)
        self._manager.config_java_home(self.jdk_path)
        self._key = sanitize_name(self.context.pipeline_name, "jmx")
        pqm = self.context.process_queue_manager
        key = self.context.process_queue_key

        def sink(group: PipelineEventGroup) -> None:
            group.set_tag(b"__source__", b"jmxfetch")
            if pqm is not None:
                pqm.push_queue(key, group)

        self._manager.register(self._key, self.instances, self.filters,
                               self.new_gc, sink)
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        if self._manager is not None:
            self._manager.unregister(self._key)
            self._manager = None
        return True
