"""input_forward — gRPC ingest.

Reference: core/forward/GrpcInputManager.h:37,92-108 — per-listen-address
grpc::Server ownership with refcounting; LoongSuiteForwardService receives
agent payloads and feeds pipelines.

Service: generic byte-payload forward (method /loongsuite.Forward/Forward)
accepting either JSON event-group fixtures or raw line payloads; gated on
grpcio availability (baked into this image).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Optional

from ..models import PipelineEventGroup
from ..pipeline.plugin.interface import Input, PluginContext
from ..utils.logger import get_logger

log = get_logger("forward")

try:
    import grpc
except ImportError:  # pragma: no cover
    grpc = None


class _ForwardHandler:
    """Generic method handler: bytes in → push to the bound queue."""

    def __init__(self, manager: "GrpcInputManager"):
        self.manager = manager

    def handle(self, data: bytes, pipeline_key: Optional[int]) -> bool:
        group = self._decode(data)
        if group is None or pipeline_key is None:
            return False
        pqm = self.manager.process_queue_manager
        return pqm is not None and pqm.push_queue(pipeline_key, group)

    @staticmethod
    def _decode(data: bytes) -> Optional[PipelineEventGroup]:
        # JSON fixture groups, SLS LogGroup wire bytes, or raw lines
        if data[:1] == b"{":
            try:
                return PipelineEventGroup.from_json(data.decode("utf-8"))
            except (ValueError, KeyError):
                return None
        if data[:1] == b"\x0a":  # LogGroup.Logs field header
            from ..pipeline.serializer.sls_serializer import parse_loggroup
            try:
                group = parse_loggroup(data)
                if not group.empty():
                    return group
            except (IndexError, ValueError, KeyError):
                pass  # not valid / truncated PB: fall through to raw
        group = PipelineEventGroup()
        sb = group.source_buffer
        ev = group.add_raw_event(int(time.time()))
        ev.set_content(sb.copy_string(data))
        return group


class GrpcInputManager:
    _instance: Optional["GrpcInputManager"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._servers: Dict[str, tuple] = {}  # addr -> (server, refcount)
        self._routes: Dict[str, int] = {}     # addr -> queue key
        self._ports: Dict[str, int] = {}      # addr -> bound port (port 0)
        self._lock = threading.Lock()
        self.process_queue_manager = None

    def bound_port(self, address: str) -> int:
        """Actual bound port for an address (resolves ':0' test binds)."""
        with self._lock:
            return self._ports.get(address, 0)

    @classmethod
    def instance(cls) -> "GrpcInputManager":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def add_listen_input(self, address: str, queue_key: int) -> bool:
        """One queue key per address: a reloaded pipeline reuses its key and
        just bumps the refcount; a DIFFERENT pipeline claiming a bound
        address is a config error (the reference shares servers per address
        but routes per service — this framework routes per address)."""
        if grpc is None:
            log.error("grpcio unavailable; input_forward disabled")
            return False
        with self._lock:
            if address in self._servers:
                if self._routes.get(address) != queue_key:
                    log.error("grpc address %s already bound to another "
                              "pipeline", address)
                    return False
                server, ref = self._servers[address]
                self._servers[address] = (server, ref + 1)
                return True
            handler = _ForwardHandler(self)

            def unary(request: bytes, context) -> bytes:
                ok = handler.handle(request, self._routes.get(address))
                return b'{"accepted": true}' if ok else b'{"accepted": false}'

            method = grpc.unary_unary_rpc_method_handler(
                unary,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b)
            service = grpc.method_handlers_generic_handler(
                "loongsuite.Forward", {"Forward": method})
            server = grpc.server(
                thread_pool=__import__("concurrent.futures", fromlist=["f"])
                .ThreadPoolExecutor(max_workers=4))
            server.add_generic_rpc_handlers((service,))
            bound = server.add_insecure_port(address)
            if bound == 0:
                log.error("failed to bind grpc address %s", address)
                return False
            self._routes[address] = queue_key
            server.start()
            self._servers[address] = (server, 1)
            self._ports[address] = bound
        log.info("grpc forward listening on %s", address)
        return True

    def remove_listen_input(self, address: str) -> None:
        with self._lock:
            entry = self._servers.get(address)
            if entry is None:
                return
            server, ref = entry
            if ref > 1:
                self._servers[address] = (server, ref - 1)
                return
            del self._servers[address]
            self._routes.pop(address, None)
            self._ports.pop(address, None)
        server.stop(grace=1)

    def stop_all(self) -> None:
        with self._lock:
            servers = [s for s, _ in self._servers.values()]
            self._servers.clear()
            self._routes.clear()
        for s in servers:
            s.stop(grace=1)


class InputForward(Input):
    name = "input_forward"

    def __init__(self) -> None:
        super().__init__()
        self.address = ""

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.address = config.get("Address", "127.0.0.1:7899")
        return bool(self.address)

    def start(self) -> bool:
        mgr = GrpcInputManager.instance()
        return mgr.add_listen_input(self.address,
                                    self.context.process_queue_key)

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        GrpcInputManager.instance().remove_listen_input(self.address)
        return True
